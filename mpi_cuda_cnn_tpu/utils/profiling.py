"""Profiling hooks.

The reference has none (SURVEY.md §5.1: no timers, no NVTX, no cudaEvent).
Here: a wall-clock step timer that understands JAX async dispatch, and a
context manager around jax.profiler for device traces viewable in
TensorBoard/XProf.
"""

from __future__ import annotations

import contextlib
import time

import jax


class StepTimer:
    """Accumulates per-step wall-clock. call block_until_ready on the step
    output before stop() — JAX dispatch is async and returns before the TPU
    finishes."""

    def __init__(self):
        self.steps = 0
        self.total_s = 0.0
        self._t0 = None

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def stop(self, n_steps: int = 1) -> float:
        dt = time.perf_counter() - self._t0
        self.steps += n_steps
        self.total_s += dt
        return dt

    @property
    def mean_step_ms(self) -> float:
        return 1000.0 * self.total_s / max(self.steps, 1)


@contextlib.contextmanager
def profile_trace(logdir: str | None):
    """Capture a device trace with jax.profiler when logdir is set."""
    if not logdir:
        yield
        return
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
