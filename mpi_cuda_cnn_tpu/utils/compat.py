"""JAX version compatibility.

The framework targets the current stable API (`jax.shard_map` with
`check_vma`, `lax.axis_size`); on older installs (<= 0.4.x) shard_map
still lives at `jax.experimental.shard_map.shard_map` with a
`check_rep` kwarg, and `lax.axis_size` does not exist (`lax.psum(1,
axis)` is its classic static-int equivalent). Importing this module
(the package __init__ does) installs translating aliases for whichever
are missing, so every call site keeps the one modern spelling. On a
modern JAX this module is a no-op.
"""

from __future__ import annotations

import jax


def _install_shard_map_alias() -> None:
    if hasattr(jax, "shard_map"):
        return
    try:
        from jax.experimental.shard_map import shard_map as _shard_map
    except ImportError:  # nothing to alias; calls will fail loudly
        return

    def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                  check_vma: bool | None = None, **kwargs):
        if check_vma is not None and "check_rep" not in kwargs:
            kwargs["check_rep"] = check_vma
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, **kwargs)

    jax.shard_map = shard_map


def _install_axis_size_alias() -> None:
    from jax import lax

    if hasattr(lax, "axis_size"):
        return

    def axis_size(axis_name) -> int:
        # psum of a Python literal over a named axis folds statically.
        return lax.psum(1, axis_name)

    lax.axis_size = axis_size


_install_shard_map_alias()
_install_axis_size_alias()
