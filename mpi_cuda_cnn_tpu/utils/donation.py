"""The ONE buffer-donation policy for jitted train/update steps.

Every train step in the framework carries its full state — params,
optimizer state, step counter, and (under grad accumulation) the
accumulator carry — as argument 0 and returns the updated state as its
first output. Donating that argument lets XLA alias the input buffers to
the output buffers: the AdamW update rewrites p/m/v IN PLACE instead of
allocating a fresh ~3x-params set per step, which halves live state at
the update and is the precondition for larger accumulation batches
(PERF.md "hot-step memory traffic"). The flagship LM step moves ~19 GB
of optimizer state per update; without donation every byte of it needs a
second resident copy at the update's peak.

Before this module each step builder wrote its own
`donate_argnums=(0,) if donate else ()` — ten sites that could (and,
with fresh builders, would) drift. `donate_jit` is the single spelling,
and `obs.cost.donation_report` / `assert_donation` are the compile-time
proof that the aliasing actually happened (the HLO's
`input_output_alias` table + XLA memory analysis — donation silently
degrades to a copy when an output shape/layout mismatches, so "we passed
the flag" is not evidence).
"""

from __future__ import annotations

import jax

__all__ = ["donate_jit"]


def donate_jit(fn, *, donate: bool = True, argnums: tuple[int, ...] = (0,),
               **jit_kwargs):
    """jax.jit with the repo's donation convention applied uniformly.

    argnums names the donated positional arguments — (0,), the state
    pytree, everywhere today. donate=False (parity tests, callers that
    reuse a state across calls) compiles the same program without
    aliasing. Extra jit kwargs pass through.
    """
    return jax.jit(
        fn, donate_argnums=argnums if donate else (), **jit_kwargs
    )
