"""Config / flag system.

The reference has no flag system: 4 positional IDX paths (cnn.c:408-412) and
every hyperparameter compiled in (rate=0.1 cnn.c:446, nepoch=10 cnn.c:448,
batch_size=32 cnn.c:449, seed 0 cnn.c:413, model shape cnn.c:416-428). This
module keeps the 4-positional-path CLI form working while exposing all of
those as flags, plus the TPU-era surface (device, dtype, parallelism,
checkpointing) the north star requires (SURVEY.md §5.6).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys


class _JsonConfig:
    """JSON round-trip shared by both config families (the C ABI's wire
    format, native/tpu_abi.h)."""

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2)

    @classmethod
    def from_json(cls, text: str):
        return cls(**json.loads(text))


@dataclasses.dataclass
class Config(_JsonConfig):
    # Data: either a registered dataset name, or the reference's 4 IDX paths.
    dataset: str = "synthetic"
    data_dir: str | None = None
    train_images: str | None = None
    train_labels: str | None = None
    test_images: str | None = None
    test_labels: str | None = None

    # Model / training — defaults are the reference's compiled-in constants.
    model: str = "reference_cnn"  # see models.presets
    epochs: int = 10              # cnn.c:448
    lr: float = 0.1               # cnn.c:446
    batch_size: int = 32          # cnn.c:449 (accumulator period)
    momentum: float = 0.0
    lr_schedule: str = "constant"  # constant | cosine
    grad_clip: float = 0.0        # global-norm clip; 0 (default) disables
                                  # (same knob as the lm subcommand's)
    seed: int = 0                 # cnn.c:413 srand(0)
    init: str = "normal"          # normal | irwin_hall (reference nrnd, cnn.c:46-49)
    augment: str = "none"         # none | shift | shift-flip (data/augment.py;
                                  # the reference has no augmentation)
    aug_pad: int = 2              # max +/- pixels for the random shift

    # Numerics (SURVEY.md §7 hard-part (b)).
    param_dtype: str = "float32"
    compute_dtype: str = "float32"  # bfloat16 engages the MXU's native path

    # Execution.
    device: str = "auto"          # auto | tpu | cpu
    num_devices: int = 0          # 0 = all visible; N = DP over first N
    mesh_shape: str = "data"      # named mesh axes: "data", "data:4,model:2",
                                  # "pipe:4", "pipe:2,data:2", ...
    num_microbatches: int = 0     # pipeline microbatches per step; 0 = auto
                                  # (= pipe-axis size when PP is active)
    fsdp: bool = False            # ZeRO-style: shard params + optimizer
                                  # state over the 'data' axis (parallel/
                                  # fsdp.py); GSPMD inserts the gathers
    use_pallas: bool = False      # Pallas kernels instead of lax ops
    donate: bool = True
    remat: bool = False           # jax.checkpoint per layer: recompute
                                  # activations in backward (HBM for FLOPs)
    grad_accum: int = 1           # micro-batches accumulated per optimizer
                                  # step (batch_size splits evenly across
                                  # them; generalizes cnn.c:467's 32-sample
                                  # accumulator)
    scan: bool = True             # many-steps-per-dispatch epochs (lax.scan
                                  # over an HBM-resident dataset); off =
                                  # one dispatch per batch
    scan_max_bytes: int = 2 << 30  # datasets above this fall back to the
                                  # streaming per-batch path (the scanned
                                  # epoch stages the whole uint8 set in
                                  # HBM — perfect for MNIST/CIFAR, wrong
                                  # for larger-than-HBM corpora); raise
                                  # it to force staging anyway

    # Aux subsystems.
    checkpoint_dir: str | None = None
    checkpoint_every: int = 0     # epochs; 0 = only at end when dir is set
    checkpoint_every_steps: int = 0  # optimizer steps; >0 = mid-epoch
                                  # saves, and --resume continues at the
                                  # exact step (bitwise — the shuffle
                                  # order is derived from (seed, epoch))
    async_checkpoint: bool = True  # write checkpoints on a background
                                  # worker (train/checkpoint.py
                                  # AsyncCheckpointer): the step loop
                                  # only pays the host snapshot, not the
                                  # npz write; --no-async-checkpoint
                                  # restores fully synchronous saves
    resume: bool = False
    # Robustness (ISSUE 4). max_restarts > 0 turns the CLI into a
    # crash-safe supervisor: a crashed attempt relaunches from the
    # latest valid checkpoint (needs --checkpoint-dir; pair with
    # --checkpoint-every-steps for tight recovery points).
    max_restarts: int = 0
    nan_policy: str = "off"       # off | abort | skip | restore — the
                                  # NaN/Inf guard on loss/metrics and the
                                  # post-update state. skip drops the bad
                                  # update; restore also rolls back to
                                  # the last checkpoint after
                                  # --nan-max-bad consecutive bad steps.
                                  # Any non-off policy steps per batch
                                  # (no scanned epochs) and costs a
                                  # per-step sync — robustness mode.
    nan_max_bad: int = 3          # consecutive non-finite steps before
                                  # nan_policy=restore rolls back
    fault_plan: str | None = None  # deterministic fault injection spec
                                  # (faults.parse_plan), e.g.
                                  # "crash@train.step:6;nan@train.batch:3"
    elastic_width: int = 0        # >0: width-invariant gradient
                                  # reduction over this many canonical
                                  # microbatches (parallel/elastic.py) —
                                  # a preempted run resumes BITWISE on
                                  # any power-of-two data width n with
                                  # elastic_width >= 2n. Power of two,
                                  # must divide batch_size; plain-DP
                                  # meshes only. 0 keeps the pmean step
    log_every: int = 100          # steps; reference prints every 1000 samples
    profile_dir: str | None = None
    metrics_jsonl: str | None = None  # write schema-stamped JSONL metrics
                                  # (obs.schema) here: train/epoch/eval
                                  # records plus telemetry — step-phase
                                  # timings, compiled-step FLOPs and
                                  # collective counts, device-memory
                                  # snapshots; `mctpu report FILE`
                                  # renders the summary tables
    eval_every: int = 1           # epochs


@dataclasses.dataclass
class LMConfig(_JsonConfig):
    """Config for the `lm` subcommand (train/lm_trainer.py) — the
    long-context model family's product surface: transformer size,
    corpus, parallelism mesh (data/seq axes), MoE, attention impl."""

    corpus: str = "self"          # self | synthetic | path to a text file
    dim: int = 256
    depth: int = 4
    heads: int = 8
    kv_heads: int = 0             # 0 = heads (MHA); < heads = GQA (1=MQA):
                                  # kv projections + decode cache shrink
    pos: str = "learned"          # learned | rope
    seq_len: int = 256
    moe_experts: int = 0          # >0: Switch-MoE MLP per block (EP over
                                  # the 'seq' axis when one exists)
    moe_top_k: int = 1            # experts per token (1=Switch, 2=GShard)
    moe_dispatch_chunk: int = 0   # >0: route MoE tokens in chunks of
                                  # this many (ep.moe_mlp) — the single-
                                  # chip lever for the quadratic
                                  # dispatch-einsum term; rejected on
                                  # expert-sharded meshes (EP already
                                  # divides the routed tokens)
    moe_dispatch_dtype: str | None = None  # routing-tensor dtype override
                                  # (ep.moe_mlp dispatch_dtype):
                                  # "bfloat16" halves the (T,E,C)
                                  # dispatch build/read bytes under an
                                  # f32 compute path; default follows
                                  # the compute dtype
    steps: int = 200
    batch_size: int = 8
    lr: float = 3e-4
    lr_schedule: str = "cosine"
    warmup_steps: int = 20
    weight_decay: float = 0.01
    grad_clip: float = 0.0        # global-norm clip; 0 (default) disables
                                  # — off by default so existing configs
                                  # reproduce; 1.0 is the usual LM choice
    grad_accum: int = 1           # chunks accumulated per optimizer step
                                  # (per-chunk value_and_grad inside a
                                  # scan: peak activation memory is ONE
                                  # chunk). Plain/TP/FSDP meshes; the
                                  # shard_map paths reject it ('pipe'
                                  # already microbatches)
    seed: int = 0
    donate: bool = True           # donate the state pytree to every jitted
                                  # step (utils/donation.donate_jit): XLA
                                  # aliases params/opt-state/accumulator
                                  # buffers in place — halves live state
                                  # at the update. Off only for debugging
                                  # (keeping a pre-step state readable)

    compute_dtype: str = "float32"   # bfloat16 = MXU-native matmuls
    attn_impl: str = "auto"          # auto | flash | oracle (seq-sharded
                                     # meshes map these to ring_flash/ring;
                                     # 'ulysses' forces all-to-all SP)
    remat: bool = False
    fsdp: bool = False               # ZeRO-style: shard LM params +
                                     # optimizer state over 'data'
                                     # (parallel/fsdp.py — generic specs;
                                     # composes with 'model' TP and with
                                     # a 'seq' axis — ZeRO x ring,
                                     # parallel/sp.py state_specs)
    ce_chunk: int = 0                # >0: fused chunked cross-entropy
                                     # (never materializes (B,S,V) f32
                                     # logits). Must divide seq_len — the
                                     # PER-SHARD seq_len under a 'seq'
                                     # mesh axis (shard-local chunked CE).
    device: str = "auto"
    num_devices: int = 0
    mesh_shape: str = "data"         # e.g. "data:2,seq:4"

    checkpoint_dir: str | None = None
    checkpoint_every: int = 0
    async_checkpoint: bool = True    # background checkpoint writes (see
                                     # Config.async_checkpoint)
    resume: bool = False
    max_restarts: int = 0            # crash-safe supervisor retries (see
                                     # Config.max_restarts)
    nan_policy: str = "off"          # off|abort|skip|restore NaN/Inf
                                     # guard (see Config.nan_policy)
    nan_max_bad: int = 3             # consecutive bad steps before
                                     # nan_policy=restore rolls back
    fault_plan: str | None = None    # fault injection spec
                                     # (faults.parse_plan)
    elastic_width: int = 0           # >0: width-invariant canonical-
                                     # tree gradient reduction (see
                                     # Config.elastic_width) — cross-
                                     # width bitwise resume; pure-DP
                                     # meshes only
    log_every: int = 20
    metrics_jsonl: str | None = None  # JSONL metrics + telemetry sink
                                     # (see Config.metrics_jsonl)
    sample_tokens: int = 0           # >0: after training, generate this
                                     # many tokens from the held-out
                                     # stream with the KV-cache decode
                                     # path and print the continuation
    sample_temperature: float = 0.0  # 0 = greedy argmax
    sample_top_k: int = 0            # >0: sample among the k most likely
    sample_top_p: float = 0.0        # >0: nucleus sampling (smallest
                                     # set reaching mass p); both compose
                                     # and need --sample-temperature > 0
    sample_speculative_k: int = 0    # >=2: draft-free prompt-lookup
                                     # speculative decoding with k-token
                                     # verify blocks (bitwise greedy at
                                     # temperature 0; rejection sampling
                                     # — exact output law — at
                                     # temperature > 0: generate.py)
    decode_cache_dtype: str = "float32"  # "bfloat16" halves the decode
                                     # KV-cache bytes (decode is cache-
                                     # read-bound: PERF.md decode table);
                                     # "int8" quarters them (absmax per
                                     # position x head, scales applied
                                     # outside the dots — generate.py);
                                     # "auto" routes from the banked
                                     # int8 table (VERDICT 7): int8 for
                                     # GQA/MQA, bf16 for MHA
                                     # (generate.pick_cache_dtype);
                                     # f32 = exactness default
    decode_weights_dtype: str = "float32"  # decode GEMV weights at
                                     # sample time (ISSUE 12): "int8" =
                                     # per-channel absmax QuantW via
                                     # the fused GEMV (ops/pallas_gemv,
                                     # quantized once per sample call);
                                     # "auto" routes int8 for GQA/MQA,
                                     # f32 for MHA
                                     # (generate.pick_weights_dtype)



def _fault_plan_arg(spec: str) -> str:
    """argparse type for --fault-plan: parse NOW so a typo dies at the
    command line with a one-line message instead of as a traceback from
    deep inside the trainer (ISSUE 5 satellite); sites AND kinds are
    checked against the CNN trainer's hook points (ISSUE 7 satellite)
    via the shared faults.fault_plan_arg — `replica_crash@fleet.tick`
    on `mctpu train` would silently never fire; it errors here. The
    original string is returned — the trainer re-parses it."""
    from ..faults import fault_plan_arg

    return fault_plan_arg("train")(spec)


def _lm_fault_plan_arg(spec: str) -> str:
    """The LM parser's --fault-plan type: same contract, "train-lm"
    surface — the LM trainer has no train.batch hook, so nan@train.batch
    (valid on the CNN trainer) must error here, not silently no-op."""
    from ..faults import fault_plan_arg

    return fault_plan_arg("train-lm")(spec)


# Per-field argparse overrides shared by both auto-generated parsers:
# flags whose values have grammar are validated AT PARSE TIME (clear
# one-line errors, exit 2) instead of wherever the value is first used.
_ARG_OVERRIDES: dict[str, dict] = {
    "nan_policy": {"choices": ("off", "abort", "skip", "restore")},
    "fault_plan": {"type": _fault_plan_arg},
}

# The LM parser validates --fault-plan against ITS hook surface.
_LM_ARG_OVERRIDES: dict[str, dict] = {
    **_ARG_OVERRIDES,
    "fault_plan": {"type": _lm_fault_plan_arg},
}


def _add_flag(p: argparse.ArgumentParser, name: str, default,
              overrides: dict[str, dict] = _ARG_OVERRIDES) -> None:
    """One auto-generated dataclass flag, with any per-parser overrides."""
    flag = "--" + name.replace("_", "-")
    if isinstance(default, bool):
        p.add_argument(flag, action=argparse.BooleanOptionalAction,
                       default=default)
        return
    extra = dict(overrides.get(name, ()))
    ftype = extra.pop("type", str if default is None else type(default))
    p.add_argument(flag, type=ftype, default=default, **extra)


def build_lm_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="mpi_cuda_cnn_tpu lm",
        description="Train the transformer LM (long-context path: "
                    "flash attention, ring/Ulysses SP, MoE).",
    )
    defaults = LMConfig()
    for f in dataclasses.fields(LMConfig):
        _add_flag(p, f.name, getattr(defaults, f.name),
                  overrides=_LM_ARG_OVERRIDES)
    return p


def parse_lm_args(argv: list[str] | None = None) -> LMConfig:
    return LMConfig(**vars(build_lm_parser().parse_args(argv)))


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="mpi_cuda_cnn_tpu",
        description="TPU-native CNN trainer (capabilities of MPI-CUDA-CNN).",
    )
    # The reference contract: exactly 4 positional IDX paths (cnn.c:408-411).
    p.add_argument("idx_paths", nargs="*", metavar="IDX",
                   help="train-images train-labels test-images test-labels "
                        "(the reference CLI form; omit to use --dataset)")
    defaults = Config()
    for f in dataclasses.fields(Config):
        if f.name in ("train_images", "train_labels", "test_images", "test_labels"):
            continue
        _add_flag(p, f.name, getattr(defaults, f.name))
    return p


def parse_args(argv: list[str] | None = None) -> Config:
    ns = build_parser().parse_args(argv)
    kwargs = vars(ns)
    idx_paths = kwargs.pop("idx_paths")
    cfg = Config(**kwargs)
    if idx_paths:
        if len(idx_paths) != 4:
            # The reference exits 100 on bad argc (cnn.c:412) — keep the code.
            print(
                "expected 4 IDX paths: train-images train-labels "
                "test-images test-labels",
                file=sys.stderr,
            )
            raise SystemExit(100)
        cfg.train_images, cfg.train_labels, cfg.test_images, cfg.test_labels = idx_paths
        cfg.dataset = "idx"
    return cfg


def parse_mesh_shape(spec: str, total_devices: int) -> dict[str, int]:
    """Parse "data" / "data:4" / "data:4,model:2" into an axis dict.

    A bare axis name takes all remaining devices. The product must divide
    total_devices.
    """
    axes: dict[str, int] = {}
    free_axis = None
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if ":" in part:
            name, n = part.split(":")
            axes[name.strip()] = int(n)
        else:
            if free_axis is not None:
                raise ValueError(f"mesh spec {spec!r}: only one unsized axis allowed")
            free_axis = part
            axes[part] = -1
    fixed = 1
    for n in axes.values():
        if n > 0:
            fixed *= n
    if free_axis is not None:
        if total_devices % fixed:
            raise ValueError(f"mesh spec {spec!r} does not divide {total_devices} devices")
        axes[free_axis] = total_devices // fixed
    return axes
