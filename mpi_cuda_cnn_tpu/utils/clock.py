"""The ONE sanctioned wall-clock surface (`mctpu lint` MCT002).

Every duration this framework measures goes through an injectable
`clock` parameter with the time.perf_counter call shape — FakeClock
substitutes it and the serving/fleet/elasticity proofs are bitwise-
deterministic because of it. One capability genuinely needs the REAL
wall clock and has no business being injectable:

- `utc_stamp()` — a human-readable absolute timestamp for run-boundary
  markers (utils/logging.py's `# run 2026-...` line). Record "t"
  fields stay relative (the schema's cross-process contract); the
  marker is documentation for a human scanning an append-mode file.

Raw `time.time` / `time.monotonic` / `datetime.now` reads anywhere
else are MCT002 findings: either the caller should take an injectable
clock, or its need belongs here with a name and a docstring — or, for
code that cannot import this package at all (bench.py's parent process
must never trigger the jax import chain), a commented
`# mctpu: disable=MCT002` at the site. The analyzer's manifest
(ci/lint_manifest.json clock_modules) allowlists exactly this file.
"""

from __future__ import annotations

import time

__all__ = ["utc_stamp"]


def utc_stamp(fmt: str = "%Y-%m-%dT%H:%M:%SZ") -> str:
    """The current UTC moment, formatted. For run markers and file
    names only — never for measuring durations (inject a clock) and
    never into record "t" fields (those are relative by schema)."""
    return time.strftime(fmt, time.gmtime())
