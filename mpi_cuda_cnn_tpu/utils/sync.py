"""Device synchronization that actually waits.

`jax.block_until_ready` is the documented way to drain async dispatch
before reading a wall-clock — but under this environment's remote-TPU
tunnel (the experimental 'axon' platform) it returns once the work is
*queued*, not done: measured, a 1.5 s matmul chain "blocks" in 0.16 s
and a later host fetch then stalls the remaining 1.4 s. Every timing in
the framework therefore syncs through `hard_block`, which combines the
normal block with a device->host fetch of the smallest array leaf — a
transfer cannot complete before its value exists, and fetching any
output of the final program in a dispatch chain drains the whole chain.

On backends where block_until_ready is correct this adds one scalar-ish
D2H copy per call — noise. Timing-critical loops should arrange for a
small leaf (a step counter, a scalar loss) to exist in the synced tree.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def hard_block(tree):
    """Force completion of every computation `tree` depends on; returns
    `tree` unchanged (like jax.block_until_ready)."""
    jax.block_until_ready(tree)
    leaves = [l for l in jax.tree.leaves(tree) if hasattr(l, "dtype")]
    if leaves:
        smallest = min(leaves, key=lambda l: getattr(l, "size", 0))
        np.asarray(jax.device_get(smallest))
    return tree


def two_point(run, n: int, *, warmup: int = 1, reps: int = 3) -> float:
    """Per-iteration time: median of `reps` samples of (T(2n) - T(n)) / n.

    THE two-point core — every benchmark in the repo routes through this
    one function (scan_two_point below, scripts/bench_lm,
    scripts/check_gqa_flash, scripts/profile_lm): both measurement
    corrections in the repo's history were exactly this logic drifting
    per script.

    `run(k)` must execute k DEPENDENT iterations (so XLA cannot overlap
    or elide them), force completion (hard_block / a host fetch), and
    return elapsed seconds. The T(2n) - T(n) difference cancels every
    fixed per-window cost — through this environment's remote-TPU tunnel
    that is a ~100 ms dispatch round-trip per timed window, which a
    naive T(n)/n would smear across the iterations (PERF.md "Methodology
    notes"). The MEDIAN over `reps` window pairs absorbs backend
    transients (observed: a single pair reading 15x slow while the next
    was normal); sub-10% differences are not resolvable from one sample.
    The warmup call absorbs compilation for run(k)'s cache entries;
    callers whose per-k programs compile per distinct k should warm both
    sizes themselves and pass warmup=0.
    """
    if warmup:
        run(warmup)
    samples = []
    for _ in range(max(reps, 1)):
        t1 = run(n)
        t2 = run(2 * n)
        samples.append((t2 - t1) / n)
    return sorted(samples)[len(samples) // 2]


def grad_stacked(fn):
    """fwd+bwd measurement target for `scan_two_point`: gradients of
    sum(fn(*args)²) wrt every positional arg, stacked into ONE array so
    the scan body's output-sum DCE defeat covers all gradient leaves.
    One definition for every script that times a backward
    (bench_attention --bwd, check_gqa_flash) — the grad-stack idiom
    must not drift per script any more than the window recipe."""

    def wrapped(*args):
        g = jax.grad(
            lambda *a: jnp.sum(fn(*a).astype(jnp.float32) ** 2),
            argnums=tuple(range(len(args))),
        )(*args)
        return jnp.stack([jnp.sum(t.astype(jnp.float32)) for t in g])

    return wrapped


def scan_two_point(fn, n: int, *args, reps: int = 3) -> float:
    """Per-call seconds of `fn(*args)` via `two_point` over ON-DEVICE
    scan windows — the micro-op form of the shared recipe (scripts/
    bench_attention.py, bench_conv_shapes.py):

    - a window of m calls is one jitted `lax.scan` of m iterations; the
      body perturbs the first operand per step (defeats CSE; the factor
      is computed in f32 then CAST BACK so bf16 operands stay bf16 —
      naive `x * (1 + i*1e-9)` promotes to f32 and benches the wrong
      kernel) and accumulates a f32 sum of the output (defeats DCE);
    - `float()` on the scan result is the hard sync (a host fetch
      cannot complete before the value exists — see hard_block above);
    - window cancellation + median over `reps` come from `two_point`.
    """

    def make(m):
        @jax.jit
        def run(args):
            def body(acc, i):
                a0 = args[0] * (1.0 + i * 1e-9).astype(args[0].dtype)
                out = fn(a0, *args[1:])
                return acc + jnp.sum(out.astype(jnp.float32)), None

            acc, _ = lax.scan(body, jnp.zeros((), jnp.float32),
                              jnp.arange(m, dtype=jnp.float32))
            return acc

        return run

    progs = {m: make(m) for m in (n, 2 * n)}
    for p in progs.values():  # compile + warm both sizes
        float(p(args))

    def run(m):
        t0 = time.perf_counter()
        float(progs[m](args))
        return time.perf_counter() - t0

    return two_point(run, n, warmup=0, reps=reps)
