"""Device synchronization that actually waits.

`jax.block_until_ready` is the documented way to drain async dispatch
before reading a wall-clock — but under this environment's remote-TPU
tunnel (the experimental 'axon' platform) it returns once the work is
*queued*, not done: measured, a 1.5 s matmul chain "blocks" in 0.16 s
and a later host fetch then stalls the remaining 1.4 s. Every timing in
the framework therefore syncs through `hard_block`, which combines the
normal block with a device->host fetch of the smallest array leaf — a
transfer cannot complete before its value exists, and fetching any
output of the final program in a dispatch chain drains the whole chain.

On backends where block_until_ready is correct this adds one scalar-ish
D2H copy per call — noise. Timing-critical loops should arrange for a
small leaf (a step counter, a scalar loss) to exist in the synced tree.
"""

from __future__ import annotations

import jax
import numpy as np


def hard_block(tree):
    """Force completion of every computation `tree` depends on; returns
    `tree` unchanged (like jax.block_until_ready)."""
    jax.block_until_ready(tree)
    leaves = [l for l in jax.tree.leaves(tree) if hasattr(l, "dtype")]
    if leaves:
        smallest = min(leaves, key=lambda l: getattr(l, "size", 0))
        np.asarray(jax.device_get(smallest))
    return tree


def two_point(run, n: int, *, warmup: int = 1) -> float:
    """Per-iteration time via (T(2n) - T(n)) / n.

    `run(k)` must execute k DEPENDENT iterations (so XLA cannot overlap
    or elide them), force completion (hard_block), and return elapsed
    seconds. The difference cancels every fixed per-call cost — through
    this environment's remote-TPU tunnel that is a ~100 ms dispatch
    round-trip per timed window, which a naive T(n)/n would smear across
    the iterations (PERF.md "Methodology notes"). The warmup call
    absorbs compilation for both point sizes' cache entries when run(k)
    compiles per distinct k (callers with per-k programs should warm
    both sizes themselves).
    """
    run(max(warmup, 1))
    return (run(2 * n) - run(n)) / n
