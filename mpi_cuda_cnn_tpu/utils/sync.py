"""Device synchronization that actually waits.

`jax.block_until_ready` is the documented way to drain async dispatch
before reading a wall-clock — but under this environment's remote-TPU
tunnel (the experimental 'axon' platform) it returns once the work is
*queued*, not done: measured, a 1.5 s matmul chain "blocks" in 0.16 s
and a later host fetch then stalls the remaining 1.4 s. Every timing in
the framework therefore syncs through `hard_block`, which combines the
normal block with a device->host fetch of the smallest array leaf — a
transfer cannot complete before its value exists, and fetching any
output of the final program in a dispatch chain drains the whole chain.

On backends where block_until_ready is correct this adds one scalar-ish
D2H copy per call — noise. Timing-critical loops should arrange for a
small leaf (a step counter, a scalar loss) to exist in the synced tree.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def hard_block(tree):
    """Force completion of every computation `tree` depends on; returns
    `tree` unchanged (like jax.block_until_ready)."""
    jax.block_until_ready(tree)
    leaves = [l for l in jax.tree.leaves(tree) if hasattr(l, "dtype")]
    if leaves:
        smallest = min(leaves, key=lambda l: getattr(l, "size", 0))
        np.asarray(jax.device_get(smallest))
    return tree


def two_point(run, n: int, *, warmup: int = 1) -> float:
    """Per-iteration time via (T(2n) - T(n)) / n.

    `run(k)` must execute k DEPENDENT iterations (so XLA cannot overlap
    or elide them), force completion (hard_block), and return elapsed
    seconds. The difference cancels every fixed per-call cost — through
    this environment's remote-TPU tunnel that is a ~100 ms dispatch
    round-trip per timed window, which a naive T(n)/n would smear across
    the iterations (PERF.md "Methodology notes"). The warmup call
    absorbs compilation for both point sizes' cache entries when run(k)
    compiles per distinct k (callers with per-k programs should warm
    both sizes themselves).
    """
    run(max(warmup, 1))
    return (run(2 * n) - run(n)) / n


def scan_two_point(fn, n: int, *args, reps: int = 3) -> float:
    """Per-call seconds of `fn(*args)` via two-point ON-DEVICE scans.

    The one shared implementation of the benchmark-timing recipe (both
    measurement corrections in this repo's history were exactly this
    logic drifting per script — scripts/bench_conv_shapes.py round 2,
    scripts/bench_attention.py round 4):

    - each sample times a jitted `lax.scan` of n and of 2n iterations
      and reports (T(2n) − T(n)) / n, so the fixed per-window cost
      (through this environment's tunnel: ~100 ms of dispatch + forced
      host read) cancels instead of being smeared across n;
    - the scan body perturbs the first operand per step (defeats CSE)
      and accumulates a f32 sum of the output (defeats DCE); the
      `float()` on the result is the hard sync (a host fetch cannot
      complete before the value exists — see hard_block above);
    - the returned value is the MEDIAN of `reps` samples: sub-10%
      differences are not resolvable from one sample through a jittery
      tunnel.

    `fn` must accept `fn(args[0]', *args[1:])` where args[0]' has
    args[0]'s shape and dtype (the perturbation is computed in f32 and
    cast back, so bf16 operands stay bf16).
    """

    def make(m):
        @jax.jit
        def run(args):
            def body(acc, i):
                a0 = args[0] * (1.0 + i * 1e-9).astype(args[0].dtype)
                out = fn(a0, *args[1:])
                return acc + jnp.sum(out.astype(jnp.float32)), None

            acc, _ = lax.scan(body, jnp.zeros((), jnp.float32),
                              jnp.arange(m, dtype=jnp.float32))
            return acc

        return run

    run_n, run_2n = make(n), make(2 * n)
    float(run_n(args)), float(run_2n(args))  # compile + warm both sizes
    samples = []
    for _ in range(max(reps, 1)):
        t0 = time.perf_counter()
        float(run_n(args))
        t1 = time.perf_counter()
        float(run_2n(args))
        t2 = time.perf_counter()
        samples.append(((t2 - t1) - (t1 - t0)) / n)
    return sorted(samples)[len(samples) // 2]
