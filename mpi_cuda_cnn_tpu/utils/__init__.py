"""Utilities: config/flags, logging/metrics, profiling."""

from . import compat  # noqa: F401  (installs the jax.shard_map alias)
from .config import Config, parse_args
from .logging import MetricsLogger, get_logger
from .profiling import StepTimer, profile_trace

__all__ = [
    "Config",
    "parse_args",
    "MetricsLogger",
    "get_logger",
    "StepTimer",
    "profile_trace",
]
