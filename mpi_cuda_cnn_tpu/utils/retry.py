"""Exponential backoff with jitter — the one delay formula every
retry loop shares.

Two consumers with very different failure domains want the same math:
`scripts/get_mnist.py` retries a flaky mirror fetch, and
`faults.supervise` paces crash-restart attempts (ISSUE 5 satellite —
an immediate restart storm against a sick filesystem or coordinator
just reproduces the crash faster). Keeping the formula here means the
two can never drift: delay = base * 2^attempt * (1 + U[0,1)), where
the jitter term de-synchronizes parallel retriers hammering a
recovering dependency.
"""

from __future__ import annotations

import random


def backoff_delay(attempt: int, base: float,
                  # injectable U[0,1) default: tests pass a constant
                  # mctpu: disable=MCT004
                  jitter=random.random) -> float:
    """Delay in seconds before retry number `attempt` (0-based: the
    delay AFTER the first failure is attempt 0). `jitter` is an
    injection point returning U[0,1) — tests pass a constant."""
    if base <= 0:
        return 0.0
    return base * (2 ** attempt) * (1.0 + jitter())
