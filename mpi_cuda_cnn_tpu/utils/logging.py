"""Logging and metrics.

The reference's entire observability surface is fprintf(stderr, ...): a
running squared-error every 1000 steps (cnn.c:470-473) and one final
"ntests=%d, ncorrect=%d" line (cnn.c:518). We keep those human-readable
lines (so e2e output is comparable) and add structured JSONL metrics with
wall-clock timing — records in the obs.schema shape, so `mctpu report`
aggregates any run file into the tables PERF.md used to get by hand.
"""

from __future__ import annotations

import json
import logging
import sys
import time
from pathlib import Path

from ..obs.schema import RUN_MARKER, make_record
from .clock import utc_stamp

_LOGGER_NAME = "mpi_cuda_cnn_tpu"


def get_logger() -> logging.Logger:
    logger = logging.getLogger(_LOGGER_NAME)
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname)s %(message)s", "%H:%M:%S")
        )
        logger.addHandler(handler)
        logger.setLevel(logging.INFO)
        logger.propagate = False
    return logger


class MetricsLogger:
    """Structured metrics: JSONL file sink + human-readable stderr echo.

    Records carry the obs.schema shape ({"schema", "event", "t", ...}).
    A context manager, so trainers hold the file handle exception-safely:

        with MetricsLogger(path) as metrics:
            Trainer(..., metrics=metrics).train()

    `clock` has the time.perf_counter call shape and stamps each
    record's "t" relative to construction; fault-harness tests inject a
    faults.FakeClock so telemetry timestamps are deterministic.
    """

    def __init__(self, path: str | Path | None = None, echo: bool = True,
                 capture: bool = False, clock=None):
        self._file = None
        self._clock = clock if clock is not None else time.perf_counter
        if path is not None:
            p = Path(path)
            p.parent.mkdir(parents=True, exist_ok=True)
            self._file = p.open("a")
            # Run-boundary marker: append mode means re-running with the
            # same path accumulates runs in one file — the comment line
            # (obs.schema.RUN_MARKER) is where iter_runs/`mctpu report`
            # split, so aggregates never blend unrelated runs.
            # Absolute stamp via the one sanctioned wall-clock surface
            # (utils/clock, MCT002) — record "t" fields stay relative.
            self._file.write(f"{RUN_MARKER} {utc_stamp()}\n")
            self._file.flush()
        self._echo = echo
        self._log = get_logger()
        self._t0 = self._clock()
        # In-memory record list, opt-in (unbounded — long-lived trainers
        # should leave it off and use the JSONL sink).
        self.rows: list[dict] | None = [] if capture else None
        # Streaming observer (ISSUE 8): called with every record as it
        # is logged — the obs.alerts.AlertEngine attaches here, so the
        # live rule engine folds EXACTLY the records the file receives
        # (which is what makes replaying the finished file reproduce
        # the identical alert sequence). The observer may itself call
        # log() (alerts are logged back through the same sink); a
        # reentrant observer call sees the alert record and must ignore
        # it, which AlertEngine.ingest does.
        self.observer = None

    @property
    def jsonl_enabled(self) -> bool:
        """True while a JSONL sink is open — the trainers' gate for
        telemetry that costs something to produce (program cost
        analysis, per-epoch memory snapshots)."""
        return self._file is not None

    def sink_or_none(self) -> MetricsLogger | None:
        """self when the JSONL sink is open, else None — the form
        obs.trace.span's `metrics=` argument wants (emit span records
        only when a run file is collecting them)."""
        return self if self.jsonl_enabled else None

    def log(self, event: str, **fields) -> None:
        record = make_record(event, self._clock() - self._t0, **fields)
        if self.rows is not None:
            self.rows.append(record)
        if self._file:
            self._file.write(json.dumps(record) + "\n")
            self._file.flush()
        if self._echo:
            body = " ".join(f"{k}={_fmt(v)}" for k, v in fields.items())
            self._log.info("%s %s", event, body)
        if self.observer is not None:
            self.observer(record)

    def close(self) -> None:
        if self._file:
            self._file.close()
            self._file = None

    def __enter__(self) -> MetricsLogger:
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        # Close the sink even when the trainer raised mid-run — the
        # records written so far must survive the exception.
        self.close()


def _fmt(v):
    if isinstance(v, float):
        return f"{v:.6g}"
    return v
