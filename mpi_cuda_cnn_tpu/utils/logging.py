"""Logging and metrics.

The reference's entire observability surface is fprintf(stderr, ...): a
running squared-error every 1000 steps (cnn.c:470-473) and one final
"ntests=%d, ncorrect=%d" line (cnn.c:518). We keep those human-readable
lines (so e2e output is comparable) and add structured JSONL metrics with
wall-clock timing — the subsystem SURVEY.md §5.5 notes the reference lacks.
"""

from __future__ import annotations

import json
import logging
import sys
import time
from pathlib import Path

_LOGGER_NAME = "mpi_cuda_cnn_tpu"


def get_logger() -> logging.Logger:
    logger = logging.getLogger(_LOGGER_NAME)
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname)s %(message)s", "%H:%M:%S")
        )
        logger.addHandler(handler)
        logger.setLevel(logging.INFO)
        logger.propagate = False
    return logger


class MetricsLogger:
    """Structured metrics: JSONL file sink + human-readable stderr echo."""

    def __init__(self, path: str | Path | None = None, echo: bool = True,
                 capture: bool = False):
        self._file = None
        if path is not None:
            p = Path(path)
            p.parent.mkdir(parents=True, exist_ok=True)
            self._file = p.open("a")
        self._echo = echo
        self._log = get_logger()
        self._t0 = time.perf_counter()
        # In-memory record list, opt-in (unbounded — long-lived trainers
        # should leave it off and use the JSONL sink).
        self.rows: list[dict] | None = [] if capture else None

    def log(self, event: str, **fields) -> None:
        record = {"event": event, "t": round(time.perf_counter() - self._t0, 4), **fields}
        if self.rows is not None:
            self.rows.append(record)
        if self._file:
            self._file.write(json.dumps(record) + "\n")
            self._file.flush()
        if self._echo:
            body = " ".join(f"{k}={_fmt(v)}" for k, v in fields.items())
            self._log.info("%s %s", event, body)

    def close(self) -> None:
        if self._file:
            self._file.close()
            self._file = None


def _fmt(v):
    if isinstance(v, float):
        return f"{v:.6g}"
    return v
