"""mpi_cuda_cnn_tpu — a TPU-native CNN training framework.

A from-scratch reimplementation of the *capabilities* of the reference
MPI-CUDA-CNN project (a hand-rolled C/MPI/CUDA CNN trainer) as an idiomatic
JAX/XLA/Pallas framework:

- data:     MNIST-style IDX loading (reference: cnn.c:345-402), dataset
            registry, synthetic data generators, batched input pipelines.
- models:   functional layer/model API with the reference's layer types
            (input/conv/full, reference: cnn.c:15-43) plus pooling, and the
            benchmark model presets (reference net, LeNet-5, CIFAR nets).
- ops:      pure-XLA reference ops and Pallas TPU kernels for conv/dense
            forward+backward (reference: cnn.c:113-247, CUDAcnn.cu:167-195).
- parallel: SPMD data parallelism over a `jax.sharding.Mesh` with XLA
            collectives, replacing the reference's per-sample MPI_Allreduce
            (reference: cnnmpi.c:487-499) with one fused gradient psum per
            batched step; extensible to model axes.
- train:    jitted train/eval loops, SGD semantics matching the reference's
            accumulate-then-apply schedule (reference: cnn.c:445-474),
            checkpoint/resume, metrics.

Design stance: everything on the hot path is traced once under `jax.jit`
(static shapes, `lax` control flow), parameters and activations stay
device-resident in HBM, matmuls/convs run on the MXU in f32 (optional bf16),
and multi-device execution is expressed as shardings over a named mesh, not
explicit message passing.
"""

__version__ = "0.1.0"

from .utils import compat as _compat  # noqa: F401  (jax API aliases first)
from . import data, models, obs, ops, parallel, train, utils  # noqa: F401
