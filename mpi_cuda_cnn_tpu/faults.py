"""Deterministic fault injection + the recovery primitives that answer it.

The reference C program has no failure handling of any kind: a NaN, a
bad read, or a killed rank loses the whole run (SURVEY.md §0). This
module makes failure a first-class, *tested* input. It has two halves:

Injection — a `FaultPlan` is a seeded, fully deterministic list of named
faults, each bound to a hook SITE (a string like "train.step" or
"serve.tick") and a trigger VALUE (the step / tick / save index the host
code passes when it reaches the site). The trainers, the checkpoint
writer, and the serve engine carry explicit hook points (`faults=`
keyword arguments threaded down from the CLI's `--fault-plan` flag), so
tests and chaos runs inject without monkeypatching anything. Fault
kinds:

- ``crash``   — raise InjectedCrash at the site (simulated process
                death; the supervisor treats it like any crash)
- ``io``      — raise InjectedIOError (an OSError) at the site
- ``nan``     — poison the training batch with NaNs (the CNN trainer's
                float image batches, via `poison_batch`; the LM's int
                token batches can't carry NaN — its guard is exercised
                by organic non-finite losses)
- ``squeeze`` — steal ``pages`` pool pages for ``ticks`` engine ticks
                (serve engine; exercises preemption + deadline expiry)
- ``slow``    — stall a serve tick by ``s`` seconds (advances the
                injector's FakeClock when one is attached, else sleeps)
- ``preempt`` — simulated scheduler SIGTERM (ISSUE 5): the trainer's
                PreemptionGuard flags it, the run finishes the
                in-flight step, snapshots through the atomic
                checkpoint path, and exits Preempted (code 75) — the
                deterministic twin of a real preemption notice
- ``replica_crash`` — kill fleet replica ``replica=K`` at a fleet tick
                (ISSUE 7; ``zombie_ticks=N`` keeps it stepping as a
                partitioned zombie whose post-failover output the
                router's generation-token fence must discard)
- ``replica_join``  — elastic scale-out: add ``replicas=N`` fresh
                replicas to the fleet at a fleet tick
- ``replica_leave`` — graceful drain: replica ``replica=K`` stops
                taking dispatches, finishes its in-flight work, then
                deregisters
- ``pool_crash``    — kill every live replica of pool ``pool=prefill``
                or ``pool=decode`` at a fleet tick (ISSUE 13: the
                pool-collapse degradation driver — the fleet flips to
                unified serving for affected requests)
- ``handoff_drop``  — drop the Nth prefill->decode KV handoff in
                flight (ISSUE 13; trigger value = handoff sequence
                number): both ends release their pages and the
                request re-prefills exactly once
- ``kv_corrupt``    — corrupt one page's integrity stamp of the Nth
                handoff (``page=K``, site fleet.handoff), the Nth
                resume re-dispatch's committed context (site
                fleet.resume), or the Nth host-tier page spill (site
                tier.spill, ISSUE 17): verification refuses the
                transfer/readmission and the request re-prefills —
                garbage is never decoded
- ``msg_drop`` / ``msg_dup`` / ``msg_delay`` — arm a one-shot lossy-
                transport effect on the fleet's message bus (ISSUE 20,
                site fleet.transport, trigger value = fleet tick): the
                next matching send is dropped, duplicated, or delayed
                ``ticks`` ticks (optional ``kind=commit`` /
                ``replica=K`` / ``count=N`` filters)
- ``partition`` — open a ``ticks``-long network partition that drops
                every message to/from replica ``replica=K`` (ISSUE 20):
                the isolated replica keeps serving, gets declared dead
                by heartbeat staleness, and every post-lease commit is
                lease/fence-refused when the window heals

Recovery — `supervise()` is the `--max-restarts N` loop: it runs one
training attempt, and on a crash rebuilds the trainer and resumes from
the latest valid checkpoint, up to N times. Together with the
step-exact-resume contract (tests/test_step_resume.py) this makes an
interrupted-then-restarted run bitwise-equal to the uninterrupted one
(tests/test_faults.py proves it end to end through an injected crash).

Every fired fault, restart, and recovery lands in the obs JSONL schema
as a ``fault`` event; `mctpu report` renders them in the robustness
table.
"""

from __future__ import annotations

import dataclasses
import random
import signal as _signal
import threading
import time
from collections.abc import Callable

import numpy as np

from .utils.retry import backoff_delay


class InjectedFault(RuntimeError):
    """Base class for exceptions raised by injected faults — lets tests
    and the supervisor distinguish injected failures from real bugs."""


class InjectedCrash(InjectedFault):
    """Simulated process death at a hook point."""


class InjectedIOError(OSError):
    """Simulated IO failure at a hook point (an OSError, so it travels
    the same except paths a real disk error would)."""


@dataclasses.dataclass(frozen=True)
class Fault:
    """One planned fault: `kind` fires when the host code reaches hook
    `site` with trigger value `at` (each fault fires exactly once)."""

    kind: str
    site: str
    at: int
    args: dict = dataclasses.field(default_factory=dict)

    def arg(self, name: str, default=None):
        return self.args.get(name, default)


KINDS = ("crash", "io", "nan", "squeeze", "slow", "preempt",
         "replica_crash", "replica_join", "replica_leave",
         "pool_crash", "handoff_drop", "kv_corrupt",
         "msg_drop", "msg_dup", "msg_delay", "partition")

# Hook sites each CLI surface actually registers, and the kinds each
# site's consumer APPLIES (ISSUE 7 satellite): a plan naming a site the
# chosen subcommand never reaches would silently never fire, and a kind
# the site's consumer ignores (e.g. replica_crash@train.step) would
# fire and silently do nothing — `validate_plan_sites` turns both into
# argparse-time errors. crash/io are legal everywhere a FIRED site
# exists: FaultInjector.fire raises them unconditionally, so they are
# always observable (the POLLED fleet.handoff/fleet.resume sites
# exclude them — poll never raises, so they would be inert there).
# The trainers are two surfaces: both thread the injector
# through train.step and the checkpoint hooks, but only the CNN
# trainer fires train.batch (the nan-poisoning site) — nan@train.batch
# on an LM run would validate and then silently never fire.
SITES: dict[str, dict[str, frozenset[str]]] = {
    "train": {
        "train.batch": frozenset({"crash", "io", "nan"}),
        "train.step": frozenset({"crash", "io", "preempt"}),
        "ckpt.pre_rename": frozenset({"crash", "io"}),
        "ckpt.manifest": frozenset({"crash", "io"}),
    },
    "train-lm": {
        "train.step": frozenset({"crash", "io", "preempt"}),
        "ckpt.pre_rename": frozenset({"crash", "io"}),
        "ckpt.manifest": frozenset({"crash", "io"}),
    },
    "serve-bench": {
        "serve.tick": frozenset({"crash", "io", "squeeze", "slow"}),
        # Host-tier spill integrity (ISSUE 17). Polled, not fired (the
        # spill happens inside the prefix cache's reclaim path, not at
        # a tick boundary), so crash/io are deliberately absent — they
        # would be inert. Triggers on the SPILL sequence number (the
        # Nth device->host page spill); kv_corrupt flips the spilled
        # page's integrity stamp so the later readmission is refused
        # and the request re-prefills — garbage is never decoded.
        "tier.spill": frozenset({"kv_corrupt"}),
    },
    "fleet-bench": {
        "fleet.tick": frozenset({"crash", "io", "replica_crash",
                                 "replica_join", "replica_leave",
                                 "pool_crash"}),
        # Disaggregated-serving faults (ISSUE 13). These sites are
        # polled, not fired, so the always-raising crash/io kinds are
        # deliberately NOT registered here — they would be inert.
        # fleet.handoff triggers on the HANDOFF sequence number (the
        # Nth prefill->decode transfer), fleet.resume on the resume
        # re-dispatch sequence number (the Nth committed-context
        # transfer across a failover).
        "fleet.handoff": frozenset({"handoff_drop", "kv_corrupt"}),
        "fleet.resume": frozenset({"kv_corrupt"}),
        # Per-replica host-tier spills (ISSUE 17): same polled site the
        # serve-bench surface registers, trigger value = the replica's
        # own spill sequence number.
        "tier.spill": frozenset({"kv_corrupt"}),
        # Lossy-transport faults (ISSUE 20). Polled once per fleet
        # TICK by the message bus (crash/io would be inert — absent).
        # partition opens a ticks-long window dropping everything
        # to/from replica=K; msg_drop/msg_dup/msg_delay arm one-shot
        # effects on the next matching send (optional kind=/replica=
        # filters, count= repeats, ticks= delay length). Requires
        # --transport; validated inert otherwise.
        "fleet.transport": frozenset({"msg_drop", "msg_dup",
                                      "msg_delay", "partition"}),
    },
}


def fault_plan_arg(surface: str):
    """argparse `type=` factory for --fault-plan: grammar AND hook-site/
    kind validation at parse time, shared by every CLI surface (train,
    serve-bench, fleet-bench) so the error contract cannot drift."""
    def check(spec: str):
        import argparse

        try:
            validate_plan_sites(parse_plan(spec), surface)
        except ValueError as e:
            raise argparse.ArgumentTypeError(str(e)) from e
        return spec
    return check


def validate_plan_sites(plan: list[Fault] | str, surface: str) -> None:
    """Raise ValueError if any fault in `plan` targets a site the
    `surface` subcommand does not register, or a kind that site's
    consumer never applies (SITES)."""
    if isinstance(plan, str):
        plan = parse_plan(plan)
    allowed = SITES.get(surface)
    if allowed is None:
        # A drifted surface string is a programming error in the CLI
        # wiring, but it must still surface as the one-line exit-2
        # argparse error (fault_plan_arg wraps ValueError only).
        raise ValueError(
            f"unknown fault surface {surface!r} "
            f"(known: {', '.join(sorted(SITES))})"
        )
    bad = sorted({f.site for f in plan if f.site not in allowed})
    if bad:
        raise ValueError(
            f"fault site(s) {', '.join(bad)} are never reached by "
            f"{surface!r} (its sites: {', '.join(sorted(allowed))}) — "
            "the fault would silently never fire"
        )
    for f in plan:
        if f.kind not in allowed[f.site]:
            raise ValueError(
                f"fault kind {f.kind!r} is never applied at {f.site} "
                f"(its kinds: {', '.join(sorted(allowed[f.site]))}) — "
                "the fault would fire and silently do nothing"
            )


def parse_plan(spec: str) -> list[Fault]:
    """Parse a compact fault-plan spec into a list of Faults.

    Grammar: faults are ';'-separated, each ``kind@site:at`` with
    optional ``?key=val&key=val`` args (ints/floats parsed, anything
    else kept as a string)::

        crash@train.step:6
        nan@train.batch:3;crash@train.step:6
        squeeze@serve.tick:2?pages=4&ticks=8
        slow@serve.tick:5?s=2.5

    Raises ValueError with the offending fragment on any malformed
    piece — a chaos run must fail at parse time, not mid-experiment.
    """
    faults = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        head, _, argstr = part.partition("?")
        try:
            kind, _, rest = head.partition("@")
            site, _, at = rest.rpartition(":")
            fault = Fault(kind=kind.strip(), site=site.strip(),
                          at=int(at), args=_parse_args(argstr))
        except ValueError as e:
            raise ValueError(
                f"bad fault spec {part!r} (want kind@site:at[?k=v&k=v]): {e}"
            ) from e
        if fault.kind not in KINDS:
            raise ValueError(
                f"bad fault spec {part!r}: unknown kind {fault.kind!r} "
                f"(want one of {KINDS})"
            )
        if not fault.site:
            raise ValueError(f"bad fault spec {part!r}: empty site")
        faults.append(fault)
    return faults


def format_fault(f: Fault) -> str:
    """One fault back in the ``kind@site:at?k=v&k=v`` grammar — the
    inverse of one parse_plan fragment. Args render in sorted key order
    so equal Faults always spell identically (the chaos sampler's
    one-line repro contract, ISSUE 19); values must survive
    _parse_args' int->float->str ladder, which every int/float/plain
    string does (a value containing ';', '&' or '=' would not — no
    registered fault kind takes one)."""
    head = f"{f.kind}@{f.site}:{f.at}"
    if not f.args:
        return head
    return head + "?" + "&".join(f"{k}={f.args[k]}" for k in sorted(f.args))


def format_plan(plan: list[Fault]) -> str:
    """A whole plan as the ';'-joined --fault-plan string: the exact
    round-trip twin of parse_plan (parse_plan(format_plan(p)) == p), so
    any sampled chaos schedule is a copy-pasteable repro line."""
    return ";".join(format_fault(f) for f in plan)


def _parse_args(argstr: str) -> dict:
    args: dict = {}
    for kv in argstr.split("&"):
        if not kv:
            continue
        k, sep, v = kv.partition("=")
        if not sep:
            raise ValueError(f"bad fault arg {kv!r} (want key=val)")
        try:
            args[k] = int(v)
        except ValueError:
            try:
                args[k] = float(v)
            except ValueError:
                args[k] = v
    return args


class FakeClock:
    """A manually-advanced clock with the time.perf_counter call shape —
    deadline/watchdog tests drive the serve engine with one of these so
    expiry is deterministic, never wall-clock-raced."""

    def __init__(self, start: float = 0.0):
        self.now = float(start)

    def __call__(self) -> float:
        return self.now

    def advance(self, seconds: float) -> None:
        self.now += float(seconds)


# The distinguished "preempted, resumable" exit code (BSD EX_TEMPFAIL):
# a supervisor or cluster scheduler seeing it knows the run snapshotted
# cleanly and wants to be relaunched with --resume on whatever hardware
# comes back — unlike a crash (traceback, nonzero generic) or a NaN
# abort (policy verdict, not retryable).
EXIT_PREEMPTED = 75


class Preempted(SystemExit):
    """Raised by a trainer after a preemption notice (SIGTERM/SIGINT or
    an injected ``preempt`` fault) once the in-flight step finished.
    Derives SystemExit so `supervise` passes it through — an in-process
    retry cannot answer a scheduler's eviction; the relaunch happens on
    the NEXT placement, via --resume.

    The exit code keeps the EXIT_PREEMPTED contract honest: 75 is
    raised ONLY when a snapshot actually landed (resumable=True); a
    preemption with no checkpoint dir exits 1 — a supervisor must not
    relaunch-with-resume a run that has nothing to resume from."""

    def __init__(self, msg: str = "preempted", *, resumable: bool = True):
        super().__init__(EXIT_PREEMPTED if resumable else 1)
        self.msg = msg
        self.resumable = resumable

    def __str__(self) -> str:  # SystemExit.__str__ shows the code only
        return self.msg


def drain_preemption(guard: "PreemptionGuard", *, state, global_step: int,
                     ckpt, metrics, logger) -> None:
    """The orderly preemption exit, shared by both trainers (ONE
    implementation, the NanGuard precedent): no-op unless the guard is
    flagged; otherwise snapshot through the atomic checksummed path,
    make it durable, emit the obs trail, raise Preempted.

    Runs at step/chunk boundaries only (the callers guarantee the
    in-flight step finished). `ckpt` is the trainer's AsyncCheckpointer
    or None; a save already issued for this exact step (an interval
    save on the same boundary) is not repeated — the drain just waits
    for it, so the eviction grace window never pays the same write
    twice. Without a checkpointer the run still exits in an orderly way
    but as NOT resumable (exit 1, no false snapshot claim)."""
    if not guard.requested:
        return
    snapshotted = ckpt is not None
    if snapshotted:
        if ckpt.last_step != global_step:
            ckpt.save(state, global_step)
        ckpt.wait()  # durable BEFORE the process exits
        metrics.log("ckpt", step=global_step, reason="preempt")
    else:
        logger.warning(
            "preempted with no --checkpoint-dir: progress up to step "
            "%d is lost", global_step,
        )
    metrics.log("fault", kind="preempt", step=global_step,
                signum=guard.signum, resumable=snapshotted)
    if snapshotted:
        logger.warning(
            "preempted at step %d: snapshot written, exiting %d "
            "(resume with --resume on whatever topology comes back)",
            global_step, EXIT_PREEMPTED,
        )
    raise Preempted(f"preempted at step {global_step}",
                    resumable=snapshotted)


class PreemptionGuard:
    """Deferred-preemption flag shared by the signal handler, the fault
    injector, and the trainer step loop.

    The handler/injector only ever SETS a flag; the trainer polls it at
    step (or scanned-chunk) boundaries, where the state is consistent,
    and performs the orderly exit itself: finish the in-flight step,
    write a checkpoint through the atomic/checksummed path, emit the
    obs events, raise Preempted. install() hooks SIGTERM+SIGINT (the
    preemptible-VM notice and the operator's ^C take the same orderly
    path); uninstall() restores the previous handlers, and the guard is
    a context manager so tests can't leak handlers. A second signal
    while the first is still draining falls through to the PREVIOUS
    handler (default: die) — a stuck drain must stay killable.
    """

    def __init__(self):
        self.requested = False
        self.signum: int | None = None
        self._prev: dict[int, object] = {}

    def request(self, signum: int | None = None) -> None:
        self.requested = True
        if self.signum is None:
            self.signum = signum

    def _handle(self, signum, frame) -> None:
        if self.requested:
            # Second notice: restore + re-raise via the previous handler
            # so an impatient operator's repeat ^C still kills the run.
            self.uninstall()
            _signal.raise_signal(signum)
            return
        self.request(signum)

    def install(self, signals=(_signal.SIGTERM, _signal.SIGINT)) -> PreemptionGuard:
        for s in signals:
            try:
                self._prev[s] = _signal.signal(s, self._handle)
            except ValueError:
                # Not the main thread (embedded caller): injected
                # preempt faults still work — only OS signals don't
                # reach this guard.
                pass
        return self

    def uninstall(self) -> None:
        for s, prev in self._prev.items():
            _signal.signal(s, prev)
        self._prev.clear()

    def __enter__(self) -> PreemptionGuard:
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()


class FaultInjector:
    """Deterministic dispenser for a FaultPlan.

    Host code calls `poll(site, value)` (returns the matching unfired
    faults and records them) or `fire(site, value)` (same, but raising
    kinds — crash/io — raise immediately). Each fault fires at most
    once, so a supervisor-restarted attempt does not re-trip the crash
    that killed the previous attempt: the injector object is shared
    across attempts, which is exactly what makes the e2e
    crash-restart-bitwise test meaningful.

    `events` accumulates one obs-schema-shaped field dict per fired
    fault; producers drain it through MetricsLogger (the injector stays
    logger-free so it can run inside the checkpoint worker thread).
    """

    def __init__(self, plan: list[Fault] | str | None = None, *,
                 clock: FakeClock | None = None,
                 sleep_fn: Callable[[float], None] | None = None):
        if isinstance(plan, str):
            plan = parse_plan(plan)
        self.plan = list(plan or ())
        self.clock = clock
        self._sleep_fn = sleep_fn
        self._fired: set[int] = set()
        self.events: list[dict] = []
        # poll() runs wherever the hook site lives — including the
        # AsyncCheckpointer's worker thread — while the trainer thread
        # swap-drains `events`; the lock keeps an event from landing on
        # a just-discarded list.
        self._lock = threading.Lock()

    def poll(self, site: str, value: int) -> list[Fault]:
        """Unfired faults matching (site, value), marked fired."""
        hits = []
        with self._lock:
            for i, f in enumerate(self.plan):
                if i in self._fired or f.site != site or f.at != int(value):
                    continue
                self._fired.add(i)
                # A fault arg may share a name with the event's own
                # keys or the logger's envelope (the transport faults'
                # `kind=` message filter, ISSUE 20) — prefix those so
                # the arg rides along without overwriting the record.
                reserved = ("kind", "site", "at", "event", "t", "mode",
                            "schema")
                self.events.append({
                    "kind": f"injected_{f.kind}", "site": site,
                    "at": int(value),
                    **{(f"arg_{k}" if k in reserved else k): v
                       for k, v in f.args.items()},
                })
                hits.append(f)
        return hits

    def pending(self, site: str, kind: str | None = None) -> list[Fault]:
        """Unfired faults at `site` (optionally filtered to `kind`), in
        plan order — lets a driver see scheduled capacity it must wait
        for (the fleet's replica_join) before declaring a dead end."""
        with self._lock:
            return [f for i, f in enumerate(self.plan)
                    if i not in self._fired and f.site == site
                    and (kind is None or f.kind == kind)]

    def fire(self, site: str, value: int) -> list[Fault]:
        """poll(), then raise for the raising kinds; non-raising faults
        are returned for the caller to apply (nan/squeeze/slow)."""
        soft = []
        for f in self.poll(site, value):
            if f.kind == "crash":
                raise InjectedCrash(f"injected crash at {site}:{value}")
            if f.kind == "io":
                raise InjectedIOError(
                    f"injected IO error at {site}:{value}")
            soft.append(f)
        return soft

    def sleep(self, seconds: float) -> None:
        """A slow-fault's stall: advances the attached FakeClock when
        one exists (deterministic tests), else really sleeps."""
        if self.clock is not None:
            self.clock.advance(seconds)
        elif self._sleep_fn is not None:
            self._sleep_fn(seconds)
        else:
            time.sleep(seconds)

    def drain_events(self) -> list[dict]:
        with self._lock:
            ev, self.events = self.events, []
        return ev


def poison_batch(x, fault: Fault):
    """Apply a ``nan`` fault to a host batch: NaN-poison a deterministic
    slice of the array (the first row unless args say otherwise) — the
    partial poisoning is what makes the NaN guard's detection, not the
    injection, do the work."""
    x = np.array(x, dtype=np.float32, copy=True)
    rows = int(fault.arg("rows", 1))
    x[:rows] = np.nan
    return x


class NonFiniteLossError(RuntimeError):
    """Raised by --nan-policy=abort when a step's loss/metrics or the
    post-update parameter norm go NaN/Inf."""


class RollbackToCheckpoint(Exception):
    """Control-flow signal raised inside a trainer's step loop when
    --nan-policy=restore hits K consecutive non-finite steps: the
    trainer's loop catches it, reloads the latest valid checkpoint, and
    re-enters at the restored step."""


# Persistent-NaN bound shared by both trainers: after this many
# nan-policy=restore rollbacks the run raises instead of looping — a
# deterministically-reproducing NaN must eventually surface.
MAX_NAN_ROLLBACKS = 5


class NanGuard:
    """The NaN/Inf guard's policy state machine, shared by both trainers
    (train/trainer.py and train/lm_trainer.py hold ONE implementation of
    the streak/skip/rollback rules; only snapshot placement differs).

    Policies: "off" (inactive), "abort" (raise on the first bad step),
    "skip" (drop the bad update, keep going), "restore" (skip, then
    RollbackToCheckpoint after `max_bad` consecutive bad steps).
    """

    def __init__(self, policy: str, max_bad: int = 3):
        if policy not in ("off", "abort", "skip", "restore"):
            raise ValueError(
                f"--nan-policy {policy!r}: want off|abort|skip|restore"
            )
        self.policy = policy
        self.max_bad = max_bad
        self.streak = 0   # consecutive non-finite steps
        self.skipped = 0  # dropped updates (skip/restore)

    @property
    def active(self) -> bool:
        return self.policy != "off"

    @property
    def snapshots(self) -> bool:
        """Whether the pre-step state must be snapshotted (skip/restore
        drop the bad update by reinstalling it)."""
        return self.policy in ("skip", "restore")

    def step_ok(self) -> None:
        self.streak = 0

    def bad_step(self, step: int, *, logger, metrics) -> None:
        """Record a non-finite step and apply the policy: raises
        NonFiniteLossError for abort, RollbackToCheckpoint when restore
        hits max_bad; RETURNS for a plain skip — the caller reinstalls
        its pre-step snapshot with the step counter advanced."""
        self.streak += 1
        metrics.log("fault", kind="nonfinite_step", step=step,
                    policy=self.policy, streak=self.streak)
        if self.policy == "abort":
            raise NonFiniteLossError(
                f"step {step}: non-finite loss/metrics or state "
                "(--nan-policy=abort)"
            )
        self.skipped += 1
        logger.warning(
            "step %d: non-finite update dropped (%s, streak %d)",
            step, self.policy, self.streak,
        )
        if self.policy == "restore" and self.streak >= self.max_bad:
            raise RollbackToCheckpoint


def step_is_finite(m, finite_fn, state) -> bool:
    """The guard's per-step check, shared by both trainers: every step
    metric (loss + the reference metrics) AND the whole post-update
    state (params, optimizer moments — a NaN gradient with a finite
    loss lands there) must be finite. `finite_fn` is the trainer's
    jitted all_finite; the check costs one scalar sync."""
    # Lazy on purpose: only the jax-entangled trainers call this;
    # importing faults.py itself must stay jax-free (bootstrap and
    # offline consumers load it directly).
    import jax  # mctpu: disable=MCT001

    vals = jax.device_get(m)
    for v in jax.tree.leaves(vals):
        if not np.all(np.isfinite(np.asarray(v, np.float64))):
            return False
    return bool(jax.device_get(finite_fn(state)))


def all_finite(tree):
    """Traced all-isfinite over a pytree's inexact leaves (int leaves —
    step counters — are always finite and are skipped). Trainers jit
    this once and call it per guarded step: ONE boolean comes back, so
    the guard costs a scalar sync, not a state download."""
    # Lazy on purpose — same contract as step_is_finite above.
    import jax  # mctpu: disable=MCT001
    import jax.numpy as jnp  # mctpu: disable=MCT001

    ok = jnp.asarray(True)
    for leaf in jax.tree.leaves(tree):
        if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.inexact):
            ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(leaf)))
    return ok


def supervise(attempt_fn: Callable[[int], object], *, max_restarts: int,
              logger=None, metrics=None, registry=None,
              backoff_base: float = 0.5,
              # injectable U[0,1) default: tests pass a constant
              # mctpu: disable=MCT004
              sleep=time.sleep, jitter=random.random) -> object:
    """The crash-safe training supervisor: run `attempt_fn(attempt)` and,
    on a crash, rerun it up to `max_restarts` more times.

    `attempt_fn` receives the attempt index (0 = first run) and must
    itself arrange resume-from-checkpoint for attempt > 0 (the CLI does
    this by forcing cfg.resume on retries). KeyboardInterrupt,
    SystemExit (which covers Preempted — a scheduler's eviction is
    answered by relaunch-with-resume, not an in-process retry), and
    NonFiniteLossError pass through — the operator's kill and the NaN
    guard's verdict are not faults to retry (an organic NaN replays
    deterministically from the checkpoint). Exhausted restarts re-raise
    the last crash.

    Restarts are paced with exponential backoff plus jitter
    (utils/retry.backoff_delay: backoff_base * 2^attempt * (1+U[0,1));
    backoff_base=0 disables) — an immediate-restart storm against a
    sick filesystem or coordinator just reproduces the crash faster,
    and the jitter de-synchronizes a fleet of supervisors relaunching
    into the same recovering dependency. Each restart emits a ``fault``
    obs event (kind="restart", with the delay) when a metrics sink is
    given, and bumps the ``train.restarts`` counter when an
    obs.MetricsRegistry is given — the supervisor outlives every
    attempt, so the registry is where restart totals survive the
    trainer rebuilds (`mctpu top` shows them live). `sleep`/`jitter`
    are test injection points.
    """
    last: BaseException | None = None
    for attempt in range(max_restarts + 1):
        try:
            return attempt_fn(attempt)
        except (KeyboardInterrupt, SystemExit, NonFiniteLossError):
            # The operator's kill is not a fault to retry — and neither
            # is the NaN guard's abort/rollback-exhausted verdict: an
            # organic NaN replays deterministically from the checkpoint,
            # so a restart would burn every retry reproducing it.
            raise
        except Exception as e:  # noqa: BLE001 — a supervisor catches broadly
            last = e
            if attempt >= max_restarts:
                break
            delay = backoff_delay(attempt, backoff_base, jitter)
            if logger is not None:
                logger.warning(
                    "training attempt %d crashed (%s: %s); restarting "
                    "from the latest valid checkpoint in %.2fs "
                    "(%d restart(s) left)", attempt, type(e).__name__, e,
                    delay, max_restarts - attempt,
                )
            if registry is not None:
                registry.inc("train.restarts")
            if metrics is not None:
                metrics.log("fault", kind="restart", attempt=attempt,
                            delay_s=round(delay, 4),
                            error=f"{type(e).__name__}: {e}")
            if delay > 0:
                sleep(delay)
    assert last is not None
    raise last
