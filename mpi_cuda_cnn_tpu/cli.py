"""Python CLI — the framework's main entry point.

Keeps the reference's CLI contract (4 positional IDX paths, cnn.c:408-411;
exit 100 on bad argc, exit 111 on unreadable files) while exposing every
compiled-in constant of the reference as a flag (utils/config.py). The C
driver (native/) offers the same surface for the north star's
`--device=tpu` C-binary form.

    python -m mpi_cuda_cnn_tpu train-images train-labels t10k-images t10k-labels
    python -m mpi_cuda_cnn_tpu --dataset synthetic --model lenet5_relu --epochs 3
    python -m mpi_cuda_cnn_tpu --metrics-jsonl run.jsonl ...   # telemetry sink
    python -m mpi_cuda_cnn_tpu report run.jsonl                # summary tables
    python -m mpi_cuda_cnn_tpu serve-bench --requests 32       # serving bench
    python -m mpi_cuda_cnn_tpu fleet-bench --replicas 4        # fleet storm
    python -m mpi_cuda_cnn_tpu trace run.jsonl --request 3     # lifecycle trace
    python -m mpi_cuda_cnn_tpu explain run.jsonl --worst ttft  # causal blame
    python -m mpi_cuda_cnn_tpu top run.jsonl                   # live dashboard
    python -m mpi_cuda_cnn_tpu compare base.jsonl new.jsonl    # regression gate
    python -m mpi_cuda_cnn_tpu health run.jsonl --slo slo.json # SLO verdicts
    python -m mpi_cuda_cnn_tpu lint --format json              # invariant lint
    python -m mpi_cuda_cnn_tpu replay run.jsonl --at-tick 40   # state replay
    python -m mpi_cuda_cnn_tpu diverge a.jsonl b.jsonl         # 1st divergence
    python -m mpi_cuda_cnn_tpu chaos --episodes 50             # fault search
"""

from __future__ import annotations

import dataclasses
import sys

from .data.datasets import get_dataset, load_idx_dataset
from .data.idx import IdxError
from .faults import FaultInjector, Preempted, PreemptionGuard, supervise
from .models.presets import get_model
from .obs.metrics import MetricsRegistry
from .parallel.distributed import initialize_distributed
from .train.trainer import Trainer
from .utils.config import Config, parse_args
from .utils.logging import MetricsLogger, get_logger


def _select_device(cfg: Config, log) -> bool:
    """Honor --device (the north star's `--device=cpu|tpu` switch,
    BASELINE.json). 'auto' takes whatever JAX picked."""
    import jax

    if cfg.device == "cpu":
        jax.config.update("jax_platforms", "cpu")
    elif cfg.device == "tpu":
        if all(d.platform == "cpu" for d in jax.devices()):
            log.error("--device=tpu requested but no accelerator is visible")
            return False
    elif cfg.device != "auto":
        log.error("unknown --device %r (want auto|tpu|cpu)", cfg.device)
        return False
    return True


def _fault_setup(cfg, log):
    """Validate the supervisor/fault flags up front. Returns
    (rc, injector): rc != 0 is a config error (nothing was run);
    injector is the ONE FaultInjector for the whole supervised run —
    faults fired in a crashed attempt stay fired, so a restart proves
    recovery instead of re-tripping the same crash."""
    if cfg.max_restarts > 0 and not cfg.checkpoint_dir:
        log.error("--max-restarts needs --checkpoint-dir: a restarted "
                  "attempt resumes from the latest valid checkpoint")
        return 2, None
    try:
        return 0, FaultInjector(cfg.fault_plan) if cfg.fault_plan else None
    except ValueError as e:
        log.error("bad --fault-plan: %s", e)
        return 2, None


def _supervised(cfg, log, metrics, first_trainer, make_trainer,
                registry=None):
    """Run training under the crash-safe supervisor.

    `first_trainer` was built by the caller OUTSIDE this call (so a
    construction/config error surfaces once, with the caller's own
    error handling, and is never mistaken for a mid-training crash);
    each restarted attempt rebuilds with resume forced — the
    supervisor's whole contract is continue-from-checkpoint. `registry`
    is the run-wide obs.MetricsRegistry the trainers share (restart and
    step totals survive the rebuilds). Returns (result, last_trainer);
    training exceptions propagate once restarts are exhausted."""
    trainer = first_trainer

    def attempt(n: int):
        nonlocal trainer
        if n > 0:
            trainer = make_trainer(dataclasses.replace(cfg, resume=True))
        return trainer.train()

    result = supervise(attempt, max_restarts=cfg.max_restarts,
                       logger=log, metrics=metrics, registry=registry)
    return result, trainer


def run(cfg: Config) -> int:
    log = get_logger()
    if not _select_device(cfg, log):
        return 2
    initialize_distributed()

    try:
        if cfg.dataset == "idx":
            ds = load_idx_dataset(
                "idx",
                cfg.train_images,
                cfg.train_labels,
                cfg.test_images,
                cfg.test_labels,
            )
        else:
            ds = get_dataset(cfg.dataset, data_dir=cfg.data_dir)
    except (OSError, IdxError, TypeError) as e:
        # The reference exits 111 on any file problem (cnn.c:432,440).
        log.error("data load failed: %s", e)
        return 111
    except (KeyError, ValueError) as e:
        log.error("bad dataset config: %s", e)
        return 2

    try:
        model = get_model(cfg.model, input_shape=ds.input_shape)
    except KeyError as e:
        log.error("%s", e)
        return 2
    log.info("model=%s dataset=%s input=%s", model.name, ds.name, ds.input_shape)
    rc, faults = _fault_setup(cfg, log)
    if rc:
        return rc
    # The context manager closes the JSONL sink even when the trainer
    # raises mid-run — the records written so far must survive.
    # The preemption guard hooks SIGTERM/SIGINT for the whole run
    # (ISSUE 5): a scheduler's eviction notice finishes the in-flight
    # step, snapshots, and exits EXIT_PREEMPTED instead of dying
    # mid-write; uninstalled on the way out so embedding callers (tests,
    # the C ABI) never inherit our handlers.
    with MetricsLogger(path=cfg.metrics_jsonl) as metrics, \
            PreemptionGuard() as guard:
        # ONE runtime registry for the whole (possibly supervised) run:
        # restart/step totals must survive per-attempt trainer rebuilds.
        registry = MetricsRegistry()

        def make_trainer(c):
            return Trainer(model, ds, c, metrics=metrics, faults=faults,
                           preempt=guard, registry=registry)

        # First construction outside the retry loop AND outside
        # _supervised: a config error (bad nan-policy, indivisible
        # batch, ...) can never succeed on retry — it fails once, fast
        # — while mid-training errors propagate with their tracebacks.
        try:
            first = make_trainer(cfg)
        except ValueError as e:
            log.error("trainer setup failed: %s", e)
            return 2
        try:
            result, _ = _supervised(cfg, log, metrics, first, make_trainer,
                                    registry=registry)
        except Preempted as e:
            if e.resumable:
                log.warning("run preempted (%s); exiting %d — relaunch "
                            "with --resume to continue", e, e.code)
            else:
                log.warning("run preempted (%s) with no checkpoint to "
                            "resume from; exiting %d", e, e.code)
            return int(e.code)
    log.info(
        "done: epochs=%d acc=%.4f mean_step=%.3fms",
        result.epochs_run,
        result.test_accuracy,
        result.mean_step_ms,
    )
    return 0


def run_lm(argv: list[str]) -> int:
    """The `lm` subcommand: train the transformer LM (long-context
    path — flash attention, data/seq meshes, MoE)."""
    from .train.lm_trainer import LMTrainer
    from .utils.config import parse_lm_args

    cfg = parse_lm_args(argv)
    log = get_logger()
    if not _select_device(cfg, log):
        return 2
    rc, faults = _fault_setup(cfg, log)
    if rc:
        return rc
    initialize_distributed()
    with MetricsLogger(path=cfg.metrics_jsonl) as metrics, \
            PreemptionGuard() as guard:
        registry = MetricsRegistry()  # shared across supervised attempts

        def make_trainer(c):
            return LMTrainer(c, metrics=metrics, faults=faults,
                             preempt=guard, registry=registry)

        # First construction outside _supervised: setup errors map to
        # rc=2 exactly once; mid-training errors keep their tracebacks.
        try:
            first = make_trainer(cfg)
        except (OSError, ValueError) as e:
            log.error("lm setup failed: %s", e)
            return 2
        log.info(
            "lm model=d%dx%d h%d seq=%d vocab=%d moe=%d mesh=%s attn=%s",
            cfg.dim, cfg.depth, cfg.heads, cfg.seq_len, first.model.vocab,
            cfg.moe_experts, dict(first.mesh.shape), first.attn_impl,
        )
        try:
            result, trainer = _supervised(cfg, log, metrics, first,
                                          make_trainer, registry=registry)
        except Preempted as e:
            if e.resumable:
                log.warning("run preempted (%s); exiting %d — relaunch "
                            "with --resume to continue", e, e.code)
            else:
                log.warning("run preempted (%s) with no checkpoint to "
                            "resume from; exiting %d", e, e.code)
            return int(e.code)
        log.info(
            "done: steps=%d eval_ppl=%.3f tokens/s=%.0f",
            result.steps_run, result.eval_ppl, result.tokens_per_s,
        )
        if cfg.sample_tokens:
            _, cont = trainer.sample(
                cfg.sample_tokens, temperature=cfg.sample_temperature,
                seed=cfg.seed,
            )
            # Char-level corpora (self / file / synthetic-mod-251) decode as
            # bytes; anything out of byte range prints as escapes.
            text = bytes(int(t) & 0xFF for t in cont)
            log.info("sample (%d tokens): %r", cfg.sample_tokens, text)
    return 0


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if argv and argv[0] == "train":
        # Explicit alias for the default command, so the supervisor form
        # reads naturally: `mctpu train --max-restarts 3 ...`.
        argv = argv[1:]
    if argv and argv[0] == "lm":
        return run_lm(argv[1:])
    if argv and argv[0] == "report":
        # Offline: summarize a metrics JSONL run (obs.report) — no jax
        # device init, safe on any machine.
        from .obs.report import report_main

        return report_main(argv[1:])
    if argv and argv[0] == "trace":
        # Offline: reconstruct per-request lifecycles from a serving
        # run's tick records (obs.timeline) — jax-free.
        from .obs.timeline import trace_main

        return trace_main(argv[1:])
    if argv and argv[0] == "explain":
        # Offline: causal critical-path attribution — per-request blame
        # trees that sum exactly to end-to-end latency, aggregate blame
        # and top-blocker tables (obs.causal, ISSUE 11) — jax-free.
        from .obs.causal import explain_main

        return explain_main(argv[1:])
    if argv and argv[0] == "replay":
        # Offline: deterministic flight-recorder replay — reconstruct
        # the full serving state from a run's tick trail, cross-checking
        # the stamped per-tick state digests (obs.replay, ISSUE 15) —
        # jax-free.
        from .obs.replay import replay_main

        return replay_main(argv[1:])
    if argv and argv[0] == "diverge":
        # Offline: first-divergence localization between two
        # identical-seed trails — the determinism gates' forensic tool
        # (obs.diverge, ISSUE 15) — jax-free.
        from .obs.diverge import diverge_main

        return diverge_main(argv[1:])
    if argv and argv[0] == "top":
        # Live dashboard: tail (or replay) a metrics JSONL and render
        # the engine/trainer gauges in place (obs.top) — jax-free.
        from .obs.top import top_main

        return top_main(argv[1:])
    if argv and argv[0] == "compare":
        # Perf-regression gate: compare run files / bench captures on
        # named metrics, exit 1 on regression (obs.regress) — jax-free.
        from .obs.regress import compare_main

        return compare_main(argv[1:])
    if argv and argv[0] == "autosize":
        # Offline capacity search: sweep candidate fleet topologies at
        # a fixed chip budget as seeded SimCompute storms, score by
        # SLO-attained goodput, emit a deterministic goodput frontier +
        # recommendation; --seed-from prunes the sweep from a finished
        # run's blame profile (obs.autosize, ISSUE 16) — jax-free.
        from .obs.autosize import autosize_main

        return autosize_main(argv[1:])
    if argv and argv[0] == "chaos":
        # Seeded fault-schedule search: sample multi-fault plans from
        # the live faults.SITES registry, run each through the fleet
        # storm under a global invariant oracle (terminal-exactly-once,
        # closed-form outputs, blame conservation, pool/tier clean
        # exit, zero-drift replay, bitwise re-run), ddmin-shrink any
        # violation to a one-line --fault-plan repro (chaos/, ISSUE 19)
        # — jax-free.
        from .chaos.cli import chaos_main

        return chaos_main(argv[1:])
    if argv and argv[0] == "health":
        # SLO health gate: per-tenant verdict table + alert replay for
        # a finished run, exit 1 on violation (obs.health, ISSUE 8) —
        # jax-free.
        from .obs.health import health_main

        return health_main(argv[1:])
    if argv and argv[0] == "lint":
        # Static analyzer: the framework-invariant rules MCT001-MCT007
        # over the repo's own contracts (analysis/, ISSUE 10) —
        # jax-free, gates CI on exit code.
        from .analysis.cli import lint_main

        return lint_main(argv[1:])
    if argv and argv[0] == "serve-bench":
        # Serving bench: paged-KV continuous batching vs static
        # batching under Poisson arrivals (serve/bench.py).
        from .serve.bench import serve_bench_main

        return serve_bench_main(argv[1:])
    if argv and argv[0] == "fleet-bench":
        # Fleet bench: N replicas behind the failure-aware router under
        # a seeded Poisson storm with injected replica crashes/joins —
        # deterministic under FakeClock (serve/fleet.py, ISSUE 7).
        from .serve.bench import fleet_bench_main

        return fleet_bench_main(argv[1:])
    cfg = parse_args(argv)
    return run(cfg)


if __name__ == "__main__":
    sys.exit(main())
