"""Batched speculative decoding policy — the jax-free half (ISSUE 14).

The two proven speculative forms (Leviathan et al.'s model-draft
rejection sampling, Saxena's draft-free prompt lookup — PAPERS.md) lived
only in models/generate.py at B=1, while the serving engine decoded one
token per slot per tick. This module is the policy layer that marries
them to the continuous-batching engine: per-slot k-token PROPOSAL plus
ONE batched verify block per tick, with greedy acceptance committing
anywhere from 1 to k tokens per slot per round.

Division of labor (the scheduler/engine split, applied again):

- THIS module is host-side, numpy-only, and deliberately jax-free
  (`mctpu lint` MCT001): proposal (prompt lookup over the request's own
  committed context), the greedy acceptance law, and the round scaffold
  `run_round` that engine.run and fleet.ReplicaCore.step both drive —
  one implementation, two drivers, so the engine and the fleet's sim
  storms can never drift.
- The VERIFY forward is the caller's: engine.PagedEngine.run_spec_tick
  (one jitted paged_forward over every slot's k candidate rows — the
  same token_forward/attend_kv stack every other decode surface shares)
  or fleet.SimCompute.verify (the pure token mix, so the 10^5 storm
  speculates with scheduling real and devices absent).
- Page accounting is scheduler.py's: grow_for_decode(spec_k=) extends
  each decoding slot OPPORTUNISTICALLY toward its speculative width
  (never preempting live work for speculation — a dry pool degrades the
  width toward 1, which is exactly spec-off behavior), and commit_spec
  rolls back pages holding only rejected-draft rows, so a rejected
  token's KV is never live.

The acceptance law here and models/generate._accept_and_emit are the
SAME law in two dialects (numpy host loop vs jitted lax); the no-drift
gate is tests/test_spec_serve.py's randomized equivalence pin between
`accept_len` and the jitted core. At temperature 0 the emitted stream
is the target's own greedy continuation for ANY proposer (the Leviathan
exactness argument) — which is what makes the engine's spec-on outputs
bitwise-equal to spec-off per request, the ISSUE 14 acceptance gate.
T>0 rejection sampling stays a generate.py (B=1) surface: the engine
samples greedily by design, and the distribution-equality tests in
tests/test_spec_sampling.py pin the shared law's sampling form.
"""

from __future__ import annotations

import numpy as np

# The serving spec surface: "off" (one token per slot per tick),
# "lookup" (draft-free prompt lookup — the agentic/template-traffic
# form, and the fleet storms' only form), "draft" (a cheap draft model
# behind the same proposer interface — engine.DraftProposer).
SPEC_MODES = ("off", "lookup", "draft")

_EMPTY = np.empty(0, np.int32)


def empty_spec_fields() -> dict:
    """The zero-valued speculative summary block a spec-off run stamps,
    so every gated metric exists in every run (the fleet/spec-gate
    contract, same as empty_prefix_fields)."""
    return {"spec_rounds": 0, "spec_proposed": 0, "spec_accepted": 0}


def accept_len(u: np.ndarray, y: np.ndarray) -> int:
    """THE greedy speculative acceptance law, host dialect: u holds the
    w verify inputs (u[0] = the slot's current committed token, u[1:]
    the proposals), y the target's per-row greedy picks (y[i] = argmax
    of the logits AFTER input i). Accept the longest prefix where
    proposal u[i+1] equals the target's own pick y[i]; the emitted
    count j = 1 + that prefix (row j-1 is the first-reject replacement
    or the fully-accepted bonus row), exactly
    models/generate._accept_and_emit's j — the randomized equivalence
    test pins the two dialects against drift."""
    w = len(u)
    j = 1
    while j < w and u[j] == y[j - 1]:
        j += 1
    return j


def lookup_propose(ctx: np.ndarray, n_props: int, ngram: int = 2) -> np.ndarray:
    """Draft-free prompt-lookup proposal over the request's committed
    context (prompt + emitted tokens): the n_props tokens that followed
    the MOST RECENT earlier occurrence of the context's current
    ngram-token tail. No earlier occurrence -> repeat the current token
    (acceptance just collapses toward 1, never an error); a match too
    close to the end pads by repeating the last available token. Same
    policy as generate._compiled_lookup_run's propose, in the host
    dialect the serving engine consumes per slot per round — proposals
    move SPEED only, never the emitted law, so the two dialects'
    clamping details are each documented, not mirrored bit-for-bit."""
    if n_props <= 0:
        return _EMPTY
    ctx = np.asarray(ctx, np.int32).reshape(-1)
    n = ctx.size
    cur = ctx[-1]
    if n <= ngram:
        return np.full(n_props, cur, np.int32)
    # Candidate match ends j in [ngram-1, n-2]: the ngram ending at j
    # equals the ngram ending at n-1 (the tail itself is excluded).
    # Pure slice comparisons — this runs once per slot per round in
    # the storm hot loop, so no index arrays are materialized.
    ok = ctx[ngram - 1 : n - 1] == cur
    for d in range(1, ngram):
        ok &= ctx[ngram - 1 - d : n - 1 - d] == ctx[n - 1 - d]
    rev = ok[::-1]
    i = int(np.argmax(rev))       # first True from the END = most recent
    if not rev[i]:
        return np.full(n_props, cur, np.int32)
    j = (ngram - 1) + (ok.size - 1 - i)
    props = ctx[j + 1 : j + 1 + n_props]
    if props.size < n_props:
        pad_tok = props[-1] if props.size else cur
        props = np.concatenate(
            [props, np.full(n_props - props.size, pad_tok, np.int32)]
        )
    return props.astype(np.int32)


class LookupProposer:
    """The draft-free per-slot proposer (Saxena's prompt lookup):
    stateless, host-side, jax-free — the form the fleet's sim storms
    and the engine's default --spec lookup both run."""

    def __init__(self, ngram: int = 2):
        if ngram < 1:
            raise ValueError(f"ngram must be >= 1 (got {ngram})")
        self.ngram = ngram

    def propose(self, ctx: np.ndarray, n_props: int) -> np.ndarray:
        return lookup_propose(ctx, n_props, self.ngram)

    def propose_batch(self, ctxs, n_props):
        """The batched proposer interface run_round drives (the draft
        proposer genuinely batches its device steps; lookup is a pure
        host loop either way)."""
        return [lookup_propose(c, n, self.ngram)
                for c, n in zip(ctxs, n_props)]


def context_tokens(req) -> np.ndarray:
    """The request's committed context (prompt + emitted tokens) as one
    int32 array — the lookup corpus AND the draft window source.

    Cached incrementally on the request (storm hot loop: rebuilding
    prompt+out from scratch every round made the context copy the
    dominant proposal cost): a private growing buffer appends only the
    tokens emitted since the last call, and any shrink of the account
    (a fleet discard re-dispatch clears `out`) rebuilds from scratch.
    Callers treat the returned view as read-only."""
    out = req.out
    n = req.prompt.size + len(out)
    buf = getattr(req, "_spec_ctx", None)
    filled = getattr(req, "_spec_ctx_fill", 0)
    if buf is None or buf.shape[0] < n or filled > n:
        cap = max(2 * n, 64)
        buf = np.empty(cap, np.int32)
        buf[: req.prompt.size] = req.prompt
        filled = req.prompt.size
        req._spec_ctx = buf
    if filled < n:
        buf[filled:n] = out[filled - req.prompt.size :]
    req._spec_ctx_fill = n
    return buf[:n]


def run_round(dslots, widths, proposer, verify):
    """One speculative round over the tick's decoding slots — THE
    scaffold engine.run and fleet.ReplicaCore.step share:

    1. per slot, propose width-1 draft tokens from its committed
       context and assemble the verify inputs u = [current token,
       proposals] (a width-1 slot verifies just its current token —
       exactly the spec-off tick for that slot);
    2. `verify(rounds)` scores ALL slots' inputs in ONE batched forward
       (rounds: [(slot, u, width)]) and returns each slot's per-row
       greedy picks;
    3. greedy acceptance (`accept_len`) per slot.

    Returns [(slot, width, j, emitted tokens)] — j in [1, width] tokens
    commit; the caller emits, commits cached via
    scheduler.commit_spec (which rolls the rejected-draft pages back),
    and finishes done requests.

    A proposer declaring `needs_slots = True` (the paged draft cache,
    ISSUE 17) carries per-slot KV state: it receives the slot handles
    alongside the contexts, and EVERY slot's real context even at
    n == 0 (a zero-proposal slot still needs its catch-up rows so the
    draft cache tracks the committed stream — stateless proposers keep
    the empty-context fast path).
    """
    need = [w - 1 for w in widths]
    if getattr(proposer, "needs_slots", False):
        ctxs = [context_tokens(s.req) for s in dslots]
        props_list = proposer.propose_batch(ctxs, need, dslots)
    else:
        ctxs = [context_tokens(s.req) if n > 0 else _EMPTY
                for s, n in zip(dslots, need)]
        props_list = proposer.propose_batch(ctxs, need)
    rounds = []
    for s, w, props in zip(dslots, widths, props_list):
        u = np.empty(w, np.int32)
        u[0] = s.req.out[-1]
        u[1:] = props
        rounds.append((s, u, w))
    ys = verify(rounds)
    out = []
    for (s, u, w), y in zip(rounds, ys):
        j = accept_len(u, y)
        out.append((s, w, j, [int(y[i]) for i in range(j)]))
    return out
