"""`mctpu serve-bench` — static vs continuous batching on one chip.

Drives the PagedEngine with a Poisson-arrival workload of mixed
prompt/output lengths (the serving regime the schedulers differ on:
identical lengths make static batching look fine) and reports, per
mode: throughput, TTFT p50/p99, per-output-token latency p50/p99,
decode-tick and preemption counts. Per-request records go through the
obs JSONL schema (`request` events + one `serve` summary event per
mode) so `mctpu report` renders the serving tables.

The workload is seeded and regenerated identically per mode — the two
schedulers see the same requests, arrivals, and (greedy) token budget;
only the schedule differs. Weights are randomly initialized: scheduling
costs do not depend on what the tokens say.

    python -m mpi_cuda_cnn_tpu serve-bench --requests 32 --rate 50
    python scripts/bench_serve.py --mode continuous --cache-dtype int8
"""

from __future__ import annotations

import argparse
import json
import sys

import numpy as np


def make_workload(*, n: int, vocab: int, prompt_min: int, prompt_max: int,
                  out_min: int, out_max: int, rate: float, seed: int,
                  deadline_s: float = 0.0):
    """n seeded requests: uniform prompt/output lengths in the given
    ranges, Poisson arrivals at `rate` req/s (exponential gaps; rate 0
    = everything arrives at t=0). deadline_s > 0 gives every request an
    absolute deadline of arrival + deadline_s. Regenerating with the
    same seed gives an identical workload — the cross-mode comparison
    contract."""
    from .scheduler import Request

    rng = np.random.default_rng(seed)
    t = 0.0
    reqs = []
    for i in range(n):
        if rate > 0:
            t += float(rng.exponential(1.0 / rate))
        plen = int(rng.integers(prompt_min, prompt_max + 1))
        olen = int(rng.integers(out_min, out_max + 1))
        prompt = rng.integers(0, vocab, (plen,)).astype(np.int32)
        reqs.append(Request(rid=i, prompt=prompt, max_new_tokens=olen,
                            arrival=t,
                            deadline=t + deadline_s if deadline_s > 0
                            else None))
    return reqs


def serve_bench_main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="mctpu serve-bench",
        description="Serving bench: paged-KV continuous batching vs "
                    "static batching under Poisson arrivals "
                    "(throughput, TTFT, p50/p99 per-token latency).",
    )
    ap.add_argument("--dim", type=int, default=128)
    ap.add_argument("--depth", type=int, default=2)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--kv-heads", type=int, default=0,
                    help="0 = MHA; fewer = GQA/MQA (smaller pages)")
    ap.add_argument("--vocab", type=int, default=512)
    ap.add_argument("--max-seq", type=int, default=512)
    ap.add_argument("--slots", type=int, default=4,
                    help="decode batch rows (in-flight sequences)")
    ap.add_argument("--page-size", type=int, default=16,
                    help="tokens per KV page")
    ap.add_argument("--pages", type=int, default=0,
                    help="global page-pool size incl. the scratch page "
                         "(0 = size for slots full-length sequences — "
                         "ample; shrink it to exercise preemption)")
    ap.add_argument("--prefill-chunk", type=int, default=32)
    ap.add_argument("--cache-dtype", default="float32",
                    choices=["float32", "bfloat16", "int8"])
    ap.add_argument("--requests", type=int, default=24)
    ap.add_argument("--prompt-min", type=int, default=8)
    ap.add_argument("--prompt-max", type=int, default=96)
    ap.add_argument("--out-min", type=int, default=8)
    ap.add_argument("--out-max", type=int, default=96)
    ap.add_argument("--rate", type=float, default=0.0,
                    help="Poisson arrival rate, requests/s (0 = all at "
                         "t=0: the pure-throughput comparison)")
    ap.add_argument("--mode", default="both",
                    choices=["both", "static", "continuous"])
    ap.add_argument("--deadline-ms", type=float, default=0.0,
                    help="per-request deadline (arrival + this many ms; "
                         "0 = none): expired queued requests are "
                         "dropped, in-flight ones aborted with their "
                         "pages returned")
    ap.add_argument("--max-queue", type=int, default=0,
                    help="bound on ARRIVED-but-waiting requests; "
                         "arrivals past it are rejected with a terminal "
                         "status (backpressure; 0 = unbounded)")
    ap.add_argument("--watchdog-ms", type=float, default=0.0,
                    help="tick watchdog: count + record engine "
                         "iterations slower than this (0 = off)")
    ap.add_argument("--fault-plan", default=None,
                    help="deterministic fault injection, e.g. "
                         "'squeeze@serve.tick:5?pages=4&ticks=8;"
                         "slow@serve.tick:9?s=0.2' (faults.parse_plan)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--metrics-jsonl", default=None,
                    help="append per-request obs records here")
    ap.add_argument("--device", default="auto",
                    choices=["auto", "tpu", "cpu"])
    args = ap.parse_args(argv)

    import jax

    if args.device == "cpu":
        jax.config.update("jax_platforms", "cpu")
    elif args.device == "tpu" and jax.default_backend() != "tpu":
        print("--device=tpu requested but the backend is "
              f"{jax.default_backend()}", file=sys.stderr)
        return 1

    from ..models.transformer import TransformerLM
    from ..obs.metrics import MetricsRegistry
    from ..utils.logging import MetricsLogger
    from .engine import PagedEngine
    from .paged_cache import pages_for

    if args.prompt_max + args.out_max > args.max_seq:
        print(f"prompt {args.prompt_max} + out {args.out_max} exceeds "
              f"--max-seq {args.max_seq}", file=sys.stderr)
        return 1
    model = TransformerLM(
        vocab=args.vocab, dim=args.dim, heads=args.heads, depth=args.depth,
        max_seq=args.max_seq, kv_heads=args.kv_heads,
    )
    params = model.init(jax.random.key(args.seed))
    max_len = args.prompt_max + args.out_max
    pages = args.pages or args.slots * pages_for(max_len, args.page_size) + 1
    engine = PagedEngine(
        model, params, slots=args.slots, num_pages=pages,
        page_size=args.page_size, prefill_chunk=args.prefill_chunk,
        cache_dtype=args.cache_dtype, max_len=max_len,
    )
    modes = (["static", "continuous"] if args.mode == "both"
             else [args.mode])
    workload_kw = dict(
        n=args.requests, vocab=args.vocab, prompt_min=args.prompt_min,
        prompt_max=args.prompt_max, out_min=args.out_min,
        out_max=args.out_max, rate=args.rate, seed=args.seed,
        deadline_s=args.deadline_ms / 1e3,
    )
    run_kw = dict(
        max_queue=args.max_queue or None,
        watchdog_s=args.watchdog_ms / 1e3,
    )
    summaries = {}
    with MetricsLogger(path=args.metrics_jsonl, echo=False) as metrics:
        # Warm both compiled programs (engine-level: the same two serve
        # every mode) on one throwaway request, so no mode pays
        # compilation inside its latencies.
        engine.run(make_workload(**{**workload_kw, "n": 1, "rate": 0.0,
                                    "deadline_s": 0.0}),
                   mode=modes[0])
        for mode in modes:
            faults = None
            if args.fault_plan:
                # Fresh injector per mode: both modes see the identical
                # fault schedule (the cross-mode comparison contract).
                from ..faults import FaultInjector

                faults = FaultInjector(args.fault_plan)
            # The runtime metrics layer (ISSUE 6): one registry per mode
            # (cross-mode aggregation would blend the two schedules) and
            # tick records streamed to the JSONL sink AS THEY HAPPEN —
            # `mctpu top run.jsonl` tails the file live; `mctpu trace`
            # reconstructs lifecycles from the same records afterwards.
            registry = MetricsRegistry()
            tick_sink = None
            if metrics.jsonl_enabled:
                def tick_sink(rec, _snap_every=64):
                    metrics.log("tick", **rec)
                    if (rec["tick"] + 1) % _snap_every == 0:
                        registry.emit(metrics, mode=rec["mode"])
            result = engine.run(make_workload(**workload_kw), mode=mode,
                                faults=faults, registry=registry,
                                tick_sink=tick_sink, **run_kw)
            s = result.summary()
            summaries[mode] = s
            registry.set("serve.tokens_per_s", s["tokens_per_s"])
            registry.emit(metrics, mode=mode, final=True)
            for rec in result.request_records():
                metrics.log("request", **rec)
            for ev in result.events:
                metrics.log("fault", **{"mode": mode, **ev})
            metrics.log("serve", **{
                "bench": "serve", "backend": jax.default_backend(),
                "cache_dtype": args.cache_dtype, "rate": args.rate,
                "slots": args.slots, "page_size": args.page_size,
                "pages": pages, **s,
            })
            print(json.dumps({"bench": "serve", "backend":
                              jax.default_backend(),
                              "cache_dtype": args.cache_dtype, **s}))
    if len(summaries) == 2:
        st, ct = summaries["static"], summaries["continuous"]
        print(json.dumps({
            "metric": "serve_tokens_per_s",
            "value": ct["tokens_per_s"],
            "unit": "tokens/s",
            "static_tokens_per_s": st["tokens_per_s"],
            "speedup": round(ct["tokens_per_s"] / max(st["tokens_per_s"],
                                                      1e-9), 3),
            "decode_ticks": {"static": st["decode_ticks"],
                             "continuous": ct["decode_ticks"]},
            "ttft_p99_ms": {"static": st["ttft_p99_ms"],
                            "continuous": ct["ttft_p99_ms"]},
        }))
    return 0


if __name__ == "__main__":
    sys.exit(serve_bench_main())
