"""`mctpu serve-bench` / `mctpu fleet-bench` — serving benchmarks.

Drives the PagedEngine with a Poisson-arrival workload of mixed
prompt/output lengths (the serving regime the schedulers differ on:
identical lengths make static batching look fine) and reports, per
mode: throughput, TTFT p50/p99, per-output-token latency p50/p99,
decode-tick and preemption counts. Per-request records go through the
obs JSONL schema (`request` events + one `serve` summary event per
mode) so `mctpu report` renders the serving tables.

The workload is seeded and regenerated identically per mode — the two
schedulers see the same requests, arrivals, and (greedy) token budget;
only the schedule differs. Weights are randomly initialized: scheduling
costs do not depend on what the tokens say.

    python -m mpi_cuda_cnn_tpu serve-bench --requests 32 --rate 50
    python scripts/bench_serve.py --mode continuous --cache-dtype int8

`fleet-bench` (ISSUE 7) drives serve/fleet.py instead: N replicas
behind the router on one FakeClock, a seeded Poisson storm, optional
injected replica crashes/joins/leaves — the determinism acceptance
(two identical-seed runs bitwise-equal in dispatch trace and
per-status counts) is what CI's fleet gate compares.

    python -m mpi_cuda_cnn_tpu fleet-bench --replicas 4 --requests 1000
    python scripts/bench_fleet.py --fault-plan \
        'replica_crash@fleet.tick:40?replica=1'
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


def _fault_plan_arg(surface: str):
    """--fault-plan argparse type: grammar + hook-site/kind validation
    at parse time (ISSUE 7 satellite) — `replica_crash@fleet.tick` on
    plain serve-bench would silently never fire; it errors here."""
    from ..faults import fault_plan_arg

    return fault_plan_arg(surface)


def _heavy_tail_len(lrng, lo: int, hi: int) -> int:
    """One lognormal length draw clipped to [lo, hi]: median at the
    geometric midpoint, sigma a quarter of the log-range — most mass
    near the low end with a heavy tail that piles up at the clip, the
    production shape (Splitwise) uniform mixes miss."""
    if hi <= lo:
        return lo
    mu = 0.5 * (np.log(lo) + np.log(hi))
    sigma = (np.log(hi) - np.log(lo)) / 4.0
    v = int(round(float(lrng.lognormal(mu, sigma))))
    return min(max(v, lo), hi)


def make_workload(*, n: int, vocab: int, prompt_min: int, prompt_max: int,
                  out_min: int, out_max: int, rate: float, seed: int,
                  deadline_s: float = 0.0, tenants: int = 0,
                  prefix_mix: float = 0.0, prefix_pool: int = 4,
                  len_dist: str = "uniform", templates: int = 0):
    """n seeded requests: uniform prompt/output lengths in the given
    ranges, Poisson arrivals at `rate` req/s (exponential gaps; rate 0
    = everything arrives at t=0). deadline_s > 0 gives every request an
    absolute deadline of arrival + deadline_s. Regenerating with the
    same seed gives an identical workload — the cross-mode comparison
    contract.

    len_dist "lognormal" (ROADMAP item 4 / ISSUE 16) draws prompt and
    output lengths from a heavy-tail lognormal clipped to the same
    ranges instead of uniform. The draws come from a SEPARATE (seed, 3)
    spawn — the same isolation trick the tenant/prefix streams use —
    so the default uniform stream is bitwise-unchanged (every committed
    baseline and pinned CRC stays valid), and tenant labels stay
    identical across the two mixes (the tenant stream never moves).

    tenants > 0 tags each request with a seeded tenant draw over
    "t0".."t{tenants-1}" (ISSUE 8's multi-tenant traffic mix). The
    labels come from a SEPARATE generator ((seed, 1) spawn), so the
    prompt/length/arrival stream is bitwise-identical with tagging on
    or off — committed baselines and every pinned tick count stay
    valid, and the same seed always maps request i to the same tenant.

    prefix_mix > 0 (ISSUE 9) makes that fraction of requests share
    template prefixes: each sharing request's prompt starts with one of
    `prefix_pool` fixed seeded templates, keeping only its last ~1/4 as
    a unique suffix — the system/template-prefix regime prefix sharing
    exists for (varying lengths hit the tree at different depths, so
    COW branching is exercised too). All prefix decisions come from a
    (seed, 2) spawn and OVERWRITE an already-drawn prompt, so lengths,
    arrivals, and tenant labels are bitwise-identical at any mix.

    templates > 0 (ISSUE 17) overrides prefix_pool with an explicitly
    sized template WORKING SET whose content comes from a SEPARATE
    (seed, 4) spawn — the --len-dist precedent again, so the default
    (templates=0) stream is bitwise-unchanged and every pinned workload
    CRC stays valid. Sizing the working set past the device page pool
    is what makes the host-tier spill/readmit story measurable: more
    templates than HBM retains forces LRU reclaim between hits."""
    from .scheduler import Request

    if len_dist not in ("uniform", "lognormal"):
        raise ValueError(f"len_dist {len_dist!r}: want uniform or "
                         "lognormal")
    rng = np.random.default_rng(seed)
    trng = np.random.default_rng([seed, 1])
    prng = np.random.default_rng([seed, 2])
    lrng = (np.random.default_rng([seed, 3])
            if len_dist == "lognormal" else None)
    if templates > 0:
        wrng = np.random.default_rng([seed, 4])
        pool_n = templates
        tmpl_rng = wrng
    else:
        pool_n = prefix_pool
        tmpl_rng = prng
    templates = [tmpl_rng.integers(0, vocab, (prompt_max,)).astype(np.int32)
                 for _ in range(pool_n)] if prefix_mix > 0 else []
    t = 0.0
    reqs = []
    for i in range(n):
        if rate > 0:
            t += float(rng.exponential(1.0 / rate))
        if lrng is None:
            plen = int(rng.integers(prompt_min, prompt_max + 1))
            olen = int(rng.integers(out_min, out_max + 1))
        else:
            plen = _heavy_tail_len(lrng, prompt_min, prompt_max)
            olen = _heavy_tail_len(lrng, out_min, out_max)
        prompt = rng.integers(0, vocab, (plen,)).astype(np.int32)
        tenant = (f"t{int(trng.integers(0, tenants))}" if tenants > 0
                  else None)
        if templates and float(prng.random()) < prefix_mix:
            k = int(prng.integers(0, pool_n))
            shared = plen - max(1, plen // 4)
            if shared > 0:
                prompt = np.concatenate(
                    [templates[k][:shared], prompt[shared:]])
        reqs.append(Request(rid=i, prompt=prompt, max_new_tokens=olen,
                            arrival=t,
                            deadline=t + deadline_s if deadline_s > 0
                            else None, tenant=tenant))
    return reqs


def load_trace(path: str) -> list[dict]:
    """Read the request GEOMETRY out of a finished run's metrics JSONL
    (ROADMAP item 4: trace-driven replay). Every `request` event
    carries the full arrival shape — id, prompt_tokens,
    max_new_tokens, arrival_s, tenant — which is exactly what a
    workload is to a scheduler. Multi-mode runs (serve-bench --mode
    both) record the same regenerated workload once per mode, so the
    FIRST record per id wins; rows come back in arrival order."""
    rows: dict[int, dict] = {}
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(f"--trace {path}: bad JSONL line: {e}")
            if rec.get("event") != "request":
                continue
            rid = rec.get("id")
            if rid is None or rid in rows:
                continue
            try:
                rows[rid] = {
                    "id": int(rid),
                    "prompt_tokens": int(rec["prompt_tokens"]),
                    "max_new_tokens": int(rec["max_new_tokens"]),
                    "arrival_s": float(rec["arrival_s"]),
                    "tenant": rec.get("tenant"),
                }
            except (KeyError, TypeError, ValueError) as e:
                raise ValueError(
                    f"--trace {path}: request record for id {rid!r} is "
                    f"missing workload geometry ({e})")
    if not rows:
        raise ValueError(f"--trace {path}: no request records — want a "
                         "metrics JSONL from a finished serve-bench / "
                         "fleet-bench run")
    return sorted(rows.values(),
                  key=lambda r: (r["arrival_s"], r["id"]))


def requests_from_trace(rows: list[dict], *, vocab: int, seed: int,
                        deadline_s: float = 0.0):
    """Fresh Request objects from trace geometry — called once per
    mode, like make_workload, because the schedulers consume requests
    in place. Arrival times, token budgets, ids, and tenant labels are
    the recorded ones bit-for-bit; prompt CONTENT is synthesized per
    id from its own seeded spawn (records do not carry tokens), so the
    replay reproduces scheduling pressure, not token identity."""
    from .scheduler import Request

    reqs = []
    for row in rows:
        rng = np.random.default_rng([seed, 5, row["id"]])
        prompt = rng.integers(0, vocab,
                              (row["prompt_tokens"],)).astype(np.int32)
        reqs.append(Request(
            rid=row["id"], prompt=prompt,
            max_new_tokens=row["max_new_tokens"],
            arrival=row["arrival_s"],
            deadline=(row["arrival_s"] + deadline_s if deadline_s > 0
                      else None),
            tenant=row["tenant"]))
    return reqs


def apply_trace_geometry(args, rows: list[dict]) -> None:
    """Size the bench to the trace: request count and prompt/output
    ranges come FROM the recorded geometry (the pool/max_len sizing
    flags keep their meaning; a trace longer than --max-seq still
    errors through the normal check)."""
    args.requests = len(rows)
    args.prompt_min = min(r["prompt_tokens"] for r in rows)
    args.prompt_max = max(r["prompt_tokens"] for r in rows)
    args.out_min = min(r["max_new_tokens"] for r in rows)
    args.out_max = max(r["max_new_tokens"] for r in rows)


def parse_turns_dist(spec: str):
    """`--turns-dist` grammar (ISSUE 18): `uniform:LO-HI` draws each
    session's turn count uniformly in [LO, HI]; `geometric:P` draws
    1 + Geometric(P) — most conversations short, a heavy tail of long
    ones. Returns the draw(rng) callable."""
    kind, sep, body = spec.partition(":")
    if sep and kind == "uniform":
        lo_s, dash, hi_s = body.partition("-")
        try:
            lo, hi = int(lo_s), int(hi_s)
        except ValueError:
            lo = hi = 0
        if dash and 1 <= lo <= hi:
            return lambda rng: int(rng.integers(lo, hi + 1))
        raise ValueError(
            f"turns-dist {spec!r}: uniform wants LO-HI with "
            "1 <= LO <= HI")
    if sep and kind == "geometric":
        try:
            p = float(body)
        except ValueError:
            p = 0.0
        if 0.0 < p <= 1.0:
            return lambda rng: int(rng.geometric(p))
        raise ValueError(
            f"turns-dist {spec!r}: geometric wants 0 < P <= 1")
    raise ValueError(
        f"turns-dist {spec!r}: want 'uniform:LO-HI' or 'geometric:P'")


def add_session_turns(reqs, *, turns_dist: str, turn_gap_s: float,
                      vocab: int, out_min: int, out_max: int,
                      max_len: int, seed: int):
    """Multi-turn session follow-ups (ISSUE 18): each session's FIRST
    request anchors a conversation; turn k+1 re-arrives carrying turn
    k's full context — its prompt is the previous turn's prompt plus a
    drawn continuation (the assistant reply + next user message), its
    arrival the previous turn's arrival plus an exponential think-time
    gap. That re-arriving shared context is the regime cache-aware
    routing exists for: the turn's prefix is hot on exactly one
    replica, and hash affinity only finds it by luck.

    Every draw comes from a SEPARATE (seed, 5) spawn — the --len-dist
    precedent — so the base workload is bitwise-unchanged (the pinned
    default CRCs stay valid) and turns-off runs never touch the
    stream. A chain stops when the grown prompt can no longer fit its
    next output inside `max_len` (validate_request's law). Follow-up
    rids continue from len(reqs); the merged list is re-sorted by
    (arrival, rid) — the arrival order every consumer assumes."""
    from .scheduler import Request

    draw_turns = parse_turns_dist(turns_dist)
    srng = np.random.default_rng([seed, 5])
    anchors: dict = {}
    for r in reqs:
        if r.session is not None and r.session not in anchors:
            anchors[r.session] = r
    out = list(reqs)
    rid = len(reqs)
    for sess in sorted(anchors):
        prev = anchors[sess]
        for _turn in range(draw_turns(srng) - 1):
            ext = int(srng.integers(out_min, out_max + 1))
            olen = int(srng.integers(out_min, out_max + 1))
            gap = (float(srng.exponential(turn_gap_s))
                   if turn_gap_s > 0 else 0.0)
            if prev.prompt.size + ext + olen > max_len:
                break
            prompt = np.concatenate(
                [prev.prompt,
                 srng.integers(0, vocab, (ext,)).astype(np.int32)])
            arrival = prev.arrival + gap
            rel_deadline = (prev.deadline - prev.arrival
                            if prev.deadline is not None else None)
            nr = Request(rid=rid, prompt=prompt, max_new_tokens=olen,
                         arrival=arrival,
                         deadline=(arrival + rel_deadline
                                   if rel_deadline is not None else None),
                         session=prev.session, tenant=prev.tenant)
            out.append(nr)
            rid += 1
            prev = nr
    out.sort(key=lambda r: (r.arrival, r.rid))
    return out


def diurnal_warp(reqs, *, amp: float, period_s: float):
    """Deterministic diurnal time-warp (ISSUE 18): remap each Poisson
    arrival t -> s so the instantaneous rate follows
    rate*(1 + amp*sin(2*pi*s/period)) — a day cycle with peak
    rate*(1+amp) and trough rate*(1-amp) — WITHOUT drawing anything
    (the base rate cancels out of the fixed point): s solves the
    cumulative-intensity equation Lambda(s) = t with
    Lambda(s) = s + amp*P/(2pi)*(1 - cos(2pi*s/P)), by
    fixed-iteration bisection (the map is monotone for amp <= 1, so
    arrival order is preserved and two runs bisect identically).
    amp=0 is the exact identity — the default workload CRCs stay
    pinned. Deadlines ride along at their original arrival-relative
    offset; the warp mutates in place and returns `reqs`."""
    if amp <= 0:
        return reqs
    if amp > 1.0:
        raise ValueError(f"diurnal amp must be <= 1 (got {amp}): past "
                         "it the intensity goes negative at the trough")
    if period_s <= 0:
        raise ValueError(f"diurnal period must be > 0 (got {period_s})")
    two_pi = 2.0 * np.pi
    span = amp * period_s / np.pi  # max warp displacement: Lambda bound
    for r in reqs:
        t = r.arrival
        lo, hi = max(0.0, t - span), t
        for _ in range(52):  # fixed count: bitwise-identical runs
            mid = 0.5 * (lo + hi)
            lam = mid + amp * period_s / two_pi * (
                1.0 - np.cos(two_pi * mid / period_s))
            if lam < t:
                lo = mid
            else:
                hi = mid
        s = 0.5 * (lo + hi)
        if r.deadline is not None:
            r.deadline = s + (r.deadline - r.arrival)
        r.arrival = s
    return reqs


def build_sched_policy(args, slo_spec):
    """The --scheduler/--tenant-priority/--tenant-quota surface, shared
    by serve-bench and fleet-bench (one grammar, one error story).
    Returns (rc, policy): rc nonzero means the error was printed and
    the caller should exit with it; policy is None under fcfs."""
    if args.scheduler != "slo":
        if args.tenant_priority or args.tenant_quota:
            print("error: --tenant-priority/--tenant-quota need "
                  "--scheduler slo", file=sys.stderr)
            return 2, None
        return 0, None
    from .scheduler import (
        SLOPolicy,
        parse_tenant_priorities,
        parse_tenant_quotas,
    )

    try:
        prios = (parse_tenant_priorities(args.tenant_priority)
                 if args.tenant_priority else {})
        slot_q, page_q = (parse_tenant_quotas(args.tenant_quota)
                          if args.tenant_quota else ({}, {}))
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2, None
    return 0, SLOPolicy(priorities=prios, slot_quota=slot_q,
                        page_quota=page_q, slo_spec=slo_spec)


def serve_bench_main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="mctpu serve-bench",
        description="Serving bench: paged-KV continuous batching vs "
                    "static batching under Poisson arrivals "
                    "(throughput, TTFT, p50/p99 per-token latency).",
    )
    ap.add_argument("--dim", type=int, default=128)
    ap.add_argument("--depth", type=int, default=2)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--kv-heads", type=int, default=0,
                    help="0 = MHA; fewer = GQA/MQA (smaller pages)")
    ap.add_argument("--vocab", type=int, default=512)
    ap.add_argument("--max-seq", type=int, default=512)
    ap.add_argument("--slots", type=int, default=4,
                    help="decode batch rows (in-flight sequences)")
    ap.add_argument("--page-size", type=int, default=16,
                    help="tokens per KV page")
    ap.add_argument("--pages", type=int, default=0,
                    help="global page-pool size incl. the scratch page "
                         "(0 = size for slots full-length sequences — "
                         "ample; shrink it to exercise preemption)")
    ap.add_argument("--prefill-chunk", type=int, default=32)
    ap.add_argument("--cache-dtype", default="float32",
                    choices=["float32", "bfloat16", "int8", "auto"],
                    help="auto routes from the banked int8 table "
                         "(VERDICT 7): int8 for GQA/MQA, bfloat16 "
                         "for MHA (models/generate.pick_cache_dtype)")
    ap.add_argument("--attn-kernel", default="gather",
                    choices=["gather", "pallas"],
                    help="paged-attention read (ISSUE 12): gather = "
                         "the XLA formulation; pallas = the fused "
                         "ops/pallas_paged_attention kernel (pages "
                         "stream HBM->VMEM; bitwise vs gather in f32, "
                         "<=1e-5 in bf16/int8; interpret mode on CPU)")
    ap.add_argument("--decode-weights-dtype", default="float32",
                    choices=["float32", "bfloat16", "int8", "auto"],
                    help="decode GEMV weights storage (ISSUE 12): int8 "
                         "= per-channel absmax QuantW via the fused "
                         "GEMV (ops/pallas_gemv), quantized ONCE at "
                         "engine construction; auto routes int8 for "
                         "GQA/MQA, float32 for MHA "
                         "(generate.pick_weights_dtype)")
    ap.add_argument("--requests", type=int, default=24)
    ap.add_argument("--prompt-min", type=int, default=8)
    ap.add_argument("--prompt-max", type=int, default=96)
    ap.add_argument("--out-min", type=int, default=8)
    ap.add_argument("--out-max", type=int, default=96)
    ap.add_argument("--rate", type=float, default=0.0,
                    help="Poisson arrival rate, requests/s (0 = all at "
                         "t=0: the pure-throughput comparison)")
    ap.add_argument("--mode", default="both",
                    choices=["both", "static", "continuous"])
    ap.add_argument("--deadline-ms", type=float, default=0.0,
                    help="per-request deadline (arrival + this many ms; "
                         "0 = none): expired queued requests are "
                         "dropped, in-flight ones aborted with their "
                         "pages returned")
    ap.add_argument("--max-queue", type=int, default=0,
                    help="bound on ARRIVED-but-waiting requests; "
                         "arrivals past it are rejected with a terminal "
                         "status (backpressure; 0 = unbounded)")
    ap.add_argument("--watchdog-ms", type=float, default=0.0,
                    help="tick watchdog: count + record engine "
                         "iterations slower than this (0 = off)")
    ap.add_argument("--fault-plan", default=None,
                    type=_fault_plan_arg("serve-bench"),
                    help="deterministic fault injection, e.g. "
                         "'squeeze@serve.tick:5?pages=4&ticks=8;"
                         "slow@serve.tick:9?s=0.2' (faults.parse_plan; "
                         "sites checked against serve-bench's hook "
                         "points at parse time)")
    ap.add_argument("--tenants", type=int, default=0,
                    help="tag requests with a seeded tenant mix over "
                         "t0..t{N-1} (0 = untagged single-tenant; the "
                         "SLO layer buckets by tenant)")
    ap.add_argument("--sessions", type=int, default=0,
                    help="session keys: request i belongs to session "
                         "i %% N (0 = sessionless). On this single-"
                         "engine bench sessions only matter as the "
                         "--turns-dist conversation anchors")
    ap.add_argument("--turns-dist", default=None,
                    help="multi-turn session conversations (ISSUE 18): "
                         "'uniform:LO-HI' or 'geometric:P' turns per "
                         "session; turn k+1 re-arrives carrying turn "
                         "k's full prompt as its prefix, from a "
                         "separate seeded spawn (default workload "
                         "bitwise-unchanged; needs --sessions)")
    ap.add_argument("--turn-gap-ms", type=float, default=0.0,
                    help="mean think-time between a session's turns, "
                         "exponential draw (needs --turns-dist; 0 = "
                         "back-to-back turns)")
    ap.add_argument("--slo", default=None,
                    help="SLO spec JSON (obs.slo grammar): run the "
                         "streaming alert engine live on the record "
                         "stream; fired alerts land in the JSONL as "
                         "`alert` events")
    ap.add_argument("--prefix-mix", type=float, default=0.0,
                    help="fraction of requests sharing seeded template "
                         "prompt prefixes (ISSUE 9 workload shape; "
                         "0 = all-unique prompts, bitwise-identical "
                         "lengths/arrivals either way)")
    ap.add_argument("--len-dist", default="uniform",
                    choices=["uniform", "lognormal"],
                    help="prompt/output length mix (ISSUE 16): uniform "
                         "over the ranges (default, bitwise-unchanged "
                         "stream) or a heavy-tail lognormal clipped to "
                         "them, drawn from a separate seeded spawn")
    ap.add_argument("--prefix-cache", action="store_true",
                    help="enable prefix-sharing KV cache on the "
                         "continuous scheduler: hash-keyed prefix "
                         "pages with refcounts + COW — cache-hit "
                         "requests prefill only their suffix")
    ap.add_argument("--templates", type=int, default=0,
                    help="prefix template working-set size (ISSUE 17): "
                         "overrides the default 4-template pool with N "
                         "templates drawn from a separate seeded spawn "
                         "(default workload bitwise-unchanged); size it "
                         "past the device page pool to exercise the "
                         "host tier (needs --prefix-mix > 0)")
    ap.add_argument("--spill", action="store_true",
                    help="host-tier KV spill (ISSUE 17): LRU-reclaimed "
                         "refcount-0 prefix pages spill to a bounded "
                         "host-memory tier instead of being discarded; "
                         "a later prefix hit readmits them (CRC-sealed "
                         "at the tier crossing — corrupt spills are "
                         "refused and re-prefill). Needs --prefix-cache")
    ap.add_argument("--host-pages", type=int, default=0,
                    help="host-tier capacity in pages (--spill; 0 = "
                         "match the device pool)")
    ap.add_argument("--spec", default="off",
                    choices=["off", "lookup", "draft"],
                    help="batched speculative decoding (ISSUE 14), "
                         "continuous mode only: lookup = draft-free "
                         "prompt lookup over each request's committed "
                         "context (the agentic/template-traffic form); "
                         "draft = a cheap sliding-window draft model "
                         "behind the same interface. Per tick: per-slot "
                         "k-token proposal + ONE batched verify block; "
                         "T=0 outputs stay bitwise spec-off's while "
                         "the tick count drops with acceptance")
    ap.add_argument("--spec-k", type=int, default=8,
                    help="speculative round width: candidate tokens "
                         "verified per slot per tick (>= 2)")
    ap.add_argument("--spec-ngram", type=int, default=2,
                    help="prompt-lookup match length (--spec lookup)")
    ap.add_argument("--draft-dim", type=int, default=0,
                    help="draft model width (--spec draft; 0 = dim/2)")
    ap.add_argument("--draft-depth", type=int, default=0,
                    help="draft model depth (--spec draft; 0 = 1)")
    ap.add_argument("--draft-cache", default="window",
                    choices=["window", "paged"],
                    help="draft KV form (--spec draft, ISSUE 17): "
                         "window = cacheless sliding-window draft "
                         "(recomputes ~W tokens per proposal); paged = "
                         "the draft holds its own paged KV cache, "
                         "per-slot block tables growing/rolling back in "
                         "lockstep with commit_spec (same T=0 outputs, "
                         "~W x fewer draft FLOPs per round)")
    ap.add_argument("--scheduler", default="fcfs",
                    choices=["fcfs", "slo"],
                    help="continuous-batching policy: fcfs (default) "
                         "or the SLO-aware scheduler (priority "
                         "classes, per-tenant quotas, burn-driven "
                         "preemption; implies --mode continuous)")
    ap.add_argument("--tenant-priority", default=None,
                    help="per-tenant priority classes, e.g. "
                         "'t0=2,t1=0' (higher = more protected; "
                         "needs --scheduler slo)")
    ap.add_argument("--tenant-quota", default=None,
                    help="per-tenant admission quotas, e.g. "
                         "'t0=pages:8/slots:2,t1=slots:1' "
                         "(needs --scheduler slo)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--trace", default=None,
                    help="trace-driven replay (ROADMAP item 4): rebuild "
                         "the workload from a finished run's metrics "
                         "JSONL `request` records — ids, prompt/output "
                         "budgets, arrivals, and tenant labels exactly "
                         "as recorded (prompt content re-synthesized "
                         "per id from --seed); overrides --requests, "
                         "--rate and the length-range flags")
    ap.add_argument("--metrics-jsonl", default=None,
                    help="append per-request obs records here")
    ap.add_argument("--device", default="auto",
                    choices=["auto", "tpu", "cpu"])
    args = ap.parse_args(argv)

    trace_rows = None
    if args.trace:
        if args.turns_dist or args.prefix_mix > 0 or args.templates:
            # Loud-config-error convention: these flags shape generated
            # prompts; a trace IS the workload, so they would silently
            # describe a run that never happens.
            print("error: --trace replaces the generated workload; "
                  "drop --turns-dist/--prefix-mix/--templates",
                  file=sys.stderr)
            return 2
        try:
            trace_rows = load_trace(args.trace)
        except (OSError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        apply_trace_geometry(args, trace_rows)

    import jax

    if args.device == "cpu":
        jax.config.update("jax_platforms", "cpu")
    elif args.device == "tpu" and jax.default_backend() != "tpu":
        print("--device=tpu requested but the backend is "
              f"{jax.default_backend()}", file=sys.stderr)
        return 1

    from ..models.transformer import TransformerLM
    from ..obs.causal import CATEGORIES, BlameAccumulator
    from ..obs.metrics import MetricsRegistry
    from ..utils.logging import MetricsLogger
    from .engine import PagedEngine
    from .paged_cache import pages_for

    if args.prompt_max + args.out_max > args.max_seq:
        print(f"prompt {args.prompt_max} + out {args.out_max} exceeds "
              f"--max-seq {args.max_seq}", file=sys.stderr)
        return 1
    from ..models.generate import pick_cache_dtype

    cache_dtype = pick_cache_dtype(args.cache_dtype, heads=args.heads,
                                   kv_heads=args.kv_heads or None)
    if args.spec != "off" and args.mode == "static":
        # Same contract as --prefix-cache: speculation is iteration-
        # level; a pure-static run would silently measure spec-off.
        print("error: --spec needs continuous batching (--mode "
              "continuous or both; static is the one-token baseline)",
              file=sys.stderr)
        return 2
    if args.spec != "off" and args.spec_k < 2:
        print(f"error: --spec-k {args.spec_k} would propose nothing "
              "(want >= 2)", file=sys.stderr)
        return 2
    if args.draft_cache == "paged" and args.spec != "draft":
        # Loud-config-error convention: the knob only shapes the draft
        # proposer; swept without one it would silently measure nothing.
        print("error: --draft-cache paged needs --spec draft",
              file=sys.stderr)
        return 2
    if args.spill and not args.prefix_cache:
        print("error: --spill needs --prefix-cache (the host tier "
              "spills prefix-cache pages; there is nothing to spill)",
              file=sys.stderr)
        return 2
    if args.host_pages and not args.spill:
        print("error: --host-pages needs --spill (without the tier the "
              "capacity knob would be silently ignored)",
              file=sys.stderr)
        return 2
    if args.templates and not args.prefix_mix > 0:
        print("error: --templates needs --prefix-mix > 0 (no request "
              "draws a template prefix at mix 0)", file=sys.stderr)
        return 2
    if args.turns_dist and args.sessions <= 0:
        print("error: --turns-dist needs --sessions > 0 (turns are "
              "per-session conversations; a sessionless workload has "
              "no chains to grow)", file=sys.stderr)
        return 2
    if args.turn_gap_ms and not args.turns_dist:
        print("error: --turn-gap-ms needs --turns-dist (without turns "
              "there are no gaps to draw)", file=sys.stderr)
        return 2
    if args.turns_dist:
        try:
            parse_turns_dist(args.turns_dist)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
    model = TransformerLM(
        vocab=args.vocab, dim=args.dim, heads=args.heads, depth=args.depth,
        max_seq=args.max_seq, kv_heads=args.kv_heads,
    )
    params = model.init(jax.random.key(args.seed))
    max_len = args.prompt_max + args.out_max
    pages = args.pages or args.slots * pages_for(max_len, args.page_size) + 1
    draft_model = draft_params = None
    if args.spec == "draft":
        # The cheap draft: narrower/shallower, same vocab/heads — its
        # params come from a DIFFERENT key so the draft is a genuinely
        # distinct model (a draft equal to the target would accept
        # everything and measure nothing).
        draft_model = TransformerLM(
            vocab=args.vocab, dim=args.draft_dim or max(args.dim // 2, 16),
            heads=args.heads, depth=args.draft_depth or 1,
            max_seq=args.max_seq, kv_heads=args.kv_heads,
        )
        draft_params = draft_model.init(jax.random.key(args.seed + 1))
    engine = PagedEngine(
        model, params, slots=args.slots, num_pages=pages,
        page_size=args.page_size, prefill_chunk=args.prefill_chunk,
        cache_dtype=cache_dtype, max_len=max_len,
        attn_kernel=args.attn_kernel,
        weights_dtype=args.decode_weights_dtype,
        spec=args.spec, spec_k=args.spec_k, spec_ngram=args.spec_ngram,
        draft_model=draft_model, draft_params=draft_params,
        draft_cache=args.draft_cache,
    )
    host_pages = (args.host_pages or pages) if args.spill else 0
    if args.scheduler == "slo":
        args.mode = "continuous"
    if args.prefix_cache and args.mode == "static":
        # Sharing is continuous-only (static is the reservation
        # baseline); running it silently sharing-off would report a
        # measurement the flags don't describe.
        print("error: --prefix-cache needs continuous batching "
              "(--mode continuous or both; static is the sharing-off "
              "baseline)", file=sys.stderr)
        return 2
    modes = (["static", "continuous"] if args.mode == "both"
             else [args.mode])
    workload_kw = dict(
        n=args.requests, vocab=args.vocab, prompt_min=args.prompt_min,
        prompt_max=args.prompt_max, out_min=args.out_min,
        out_max=args.out_max, rate=args.rate, seed=args.seed,
        deadline_s=args.deadline_ms / 1e3, tenants=args.tenants,
        prefix_mix=args.prefix_mix, len_dist=args.len_dist,
        templates=args.templates,
    )
    run_kw = dict(
        max_queue=args.max_queue or None,
        watchdog_s=args.watchdog_ms / 1e3,
    )

    def build_reqs():
        # Regenerated identically per mode (the cross-mode contract);
        # session tags + multi-turn follow-ups (ISSUE 18) layer on top
        # of the base stream without perturbing it.
        if trace_rows is not None:
            reqs = requests_from_trace(
                trace_rows, vocab=args.vocab, seed=args.seed,
                deadline_s=args.deadline_ms / 1e3)
        else:
            reqs = make_workload(**workload_kw)
        if args.sessions > 0:
            for r in reqs:
                r.session = r.rid % args.sessions
        if args.turns_dist:
            reqs = add_session_turns(
                reqs, turns_dist=args.turns_dist,
                turn_gap_s=args.turn_gap_ms / 1e3, vocab=args.vocab,
                out_min=args.out_min, out_max=args.out_max,
                max_len=max_len, seed=args.seed)
        return reqs
    alert_engine = None
    slo_spec = None
    if args.slo:
        from ..obs.alerts import AlertEngine
        from ..obs.slo import SLOSpec

        try:
            slo_spec = SLOSpec.load(args.slo)
            alert_engine = AlertEngine(slo=slo_spec)
        except (OSError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
    rc, sched_policy = build_sched_policy(args, slo_spec)
    if rc:
        return rc
    summaries = {}
    with MetricsLogger(path=args.metrics_jsonl, echo=False) as metrics:
        if alert_engine is not None:
            # Live alerting folds EXACTLY the records the file gets
            # (MetricsLogger observer): replaying the finished JSONL
            # reproduces the identical alert sequence, CRC-pinned.
            alert_engine.attach(metrics)
        # Warm the compiled programs (engine-level: the same ones serve
        # every mode) on one throwaway request, so no mode pays
        # compilation inside its latencies. With sharing on, the COW
        # copy program warms too (scratch onto itself — harmless).
        engine.run(make_workload(**{**workload_kw, "n": 1, "rate": 0.0,
                                    "deadline_s": 0.0}),
                   mode=modes[0])
        if args.spec != "off":
            # Warm the speculative verify program too (one continuous
            # spec round on the throwaway request).
            engine.run(make_workload(**{**workload_kw, "n": 1, "rate": 0.0,
                                        "deadline_s": 0.0}),
                       mode="continuous", spec=True)
        if args.prefix_cache:
            engine.copy_page(0, 0)
        if args.spill:
            # Warm the readmission restore program (scratch page onto
            # itself, like the COW warm-up above — harmless: scratch is
            # the sanctioned garbage sink).
            engine.readmit_page(0, engine.spill_page(0))
        for mode in modes:
            faults = None
            if args.fault_plan:
                # Fresh injector per mode: both modes see the identical
                # fault schedule (the cross-mode comparison contract).
                from ..faults import FaultInjector

                faults = FaultInjector(args.fault_plan)
            # The runtime metrics layer (ISSUE 6): one registry per mode
            # (cross-mode aggregation would blend the two schedules) and
            # tick records streamed to the JSONL sink AS THEY HAPPEN —
            # `mctpu top run.jsonl` tails the file live; `mctpu trace`
            # reconstructs lifecycles from the same records afterwards.
            registry = MetricsRegistry()
            base_sink = None
            if metrics.jsonl_enabled or alert_engine is not None:
                # Tick records route through metrics.log either way:
                # the JSONL sink and the alert observer both hang off
                # it (with no file open, log() is observer-only).
                def base_sink(rec, _snap_every=64):
                    metrics.log("tick", **rec)
                    if (rec["tick"] + 1) % _snap_every == 0:
                        registry.emit(metrics, mode=rec["mode"])
            # Causal blame (ISSUE 11) folds the live tick stream the
            # way the alert engine does — always on, so every serve
            # summary carries blame_crc + per-category totals whether
            # or not the ticks reach a file.
            blame = BlameAccumulator()

            def tick_sink(rec, _base=base_sink):
                blame.ingest_tick(rec)
                if _base is not None:
                    _base(rec)
            result = engine.run(build_reqs(), mode=mode,
                                faults=faults, registry=registry,
                                tick_sink=tick_sink,
                                prefix=(args.prefix_cache
                                        and mode == "continuous"),
                                policy=(sched_policy
                                        if mode == "continuous" else None),
                                spec=(args.spec != "off"
                                      and mode == "continuous"),
                                host_pages=(host_pages
                                            if mode == "continuous" else 0),
                                **run_kw)
            s = result.summary()
            # Blame stamp (ISSUE 11): the crc + per-category totals
            # `mctpu compare` flattens as serve.<mode>.blame_*, plus
            # the full `blame` summary record for `mctpu report`.
            bf = blame.summary_fields(mode)
            s["blame_crc"] = bf["crc"]
            s["blame_quota_ticks"] = bf["quota_ticks"]
            for cat in CATEGORIES:
                s[f"blame_{cat}"] = bf["categories"][cat]
            metrics.log("blame", **bf)
            summaries[mode] = s
            registry.set("serve.tokens_per_s", s["tokens_per_s"])
            registry.emit(metrics, mode=mode, final=True)
            for rec in result.request_records():
                metrics.log("request", **rec)
            for ev in result.events:
                metrics.log("fault", **{"mode": mode, **ev})
            metrics.log("serve", **{
                "bench": "serve", "backend": jax.default_backend(),
                "cache_dtype": cache_dtype, "rate": args.rate,
                "attn_kernel": args.attn_kernel,
                "weights_dtype": engine.weights_dtype,
                "spec": args.spec, "spec_k": args.spec_k,
                "slots": args.slots, "page_size": args.page_size,
                "pages": pages,
                # Whether the continuous run shared prefixes (ISSUE 15):
                # the replay reconstruction needs the flag — a sharing-on
                # run with zero hits digests (0,0,...) where a
                # sharing-off run digests None.
                "prefix_cache": bool(args.prefix_cache),
                # Host-tier + draft-cache geometry (ISSUE 17): the
                # replay mirror rebuilds the tier digest extension from
                # host_pages > 0 and the draft-pool extension from
                # draft_cache == "paged" (max_len sizes the draft pool).
                "host_pages": host_pages,
                "draft_cache": args.draft_cache,
                "max_len": max_len, **s,
            })
            print(json.dumps({"bench": "serve", "backend":
                              jax.default_backend(),
                              "cache_dtype": cache_dtype,
                              "attn_kernel": args.attn_kernel,
                              "weights_dtype": engine.weights_dtype,
                              "spec": args.spec, "spec_k": args.spec_k,
                              **s}))
    if alert_engine is not None:
        print(json.dumps({"metric": "serve_alerts_fired",
                          "value": len(alert_engine.alerts),
                          "alerts_crc": alert_engine.crc}))
    if len(summaries) == 2:
        st, ct = summaries["static"], summaries["continuous"]
        print(json.dumps({
            "metric": "serve_tokens_per_s",
            "value": ct["tokens_per_s"],
            "unit": "tokens/s",
            "static_tokens_per_s": st["tokens_per_s"],
            "speedup": round(ct["tokens_per_s"] / max(st["tokens_per_s"],
                                                      1e-9), 3),
            "decode_ticks": {"static": st["decode_ticks"],
                             "continuous": ct["decode_ticks"]},
            "ttft_p99_ms": {"static": st["ttft_p99_ms"],
                            "continuous": ct["ttft_p99_ms"]},
        }))
    return 0


def fleet_bench_main(argv: list[str] | None = None) -> int:
    """`mctpu fleet-bench` — the multi-replica storm harness (ISSUE 7).

    Everything host-side runs on a FakeClock advanced `--tick-ms` per
    fleet tick, so the schedule — dispatches, failovers, re-dispatches
    — is a pure function of (workload seed, fault plan, fleet shape):
    two identical invocations are bitwise-equal in dispatch trace and
    per-status counts, which is exactly what CI's fleet determinism
    gate compares (`mctpu compare ... --gate ci/fleet_gate.json`).
    Latency/throughput figures are in fleet-clock units unless marked
    wall_*.
    """
    ap = argparse.ArgumentParser(
        prog="mctpu fleet-bench",
        description="Failure-aware fleet bench: N single-engine "
                    "replicas behind the router under a seeded Poisson "
                    "storm, with optional injected replica crashes / "
                    "joins / leaves (exactly-once re-dispatch).",
    )
    ap.add_argument("--replicas", type=int, default=4)
    ap.add_argument("--pools", default=None,
                    help="disaggregated prefill/decode serving "
                         "(ISSUE 13): 'prefill:N,decode:M' splits the "
                         "fleet by phase — arrivals dispatch to the "
                         "prefill pool, completed prefills hand their "
                         "KV page sets to decode replicas through the "
                         "crash-safe page-granular transfer protocol "
                         "(per-page CRCs, per-handoff fences); "
                         "overrides --replicas. An emptied pool "
                         "degrades affected requests to unified "
                         "serving instead of stalling")
    ap.add_argument("--handoff-ticks", type=int, default=1,
                    help="fleet ticks one KV handoff's copy is in "
                         "flight (the mid-handoff crash window; "
                         "needs --pools)")
    ap.add_argument("--policy", default="least_loaded",
                    choices=["least_loaded", "session", "cache_aware"],
                    help="dispatch policy: least_loaded, session "
                         "(rendezvous-hash affinity), or cache_aware "
                         "(ISSUE 18: score candidates by expected "
                         "prefix-token overlap against each replica's "
                         "live routing digest — device tree + host "
                         "tier; least-loaded tie-break, hash-affinity "
                         "fallback at zero overlap. Needs "
                         "--prefix-cache)")
    ap.add_argument("--redispatch", default="resume",
                    choices=["resume", "discard"],
                    help="failover semantics for in-flight requests: "
                         "resume re-prefills prompt + committed tokens "
                         "on the new replica; discard restarts from "
                         "the prompt")
    ap.add_argument("--heartbeat-miss", type=int, default=3,
                    help="consecutive missed heartbeat ticks before a "
                         "replica is declared dead")
    ap.add_argument("--transport", action="store_true",
                    help="route the control plane over the simulated "
                         "lossy message bus (ISSUE 20): dispatch, "
                         "commits, terminals, and heartbeats become "
                         "sequenced messages with at-least-once "
                         "retransmission + receiver dedup; fences gain "
                         "lease expiries and failure detection becomes "
                         "fallible (late != dead). Zero-fault runs stay "
                         "bitwise-equal to the direct-call fleet; "
                         "unlocks the fleet.transport fault site")
    ap.add_argument("--lease-ticks", type=int, default=0,
                    help="commit-lease lifetime in fleet ticks "
                         "(--transport; 0 = heartbeat_miss + 2; must "
                         "exceed --heartbeat-miss so a live replica's "
                         "heartbeats renew faster than its lease decays)")
    ap.add_argument("--rto-base", type=float, default=2.0,
                    help="retransmission-timeout base in fleet ticks "
                         "(--transport; utils/retry.backoff_delay-paced "
                         "exponential, deterministic zero-jitter)")
    ap.add_argument("--max-flaps", type=int, default=3,
                    help="crashes before a flapping replica's circuit "
                         "opens (it never rejoins)")
    ap.add_argument("--backoff-base", type=float, default=0.05,
                    help="restart backoff base, fleet-clock seconds "
                         "(utils/retry.backoff_delay; 0 = immediate)")
    ap.add_argument("--tick-ms", type=float, default=1.0,
                    help="fleet-clock advance per tick")
    ap.add_argument("--check-every", type=int, default=16,
                    help="page-pool invariant check cadence per replica "
                         "(1 = every step; always checked at exit)")
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--page-size", type=int, default=16)
    ap.add_argument("--pages", type=int, default=0,
                    help="pages per replica incl. scratch (0 = size for "
                         "slots full-length sequences)")
    ap.add_argument("--prefill-chunk", type=int, default=32)
    ap.add_argument("--max-queue", type=int, default=0,
                    help="per-replica bound on waiting arrivals "
                         "(0 = unbounded)")
    ap.add_argument("--requests", type=int, default=256)
    ap.add_argument("--vocab", type=int, default=512)
    ap.add_argument("--prompt-min", type=int, default=8)
    ap.add_argument("--prompt-max", type=int, default=96)
    ap.add_argument("--out-min", type=int, default=8)
    ap.add_argument("--out-max", type=int, default=96)
    ap.add_argument("--rate", type=float, default=200.0,
                    help="Poisson arrival rate in fleet-clock req/s "
                         "(0 = everything at t=0)")
    ap.add_argument("--sessions", type=int, default=0,
                    help="session keys for the affinity policy: request "
                         "i belongs to session i %% N (0 = sessionless)")
    ap.add_argument("--turns-dist", default=None,
                    help="multi-turn session conversations (ISSUE 18): "
                         "'uniform:LO-HI' or 'geometric:P' turns per "
                         "session; turn k+1 re-arrives carrying turn "
                         "k's full prompt as its prefix, from a "
                         "separate seeded spawn (default workload "
                         "bitwise-unchanged; needs --sessions)")
    ap.add_argument("--turn-gap-ms", type=float, default=0.0,
                    help="mean think-time between a session's turns in "
                         "fleet-clock ms, exponential draw (needs "
                         "--turns-dist; 0 = back-to-back turns)")
    ap.add_argument("--diurnal-amp", type=float, default=0.0,
                    help="diurnal arrival modulation depth (ISSUE 18): "
                         "time-warp the Poisson arrivals so the rate "
                         "follows rate*(1 + amp*sin) over "
                         "--diurnal-period — peak rate*(1+amp), trough "
                         "rate*(1-amp); 0 = identity (default stream "
                         "bitwise-unchanged), max 1. Needs --rate > 0")
    ap.add_argument("--diurnal-period", type=float, default=10.0,
                    help="diurnal cycle length, fleet-clock seconds "
                         "(--diurnal-amp)")
    ap.add_argument("--autoscale", default=None,
                    help="online goodput autoscaler (ISSUE 18): fold "
                         "live queue pressure, SLO burn rates (--slo), "
                         "and the autosize frontier target "
                         "(--autoscale-frontier) into replica "
                         "join/leave decisions each tick. Grammar: "
                         "comma-separated key=value over min/max/high/"
                         "low/up/down/cooldown/burn, or bare 'on' for "
                         "defaults (serve/autoscale.parse_autoscale)")
    ap.add_argument("--autoscale-frontier", default=None,
                    help="goodput JSONL from `mctpu autosize "
                         "--metrics-jsonl`: its frontier record's "
                         "best_per_chip_rps converts the observed "
                         "dispatch rate into a target replica count "
                         "(needs --autoscale)")
    ap.add_argument("--tenants", type=int, default=0,
                    help="tag requests with a seeded tenant mix over "
                         "t0..t{N-1} (0 = untagged single-tenant; the "
                         "SLO layer buckets by tenant)")
    ap.add_argument("--slo", default=None,
                    help="SLO spec JSON (obs.slo grammar): run the "
                         "streaming alert engine live; with --log "
                         "summary the engine taps the per-tick sinks "
                         "directly (the records stay out of the JSONL, "
                         "the alerts land in it). Summary gains "
                         "alerts_fired/alerts_crc either way")
    ap.add_argument("--prefix-mix", type=float, default=0.0,
                    help="fraction of requests sharing seeded template "
                         "prompt prefixes (ISSUE 9; 0 = all-unique)")
    ap.add_argument("--len-dist", default="uniform",
                    choices=["uniform", "lognormal"],
                    help="prompt/output length mix (ISSUE 16): uniform "
                         "(default, bitwise-unchanged stream) or "
                         "heavy-tail lognormal from a separate spawn")
    ap.add_argument("--prefix-cache", action="store_true",
                    help="per-replica prefix-sharing KV cache: "
                         "cache-hit requests prefill only their suffix "
                         "(restarted incarnations come back cold)")
    ap.add_argument("--templates", type=int, default=0,
                    help="prefix template working-set size (ISSUE 17): "
                         "N templates from a separate seeded spawn "
                         "(default workload bitwise-unchanged; needs "
                         "--prefix-mix > 0)")
    ap.add_argument("--spill", action="store_true",
                    help="per-replica host-tier KV spill (ISSUE 17): "
                         "LRU-reclaimed prefix pages spill to a bounded "
                         "host tier and readmit on the next hit "
                         "(CRC-sealed; sim compute is accounting-only). "
                         "A restarted incarnation drops its tier with "
                         "its pool. Needs --prefix-cache")
    ap.add_argument("--host-pages", type=int, default=0,
                    help="host-tier capacity per replica in pages "
                         "(--spill; 0 = match the device pool)")
    ap.add_argument("--spec", default="off",
                    choices=["off", "lookup"],
                    help="per-replica batched speculative decoding "
                         "(ISSUE 14): lookup = draft-free prompt "
                         "lookup; every replica (and every restarted "
                         "incarnation) speculates identically, so the "
                         "dispatch trace stays seed-deterministic "
                         "(model-draft is a serve-bench/engine surface)")
    ap.add_argument("--spec-k", type=int, default=8,
                    help="speculative round width per slot per tick")
    ap.add_argument("--spec-ngram", type=int, default=2,
                    help="prompt-lookup match length (--spec lookup)")
    ap.add_argument("--scheduler", default="fcfs",
                    choices=["fcfs", "slo"],
                    help="per-replica batching policy: fcfs or the "
                         "SLO-aware scheduler (priorities, quotas, "
                         "burn-driven preemption)")
    ap.add_argument("--tenant-priority", default=None,
                    help="per-tenant priority classes, e.g. 't0=2,t1=0'"
                         " (higher = more protected; --scheduler slo)")
    ap.add_argument("--tenant-quota", default=None,
                    help="per-tenant admission quotas, e.g. "
                         "'t0=pages:8/slots:2' (--scheduler slo)")
    ap.add_argument("--deadline-ms", type=float, default=0.0,
                    help="per-request fleet-clock deadline (0 = none)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--trace", default=None,
                    help="trace-driven replay (ROADMAP item 4): feed a "
                         "recorded request trail (any finished run's "
                         "metrics JSONL) back through the fleet — ids, "
                         "prompt/output budgets, arrivals, and tenant "
                         "labels exactly as recorded (prompt content "
                         "re-synthesized per id from --seed); overrides "
                         "--requests, --rate and the length-range flags")
    ap.add_argument("--fault-plan", default=None,
                    type=_fault_plan_arg("fleet-bench"),
                    help="deterministic replica faults, e.g. "
                         "'replica_crash@fleet.tick:40?replica=1&"
                         "zombie_ticks=3;replica_join@fleet.tick:90' "
                         "(sites checked against fleet-bench's hook "
                         "points at parse time)")
    ap.add_argument("--compute", default="sim", choices=["sim", "engine"],
                    help="sim: device-free pure-token replicas (the "
                         "10^5-storm scale mode); engine: one real "
                         "PagedEngine per replica, shared weights")
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--depth", type=int, default=2)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--kv-heads", type=int, default=0)
    ap.add_argument("--cache-dtype", default="float32",
                    choices=["float32", "bfloat16", "int8", "auto"],
                    help="auto routes int8 for GQA/MQA, bfloat16 for "
                         "MHA (models/generate.pick_cache_dtype)")
    ap.add_argument("--attn-kernel", default="gather",
                    choices=["gather", "pallas"],
                    help="paged-attention read per engine replica "
                         "(ISSUE 12; engine compute only): gather = "
                         "XLA, pallas = the fused kernel")
    ap.add_argument("--decode-weights-dtype", default="float32",
                    choices=["float32", "bfloat16", "int8", "auto"],
                    help="decode GEMV weights per engine replica "
                         "(ISSUE 12; engine compute only; auto = int8 "
                         "for GQA/MQA, float32 for MHA)")
    ap.add_argument("--device", default="auto",
                    choices=["auto", "tpu", "cpu"])
    ap.add_argument("--metrics-jsonl", default=None,
                    help="append obs records here (fleet/replica/"
                         "request/fault events + registry snapshots)")
    ap.add_argument("--log", default="full", choices=["full", "summary"],
                    help="full: per-tick fleet + per-replica tick + "
                         "per-request records (what `mctpu trace`/`top` "
                         "consume); summary: lifecycle + totals only "
                         "(the 10^5-storm mode — per-tick JSONL would "
                         "dominate the run)")
    args = ap.parse_args(argv)

    from ..faults import FakeClock, FaultInjector
    from ..obs.causal import CATEGORIES, BlameAccumulator
    from ..obs.metrics import MetricsRegistry
    from ..utils.logging import MetricsLogger
    from .fleet import (
        EngineCompute,
        Fleet,
        SimCompute,
        make_fleet_workload,
        parse_pools,
    )
    from .paged_cache import pages_for

    pools = None
    if args.pools:
        try:
            pools = parse_pools(args.pools)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
    elif args.handoff_ticks != 1:
        # The loud-config-error convention: a unified fleet has no
        # handoffs, so a swept --handoff-ticks would be silently
        # ignored and every run would measure the same thing.
        print("error: --handoff-ticks needs --pools (a unified fleet "
              "performs no KV handoffs)", file=sys.stderr)
        return 2

    if args.lease_ticks and not args.transport:
        print("error: --lease-ticks needs --transport (leases pace the "
              "bus's commit fences; the direct-call fleet has no wire "
              "to lease against)", file=sys.stderr)
        return 2
    if args.rto_base != 2.0 and not args.transport:
        print("error: --rto-base needs --transport (there are no "
              "retransmissions without the bus)", file=sys.stderr)
        return 2
    if args.spill and not args.prefix_cache:
        print("error: --spill needs --prefix-cache (the host tier "
              "spills prefix-cache pages; there is nothing to spill)",
              file=sys.stderr)
        return 2
    if args.host_pages and not args.spill:
        print("error: --host-pages needs --spill (without the tier the "
              "capacity knob would be silently ignored)",
              file=sys.stderr)
        return 2
    if args.templates and not args.prefix_mix > 0:
        print("error: --templates needs --prefix-mix > 0 (no request "
              "draws a template prefix at mix 0)", file=sys.stderr)
        return 2
    if args.policy == "cache_aware" and not args.prefix_cache:
        print("error: --policy cache_aware needs --prefix-cache (the "
              "score is expected prefix-cache overlap; without the "
              "cache every score is zero and the policy silently "
              "degrades to its fallback)", file=sys.stderr)
        return 2
    if args.turns_dist and args.sessions <= 0:
        print("error: --turns-dist needs --sessions > 0 (turns are "
              "per-session conversations; a sessionless workload has "
              "no chains to grow)", file=sys.stderr)
        return 2
    if args.turn_gap_ms and not args.turns_dist:
        print("error: --turn-gap-ms needs --turns-dist (without turns "
              "there are no gaps to draw)", file=sys.stderr)
        return 2
    if args.diurnal_amp > 0 and args.rate <= 0:
        print("error: --diurnal-amp needs --rate > 0 (rate 0 puts "
              "every arrival at t=0; there is no arrival process to "
              "modulate)", file=sys.stderr)
        return 2
    if args.diurnal_amp > 1.0:
        print(f"error: diurnal amp must be <= 1 (got {args.diurnal_amp})"
              ": past it the intensity goes negative at the trough",
              file=sys.stderr)
        return 2
    if args.autoscale_frontier and not args.autoscale:
        print("error: --autoscale-frontier needs --autoscale (the "
              "frontier is the autoscaler's lookup table; without the "
              "policy it would be silently ignored)", file=sys.stderr)
        return 2
    if args.turns_dist:
        try:
            parse_turns_dist(args.turns_dist)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
    trace_rows = None
    if args.trace:
        if (args.turns_dist or args.prefix_mix > 0 or args.templates
                or args.diurnal_amp > 0):
            # Loud-config-error convention: these flags shape generated
            # prompts/arrivals; a trace IS the workload.
            print("error: --trace replaces the generated workload; "
                  "drop --turns-dist/--prefix-mix/--templates/"
                  "--diurnal-amp", file=sys.stderr)
            return 2
        try:
            trace_rows = load_trace(args.trace)
        except (OSError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        apply_trace_geometry(args, trace_rows)
    max_len = args.prompt_max + args.out_max
    pages = args.pages or args.slots * pages_for(max_len, args.page_size) + 1
    host_pages = (args.host_pages or pages) if args.spill else 0
    if args.compute == "engine":
        import jax

        if args.device == "cpu":
            jax.config.update("jax_platforms", "cpu")
        elif args.device == "tpu" and jax.default_backend() != "tpu":
            print("--device=tpu requested but the backend is "
                  f"{jax.default_backend()}", file=sys.stderr)
            return 1
        from ..models.transformer import TransformerLM
        from .engine import PagedEngine

        model = TransformerLM(
            vocab=args.vocab, dim=args.dim, heads=args.heads,
            depth=args.depth, max_seq=max_len, kv_heads=args.kv_heads,
        )
        params = model.init(jax.random.key(args.seed))

        def compute_factory(name):
            # One engine (own page pools) per replica INCARNATION: a
            # restarted replica comes back with an empty cache. The
            # weights are shared — same params on every replica, which
            # is what makes cross-replica re-dispatch output-exact.
            return EngineCompute(PagedEngine(
                model, params, slots=args.slots, num_pages=pages,
                page_size=args.page_size, prefill_chunk=args.prefill_chunk,
                cache_dtype=args.cache_dtype, max_len=max_len,
                attn_kernel=args.attn_kernel,
                weights_dtype=args.decode_weights_dtype,
                spec=args.spec, spec_k=args.spec_k,
                spec_ngram=args.spec_ngram,
            ))
    else:
        def compute_factory(name):
            return SimCompute(vocab=args.vocab, chunk=args.prefill_chunk,
                              salt=args.seed)

    try:
        if trace_rows is not None:
            reqs = requests_from_trace(
                trace_rows, vocab=args.vocab, seed=args.seed,
                deadline_s=args.deadline_ms / 1e3)
            if args.sessions > 0:
                for r in reqs:
                    r.session = r.rid % args.sessions
        else:
            reqs = make_fleet_workload(
                n=args.requests, vocab=args.vocab,
                prompt_min=args.prompt_min,
                prompt_max=args.prompt_max, out_min=args.out_min,
                out_max=args.out_max, rate=args.rate, seed=args.seed,
                sessions=args.sessions, deadline_s=args.deadline_ms / 1e3,
                tenants=args.tenants, prefix_mix=args.prefix_mix,
                len_dist=args.len_dist, templates=args.templates,
                turns_dist=args.turns_dist,
                turn_gap_s=args.turn_gap_ms / 1e3,
                diurnal_amp=args.diurnal_amp,
                diurnal_period_s=args.diurnal_period,
            )
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    alert_engine = None
    slo_spec = None
    if args.slo:
        from ..obs.alerts import AlertEngine
        from ..obs.slo import SLOSpec

        try:
            slo_spec = SLOSpec.load(args.slo)
            alert_engine = AlertEngine(slo=slo_spec)
        except (OSError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
    rc, sched_policy = build_sched_policy(args, slo_spec)
    if rc:
        return rc
    autoscaler = None
    if args.autoscale:
        from .autoscale import Autoscaler, load_frontier, parse_autoscale

        try:
            pol = parse_autoscale(args.autoscale)
            per_chip = (load_frontier(args.autoscale_frontier)
                        if args.autoscale_frontier else 0.0)
        except (OSError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        # slo_spec switches the burn-rate feed on: the autoscaler runs
        # the SAME windowed Accountant fold the alert engine does, over
        # the fence-accepted terminal stream.
        autoscaler = Autoscaler(pol, slo_spec=slo_spec,
                                per_chip_rps=per_chip)
    clock = FakeClock()
    registry = MetricsRegistry(clock=clock)
    faults = FaultInjector(args.fault_plan) if args.fault_plan else None
    with MetricsLogger(path=args.metrics_jsonl, echo=False) as metrics:
        if alert_engine is not None:
            # Everything that goes through metrics.log (registry
            # snapshots, replica/fault/request/serve records — and, at
            # --log full, the tick/fleet stream) is folded live; the
            # fired alerts are logged straight back as `alert` events.
            alert_engine.attach(metrics)
        base_fleet = base_replica = None
        if metrics.jsonl_enabled and args.log == "full":
            def base_fleet(rec):
                metrics.log("fleet", **rec)

            def base_replica(rec):
                metrics.log("tick", **rec)
        elif alert_engine is not None:
            # Summary mode keeps per-tick records OUT of the JSONL (at
            # 10^5 requests they would dominate the run) but the live
            # rule engine still sees them: tap the sinks directly.
            # Replay-from-file cannot reproduce these alerts — that
            # contract needs --log full; the determinism CI instead
            # pins alerts_crc across two identical-seed runs.
            def base_fleet(rec):
                for a in alert_engine.ingest(rec, event="fleet"):
                    metrics.log("alert", **a)

            def base_replica(rec):
                for a in alert_engine.ingest(rec, event="tick"):
                    metrics.log("alert", **a)
        # Causal blame (ISSUE 11): ALWAYS folded live off the sinks,
        # like the alert engine under --log summary — the determinism
        # gate pins blame_crc + per-category totals on every fleet-
        # bench run, including the 10^5 storm whose per-tick records
        # never reach the JSONL.
        blame = BlameAccumulator()

        def fleet_sink(rec, _base=base_fleet):
            blame.ingest_fleet(rec)
            if _base is not None:
                _base(rec)

        def replica_tick_sink(rec, _base=base_replica):
            blame.ingest_tick(rec)
            if _base is not None:
                _base(rec)
        try:
            fleet = Fleet(
                compute_factory, replicas=args.replicas, slots=args.slots,
                num_pages=pages, page_size=args.page_size, max_len=max_len,
                max_queue=args.max_queue or None, policy=args.policy,
                heartbeat_miss=args.heartbeat_miss,
                backoff_base=args.backoff_base, max_flaps=args.max_flaps,
                redispatch=args.redispatch, tick_s=args.tick_ms / 1e3,
                check_every=args.check_every, faults=faults, clock=clock,
                registry=registry, fleet_sink=fleet_sink,
                replica_tick_sink=replica_tick_sink,
                prefix=args.prefix_cache, sched_policy=sched_policy,
                host_pages=host_pages,
                spec=args.spec, spec_k=args.spec_k,
                spec_ngram=args.spec_ngram,
                pools=pools, handoff_ticks=args.handoff_ticks,
                autoscale=autoscaler,
                transport=args.transport, lease_ticks=args.lease_ticks,
                rto_base=args.rto_base,
                # The per-transfer lifecycle log is only ever emitted at
                # --log full; at summary-mode storm scale retaining it
                # would be pure GC ballast (the counters still stamp).
                log_handoffs=(args.log == "full"),
            )
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
        t_wall = time.perf_counter()
        try:
            result = fleet.run(reqs)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
        wall_s = time.perf_counter() - t_wall
        s = result.summary()
        # Blame stamp (ISSUE 11): flat keys the fleet determinism gate
        # pins at exact equality, plus the `blame` summary record.
        bf = blame.summary_fields("fleet")
        s["blame_crc"] = bf["crc"]
        s["blame_quota_ticks"] = bf["quota_ticks"]
        for cat in CATEGORIES:
            s[f"blame_{cat}"] = bf["categories"][cat]
        metrics.log("blame", **bf)
        s["wall_s"] = round(wall_s, 3)
        s["wall_tokens_per_s"] = round(
            result.output_tokens / max(wall_s, 1e-9), 1)
        registry.set("serve.tokens_per_s", s["tokens_per_s"])
        registry.emit(metrics, mode="fleet", final=True)
        for rec in result.replica_log:
            metrics.log("replica", **rec)
        for rec in result.transport_log:
            metrics.log("transport", **rec)
        for ev in result.events:
            metrics.log("fault", **{"mode": "fleet", **ev})
        if metrics.jsonl_enabled and args.log == "full":
            # Handoff lifecycle records (ISSUE 13): full-log only —
            # at 10^5-storm scale one record per transfer state would
            # rival the tick volume the summary mode exists to avoid
            # (the gated summary counters cover the totals either way).
            for rec in result.handoff_log:
                metrics.log("handoff", **rec)
            for rec in result.request_records():
                metrics.log("request", **rec)
        # Alert totals are ALWAYS stamped (zero/empty-CRC without
        # --slo): the fleet determinism gate lists them, and a gated
        # metric must exist in every fleet-bench run. The stamp covers
        # every alert fired BEFORE the summary record itself — a rule
        # matching the `serve` event would fire after the stamp is
        # frozen (its record still lands in the JSONL, and `mctpu
        # health` judges the file, not this stamp). Identical-seed
        # runs freeze identically, so the determinism gate holds.
        from ..obs.alerts import alerts_crc

        s["alerts_fired"] = (len(alert_engine.alerts)
                             if alert_engine is not None else 0)
        s["alerts_crc"] = (alert_engine.crc if alert_engine is not None
                           else alerts_crc([]))
        metrics.log("serve", **{
            "bench": "fleet", "policy": args.policy,
            "autoscale": bool(args.autoscale),
            "redispatch": args.redispatch,
            "spec": args.spec, "spec_k": args.spec_k,
            "replicas_initial": (sum(pools.values()) if pools
                                 else args.replicas),
            "rate": args.rate,
            "slots": args.slots, "page_size": args.page_size,
            "pages": pages, "compute": args.compute,
            # Flight-recorder geometry flag (ISSUE 15): `mctpu replay`
            # rebuilds each replica's mirror with sharing on/off from it.
            "prefix_cache": bool(args.prefix_cache),
            # Host-tier geometry (ISSUE 17): the replay mirror extends
            # each replica's digest with the tier tuple iff > 0.
            "host_pages": host_pages,
            # Transport mode (ISSUE 20): the replay mirror folds the
            # per-tick transport block into fleet_digest iff enabled;
            # lease_ticks is the EFFECTIVE value (0 flag -> default).
            "transport": bool(args.transport),
            "lease_ticks": fleet.lease_ticks, **s,
        })
        print(json.dumps({"bench": "fleet", "compute": args.compute,
                          "policy": args.policy, **s}))
        print(json.dumps({
            "metric": "fleet_tokens_per_s", "value": s["tokens_per_s"],
            "unit": "tokens/s (fleet-clock)",
            "wall_s": s["wall_s"],
            "wall_tokens_per_s": s["wall_tokens_per_s"],
            "requests": len(result.requests),
            "replicas": result.replicas_final,
            "redispatches": result.redispatches,
            "trace_crc": result.trace_crc,
        }))
    return 0


if __name__ == "__main__":
    sys.exit(serve_bench_main())
