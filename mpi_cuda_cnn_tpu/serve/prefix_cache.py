"""Prefix-sharing KV cache: a hash-keyed prefix tree over pages with
copy-on-write and LRU retention (ISSUE 9, ROADMAP item 2).

At production scale most traffic shares long system/template prefixes
(the vLLM/PagedAttention observation, Kwon et al., SOSP '23; SGLang's
RadixAttention, Zheng et al. 2024, is the prefix-tree form this module
follows). The paged cache's block tables make dedup natural: a KV page
holding positions [c*ps, (c+1)*ps) of a prompt is a pure function of
the prompt's first (c+1)*ps tokens, so two requests sharing that token
prefix can share the PHYSICAL page — the second request's prefill
drops to its suffix, and TTFT drops with it.

The tree: one node per FULL page of prompt tokens, keyed by
(parent, tokens-bytes) — i.e. path-compressed only down to page
granularity, exactly the granularity the block table dispatches on.
Matching walks full chunks of the prompt; at the first non-exact chunk
the best longest-common-prefix child (deterministic: max lcp, then
smallest key) is shared COPY-ON-WRITE: the scheduler allocates a fresh
private page, the engine copies the shared page's rows into it before
the slot's first write, and the shared source is dereferenced — the
"first divergent token" lands in the copy, never in a shared page.
A full match is capped at context-1 tokens so at least one prefill
chunk always runs (the completing chunk's logits are where the first
generated token comes from).

Ownership discipline (PagePool, ISSUE 9 extensions): tree pages are
owned by the cache (`PREFIX_OWNER`), frozen read-only at adoption, and
reference-counted per reader. A node whose refcount drops to zero is
NOT freed — it is retained for future hits and becomes reclaimable.
`reclaim(n)` evicts refcount-0 LEAF nodes in LRU order (an interior
node stays until its subtree drains — children are unreachable without
their parent), which is what allocation pressure (admission shortfall,
decode growth, an injected squeeze) drives instead of preempting live
work. `PagePool.check()` proves the whole arrangement after every op:
refcount conservation, no leak, no writable page ever shared.

Everything here is host-side, jax-free, and deterministic: the tree is
a pure function of the (seeded) request stream, so two identical-seed
runs produce bitwise-identical hit/evict/COW schedules — the property
the CI fleet gate pins.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from .pool import PagePool

PREFIX_OWNER = "__prefix__"


class PrefixNode:
    """One shared page of prompt KV: `tokens` are the page_size prompt
    tokens it covers, `page` the physical page index, `children` the
    continuations keyed by their tokens-bytes. `path` is the CUMULATIVE
    prefix bytes root..this-node inclusive — the host-tier spill key
    (ISSUE 17): a spilled page must be findable by a later request with
    no tree state surviving, and the cumulative token prefix is the one
    name both sides can compute independently."""

    __slots__ = ("node_id", "tokens", "page", "children", "parent_map",
                 "key", "last_used", "path")

    def __init__(self, node_id: int, tokens: np.ndarray, page: int,
                 parent_map: dict, key: bytes, path: bytes = b""):
        self.node_id = node_id
        self.tokens = tokens
        self.page = page
        self.children: dict[bytes, PrefixNode] = {}
        self.parent_map = parent_map
        self.key = key
        self.last_used = 0
        self.path = path


@dataclasses.dataclass
class Acquisition:
    """One admission's prefix match: `nodes` are the fully matched
    pages (reader references held, in position order), `cow` the
    partially matched page to copy-on-write (a transient reference is
    held until the copy completes or the slot releases), `cow_valid`
    how many of its tokens match, `matched` the total matched tokens
    (= len(nodes) * page_size + cow_valid)."""

    nodes: list[PrefixNode]
    cow: PrefixNode | None
    cow_valid: int
    matched: int


def _lcp(a: np.ndarray, b: np.ndarray) -> int:
    n = min(a.size, b.size)
    if n == 0:
        return 0
    neq = np.nonzero(a[:n] != b[:n])[0]
    return int(neq[0]) if neq.size else n


class PrefixCache:
    """The prefix tree + its policy: acquire (match & reference),
    insert (adopt a finished prefill's full prompt pages), release,
    and LRU reclaim. One instance per scheduler/pool pair — per
    replica in the fleet (each replica owns its pool)."""

    def __init__(self, pool: PagePool, page_size: int, tier=None,
                 route_keys: set | None = None):
        self.pool = pool
        self.page_size = page_size
        # Optional host-memory spill tier (serve/host_tier.py, ISSUE
        # 17): None keeps the ISSUE-9 discard-on-reclaim behavior
        # bit-for-bit (digests, schedules, summaries all unchanged).
        self.tier = tier
        # Optional fleet-owned routing digest (ISSUE 18): the set of
        # cumulative prefix keys THIS replica can serve a hit from —
        # tree node paths here, host-tier keys via the tier's own
        # hooks; a key lives in exactly one of the two at a time.
        # Maintained incrementally at the insert/readmit/evict seams
        # below; read by Router.pick's cache_aware scoring. NEVER part
        # of digest_tuple — replay re-applies recorded routing and must
        # not need this state.
        self.route_keys = route_keys
        self.root_children: dict[bytes, PrefixNode] = {}
        self.nodes: dict[int, PrefixNode] = {}     # node_id -> node
        self._next_id = 0
        self._clock = 0
        self.stats = {"hits": 0, "misses": 0, "hit_tokens": 0,
                      "cow_copies": 0, "inserts": 0, "evictions": 0}
        # Per-tick telemetry, drained by the engine/replica step like
        # the scheduler's preempted_log.
        self._tick_hits: list[list[int]] = []
        self._tick_readmits: list[list[int]] = []
        self._tick_deltas = {"cow": 0, "evictions": 0, "inserts": 0}

    # -- bookkeeping helpers --------------------------------------------

    def _touch(self, node: PrefixNode) -> None:
        self._clock += 1
        node.last_used = self._clock

    @property
    def shared_pages(self) -> int:
        return len(self.nodes)

    def retained_pages(self) -> int:
        """Refcount-0 resident tree pages (the LRU-reclaimable set)."""
        return sum(1 for n in self.nodes.values()
                   if self.pool.refs(n.page) == 0)

    def drain_tick(self) -> dict:
        """This tick's prefix moments: hits [[rid, matched_tokens]],
        cow/eviction/insert deltas since the last drain, and — with a
        host tier attached — the tick's readmission lifecycle markers
        [[rid, prefix_tokens]] (the `mctpu trace` anchor)."""
        out = {"hits": self._tick_hits, **self._tick_deltas}
        if self.tier is not None:
            out["readmits"] = self._tick_readmits
            self._tick_readmits = []
        self._tick_hits = []
        self._tick_deltas = {"cow": 0, "evictions": 0, "inserts": 0}
        return out

    # -- matching -------------------------------------------------------

    def acquire(self, prompt: np.ndarray, rid, *,
                max_tokens: int) -> Acquisition:
        """Match `prompt` against the tree and take reader references
        on every shared page. The match is capped at `max_tokens`
        (callers pass context-1: at least one token must always be
        computed so the completing prefill chunk yields the first
        generated token)."""
        ps = self.page_size
        toks = np.asarray(prompt, np.int32).reshape(-1)
        nodes: list[PrefixNode] = []
        children = self.root_children
        cow: PrefixNode | None = None
        j = 0
        i = 0
        while True:
            chunk = toks[i * ps:(i + 1) * ps]
            if chunk.size == ps:
                node = children.get(chunk.tobytes())
                if node is None and self.tier is not None \
                        and (i + 1) * ps <= max_tokens:
                    node = self._readmit(toks, i, chunk, children, rid)
                if node is not None:
                    nodes.append(node)
                    children = node.children
                    i += 1
                    continue
            # Divergent or partial final chunk: best-lcp child becomes
            # the copy-on-write source (deterministic tie-break on key).
            best, bestj = None, 0
            for key in sorted(children):
                cand = children[key]
                n = _lcp(chunk, cand.tokens)
                if n > bestj:
                    best, bestj = cand, n
            if bestj > 0:
                cow, j = best, bestj
            break
        matched = len(nodes) * ps + j
        if matched > max_tokens:
            target = max(max_tokens, 0)
            f2, j2 = divmod(target, ps)
            if j2 > 0:
                cow = nodes[f2] if f2 < len(nodes) else cow
                j = j2
            else:
                cow, j = None, 0
            nodes = nodes[:f2]
            matched = target
        if cow is not None and j == 0:
            cow = None
        for node in nodes:
            self.pool.share(node.page, rid)
            self._touch(node)
        if cow is not None:
            self.pool.share(cow.page, ("cow", rid))
            self._touch(cow)
        return Acquisition(nodes=nodes, cow=cow, cow_valid=j,
                           matched=matched)

    def _readmit(self, toks: np.ndarray, i: int, chunk: np.ndarray,
                 children: dict, rid) -> PrefixNode | None:
        """The tier consult on a device-tree chunk miss (ISSUE 17):
        look the cumulative prefix up in the host tier, CRC-verify the
        entry against the requesting prompt's own chunk, allocate a
        fresh read-only device page, restore the KV rows (engine tier)
        and re-insert the tree node — the walk resumes sharing as if
        the page had never been evicted. Returns None on a host miss,
        a CRC refusal (counted by the tier — the entry is dropped and
        the request re-prefills, never decodes the payload), or a dry
        device pool (readmission never preempts live work; the hit
        degrades to a miss)."""
        ps = self.page_size
        key = toks[:(i + 1) * ps].tobytes()
        entry = self.tier.lookup(key, chunk)
        if entry is None:
            return None
        pages = self.pool.try_alloc(1, PREFIX_OWNER)
        if pages is None:
            return None
        page = pages[0]
        self.pool.freeze(page, PREFIX_OWNER)
        self.tier.take(entry, page)
        self._next_id += 1
        node = PrefixNode(self._next_id, chunk.copy(), page,
                          children, chunk.tobytes(), key)
        children[node.key] = node
        self.nodes[node.node_id] = node
        if self.route_keys is not None:
            # The key moved tier -> tree; still servable, still routed.
            self.route_keys.add(key)
        self._tick_readmits.append([rid, (i + 1) * ps])
        return node

    def note_admitted(self, acq: Acquisition, rid) -> None:
        """Count one ADMITTED acquisition (the scheduler calls this at
        bind time, not at acquire time): hits + misses equals
        admissions, and a page-blocked head retried every tick leaves
        no phantom counts behind."""
        if acq.matched > 0:
            self.stats["hits"] += 1
            self.stats["hit_tokens"] += acq.matched
            self._tick_hits.append([rid, acq.matched])
        else:
            self.stats["misses"] += 1

    def release(self, nodes: list[PrefixNode], rid) -> None:
        """Return a slot's reader references (slot release/preempt).
        Pages stay resident — refcount-0 nodes are retained for future
        hits until reclaim evicts them."""
        for node in nodes:
            self.pool.unshare(node.page, rid)
            self._touch(node)

    def cow_done(self, node: PrefixNode, rid) -> None:
        """The engine copied the shared page into the slot's private
        page: drop the transient source reference and count the copy."""
        self.pool.unshare(node.page, ("cow", rid))
        self._touch(node)
        self.stats["cow_copies"] += 1
        self._tick_deltas["cow"] += 1

    def cow_abandon(self, node: PrefixNode, rid) -> None:
        """The slot released before its first write (preempt/abort):
        drop the transient source reference without counting a copy."""
        self.pool.unshare(node.page, ("cow", rid))
        self._touch(node)

    # -- insertion ------------------------------------------------------

    def insert(self, prompt: np.ndarray, slot) -> None:
        """Adopt the slot's full PROMPT pages into the tree at prefill
        completion. Pages already matched (the slot's refs) are walked
        through; a chunk whose node exists under a different physical
        page (two same-prefix requests prefilled concurrently) keeps
        the slot's private duplicate and continues under the existing
        node; a new chunk's private page is adopted read-only, the
        slot becomes its first reader."""
        ps = self.page_size
        toks = np.asarray(prompt, np.int32).reshape(-1)
        rid = slot.req.rid
        children = self.root_children
        for c in range(toks.size // ps):
            chunk = toks[c * ps:(c + 1) * ps]
            key = chunk.tobytes()
            node = children.get(key)
            if node is None:
                page = slot.pages[c]
                if self.pool.is_shared(page):
                    # The slot's page at this position is already a
                    # tree page (its node sits on another path after a
                    # COW branch) — never re-adopt someone's page.
                    break
                self.pool.adopt(page, rid, PREFIX_OWNER, readonly=True)
                self.pool.share(page, rid)
                self._next_id += 1
                node = PrefixNode(self._next_id, chunk.copy(), page,
                                  children, key,
                                  toks[:(c + 1) * ps].tobytes())
                children[key] = node
                self.nodes[node.node_id] = node
                if self.route_keys is not None:
                    self.route_keys.add(node.path)
                slot.refs.append(page)
                slot.prefix_nodes.append(node)
                self.stats["inserts"] += 1
                self._tick_deltas["inserts"] += 1
            self._touch(node)
            children = node.children

    # -- reclaim --------------------------------------------------------

    def reclaim(self, n: int) -> int:
        """Free up to `n` pages by evicting refcount-0 LEAF nodes in
        LRU order (oldest last_used first, node_id tie-break). Only
        unreferenced pages are ever freed — a page a live slot reads
        through its block table always holds a reference. Returns the
        number of pages actually freed."""
        freed = 0
        while freed < n:
            cands = [node for node in self.nodes.values()
                     if not node.children and self.pool.refs(node.page) == 0]
            if not cands:
                break
            victim = min(cands, key=lambda nd: (nd.last_used, nd.node_id))
            self._evict(victim)
            freed += 1
        return freed

    def _evict(self, node: PrefixNode, *, spill: bool = True) -> None:
        if spill and self.tier is not None:
            # Spill BEFORE the device page is freed (ISSUE 17): the
            # tier seals the page (CRC stamp + device fetch under an
            # engine) while the content is still addressable. The
            # device-side accounting below is unchanged either way —
            # eviction always returns the page to the pool, which is
            # what keeps the replay mirror's free-page law one rule.
            # Routing digest: the tier's spill hook keeps the key
            # registered (it moved tree -> tier, still servable).
            self.tier.spill(node.path, node.tokens, node.page)
        elif self.route_keys is not None:
            self.route_keys.discard(node.path)
        self.pool.free([node.page], PREFIX_OWNER)
        del node.parent_map[node.key]
        del self.nodes[node.node_id]
        self.stats["evictions"] += 1
        self._tick_deltas["evictions"] += 1

    def clear(self) -> int:
        """Evict every reclaimable node (end-of-run: hand all retained
        pages back so the pool's all-free exit invariant holds). The
        teardown is NOT allocation pressure — nothing spills (a
        run-end spill burst would land after the last tick's digest,
        leaving summary counters no tick record covers).
        Returns pages freed; raises if any node is still referenced."""
        freed = 0
        while self.nodes:
            cands = [node for node in self.nodes.values()
                     if not node.children
                     and self.pool.refs(node.page) == 0]
            if not cands:
                break
            victim = min(cands, key=lambda nd: (nd.last_used, nd.node_id))
            self._evict(victim, spill=False)
            freed += 1
        if self.nodes:
            raise RuntimeError(
                f"{len(self.nodes)} prefix page(s) still referenced at "
                "clear() — a slot leaked its reader references"
            )
        return freed

    def digest_tuple(self) -> tuple:
        """The prefix cache's contribution to the per-tick state digest
        — ONE spelling shared by scheduler.scheduler_digest and (via
        the tick record's cumulative counters) obs.replay.SchedMirror.
        The base seven ints are the ISSUE-9 shape bit-for-bit; a host
        tier appends its own five (ISSUE 17) so a tier-on digest covers
        spill/readmit/refusal/occupancy state too."""
        t = (len(self.nodes), self.stats["hits"], self.stats["misses"],
             self.stats["hit_tokens"], self.stats["cow_copies"],
             self.stats["inserts"], self.stats["evictions"])
        if self.tier is not None:
            t += self.tier.digest_tuple()
        return t

    def summary_fields(self) -> dict:
        """Cumulative stats as the flat serve-summary keys the CI gate
        names (prefix_hits etc.), plus the always-stamped host-tier
        counters (zeros with no tier — the gate contract)."""
        from .host_tier import empty_tier_fields

        return {
            "prefix_hits": self.stats["hits"],
            "prefix_misses": self.stats["misses"],
            "prefix_hit_tokens": self.stats["hit_tokens"],
            "prefix_cow": self.stats["cow_copies"],
            "prefix_inserts": self.stats["inserts"],
            "prefix_evictions": self.stats["evictions"],
            **(self.tier.summary_fields() if self.tier is not None
               else empty_tier_fields()),
        }


def empty_prefix_fields() -> dict:
    """The zero-valued summary block a sharing-off run stamps, so every
    gated metric exists in every run (the fleet-gate contract)."""
    from .host_tier import empty_tier_fields

    return {"prefix_hits": 0, "prefix_misses": 0, "prefix_hit_tokens": 0,
            "prefix_cow": 0, "prefix_inserts": 0, "prefix_evictions": 0,
            **empty_tier_fields()}
