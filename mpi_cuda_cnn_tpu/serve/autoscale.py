"""Online goodput autoscaler for the serving fleet (ISSUE 18,
ROADMAP item 2's ONLINE half).

`mctpu autosize` (PR 16) answers the OFFLINE sizing question: given a
chip budget, which topology maximizes SLO-goodput (DistServe's metric,
Zhong et al., PAPERS.md). This module closes the loop at runtime: the
running fleet folds its own live signals into replica join/leave
decisions every tick, so a diurnal workload is served by the capacity
it needs instead of the capacity its peak needed.

Three pressure signals, one decision:

- **Queue pressure**: mean per-replica load (queue depth + running
  slots + same-tick dispatches, the router's own gauge) plus the
  re-dispatch backlog. Above `high` long enough -> scale out; below
  `low` long enough -> scale in. The two thresholds are the hysteresis
  band — a fleet sitting between them is left alone.
- **Burn-rate pressure** (`obs/slo.py`): the SAME per-(tenant,
  objective) windowed Accountant fold the streaming alert rule and
  `mctpu health` drive. An event stream burning error budget faster
  than `burn` across EVERY configured window (the multiwindow AND of
  the SRE rule) forces up-pressure even while queues look shallow —
  latency SLOs degrade before backlogs form.
- **Goodput frontier** (optional): the committed `mctpu autosize`
  frontier is the policy's lookup table. Its recommendation's
  per-chip good-request rate converts the observed dispatch rate into
  a target replica count (ceil(rate / per_chip_rps), clamped to
  [min, max]); the fleet scales toward the target through the same
  hysteresis gates.

Flap control: a decision must hold for `up`/`down` CONSECUTIVE ticks
(streaks reset the moment the signal drops), and every applied
decision opens a cooldown paced by utils/retry.backoff_delay — with
consecutive direction REVERSALS as the attempt counter, so an
oscillating policy backs itself off exponentially instead of
thrashing the membership.

Deterministic by construction: every input is host-side fleet state
under FakeClock (loads, dispatch counts, event-time burn windows) and
the jitter hook defaults to the same constant 0.5 the router's restart
pacing uses — two identical-seed storms produce bitwise-identical
scale-event logs (scale_crc, gate-pinned). jax-free (`mctpu lint`
MCT001): offline consumers and the sim storms load this module with
no device runtime present.
"""

from __future__ import annotations

import dataclasses
import json
import math
from collections import deque
from pathlib import Path

from ..obs.slo import Accountant, SLOSpec
from ..utils.retry import backoff_delay

__all__ = ["AutoscalePolicy", "Autoscaler", "load_frontier",
           "parse_autoscale"]


@dataclasses.dataclass(frozen=True)
class AutoscalePolicy:
    """The policy knobs (grammar: `parse_autoscale`). Defaults are the
    CI diurnal storm's shape: scale out fast (3 consecutive hot ticks),
    scale in slow (200 calm ticks — capacity is cheap to hold for a
    moment and expensive to re-warm), cooldown ~50 fleet ticks at the
    default 1 ms tick."""

    min_replicas: int = 1
    max_replicas: int = 8
    high: float = 4.0          # mean load per replica that means "hot"
    low: float = 1.0           # mean load per replica that means "calm"
    up_ticks: int = 3          # consecutive hot ticks before scale-out
    down_ticks: int = 200      # consecutive calm ticks before scale-in
    cooldown_s: float = 0.05   # backoff_delay base between decisions
    max_burn: float = 0.0      # burn-rate trip point; 0 = burn feed off

    def __post_init__(self):
        if not (1 <= self.min_replicas <= self.max_replicas):
            raise ValueError(
                f"autoscale bounds: want 1 <= min <= max, got "
                f"min={self.min_replicas} max={self.max_replicas}")
        if not (0.0 <= self.low < self.high):
            raise ValueError(
                f"autoscale thresholds: want 0 <= low < high, got "
                f"low={self.low} high={self.high}")
        if self.up_ticks < 1 or self.down_ticks < 1:
            raise ValueError(
                f"autoscale streaks: want up/down >= 1, got "
                f"up={self.up_ticks} down={self.down_ticks}")
        if self.cooldown_s < 0.0:
            raise ValueError(
                f"autoscale cooldown must be >= 0, got {self.cooldown_s}")


_FIELDS = {
    "min": ("min_replicas", int), "max": ("max_replicas", int),
    "high": ("high", float), "low": ("low", float),
    "up": ("up_ticks", int), "down": ("down_ticks", int),
    "cooldown": ("cooldown_s", float), "burn": ("max_burn", float),
}


def parse_autoscale(spec: str) -> AutoscalePolicy:
    """`--autoscale` grammar: comma-separated `key=value` pairs over
    min/max/high/low/up/down/cooldown/burn (any subset; the rest keep
    their defaults), e.g. `min=1,max=6,high=6,low=0.5,burn=10`. The
    bare string 'on' takes every default."""
    kw = {}
    body = spec.strip()
    if body and body != "on":
        for part in body.split(","):
            key, sep, val = part.partition("=")
            key = key.strip()
            if not sep or key not in _FIELDS:
                raise ValueError(
                    f"autoscale spec {spec!r}: bad term {part!r} — want "
                    f"key=value with key one of {sorted(_FIELDS)}")
            name, cast = _FIELDS[key]
            try:
                kw[name] = cast(val)
            except ValueError:
                raise ValueError(
                    f"autoscale spec {spec!r}: {key}={val!r} is not "
                    f"a valid {cast.__name__}") from None
    return AutoscalePolicy(**kw)


def load_frontier(path: str | Path) -> float:
    """The committed autosize frontier's per-chip good-request rate:
    the `kind="frontier"` goodput record's `best_per_chip_rps` (the
    last one wins if the JSONL holds several sweeps) — the one number
    that converts an observed request rate into a replica count."""
    per_chip = None
    for line in Path(path).read_text().splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if rec.get("event") == "goodput" and rec.get("kind") == "frontier":
            v = rec.get("best_per_chip_rps")
            if v is not None:
                per_chip = float(v)
    if per_chip is None or per_chip <= 0:
        raise ValueError(
            f"{path}: no goodput frontier record with best_per_chip_rps "
            "> 0 — run `mctpu autosize --metrics-jsonl` to produce one")
    return per_chip


class Autoscaler:
    """The runtime policy engine the fleet consults once per tick
    (Fleet._autoscale_step). Stateful but never digested: its decisions
    act only through mirrored join/leave events, so the replay
    reconstruction needs none of this state.

    `slo_spec` (an obs.slo.SLOSpec) switches the burn-rate feed on —
    the fleet passes every fence-accepted terminal through
    observe_terminal. `per_chip_rps` (load_frontier's number) switches
    the frontier target on. `jitter` has the random.random call shape
    and feeds the cooldown's backoff_delay; the default 0.5 keeps
    pacing deterministic (the FakeClock contract)."""

    def __init__(self, policy: AutoscalePolicy | None = None, *,
                 slo_spec: SLOSpec | None = None,
                 per_chip_rps: float = 0.0,
                 rate_window_s: float = 2.0, jitter=None):
        self.policy = policy if policy is not None else AutoscalePolicy()
        self.acct = Accountant(slo_spec) if slo_spec is not None else None
        self.per_chip_rps = per_chip_rps
        self.rate_window_s = rate_window_s
        self.jitter = jitter if jitter is not None else (lambda: 0.5)
        self._burn_hot = False     # latched by observe, drained by step
        self._up_streak = 0
        self._down_streak = 0
        self._cooldown_until = -1.0
        self._last_dir: str | None = None
        self._flips = 0            # consecutive direction reversals
        self._hist: deque[tuple[float, int]] = deque()  # (now, dispatched)

    # -- signal feeds ---------------------------------------------------

    def observe_terminal(self, term: dict, now: float) -> None:
        """Fold one fence-accepted terminal's SLO classification. Trips
        the burn latch when ANY (tenant, objective) stream burns past
        `max_burn` across every window — the multiwindow AND, so one
        transient bad event can't trip it alone."""
        if self.acct is None:
            return
        for _tenant, obj, we, _good in self.acct.observe(term, now):
            if self.policy.max_burn > 0 and all(
                    we.burn_rate(w, obj.target) > self.policy.max_burn
                    for w in we.windows_s):
                self._burn_hot = True

    # -- the decision ---------------------------------------------------

    def _rate(self, now: float, dispatched: int) -> float:
        """Observed dispatch rate (req/s) over the trailing window —
        the demand estimate the frontier target divides."""
        self._hist.append((now, dispatched))
        while (len(self._hist) > 1
               and self._hist[0][0] <= now - self.rate_window_s):
            self._hist.popleft()
        t0, d0 = self._hist[0]
        if now <= t0:
            return 0.0
        return (dispatched - d0) / (now - t0)

    def step(self, *, now: float, live: int, load: float,
             dispatched: int) -> str | None:
        """One consult: "up", "down", or None. `live` is the count of
        dispatch-taking members in the governed pool, `load` their
        summed load plus the re-dispatch backlog, `dispatched` the
        fleet's cumulative dispatch count (the rate source)."""
        pol = self.policy
        rate = self._rate(now, dispatched)
        pressure = load / max(live, 1)
        burn_hot, self._burn_hot = self._burn_hot, False
        want_up = pressure > pol.high or burn_hot
        want_down = pressure < pol.low and not burn_hot
        if self.per_chip_rps > 0:
            target = max(pol.min_replicas,
                         min(pol.max_replicas,
                             math.ceil(rate / self.per_chip_rps)))
            # The frontier target adds up-pressure below it and GATES
            # scale-in above it; the queue/burn signals keep their say,
            # so a mis-calibrated frontier can't pin a drowning fleet.
            want_up = want_up or live < target
            want_down = want_down and live > target
        self._up_streak = self._up_streak + 1 if want_up else 0
        self._down_streak = self._down_streak + 1 if want_down else 0
        if now < self._cooldown_until:
            return None
        direction = None
        if (want_up and self._up_streak >= pol.up_ticks
                and live < pol.max_replicas):
            direction = "up"
        elif (want_down and self._down_streak >= pol.down_ticks
                and live > pol.min_replicas):
            direction = "down"
        if direction is None:
            return None
        self._flips = (self._flips + 1
                       if (self._last_dir is not None
                           and direction != self._last_dir) else 0)
        self._last_dir = direction
        self._up_streak = self._down_streak = 0
        self._cooldown_until = now + backoff_delay(
            self._flips, pol.cooldown_s, self.jitter)
        return direction
