"""Host-memory spill tier for refcount-0 prefix pages (ISSUE 17,
ROADMAP item 3).

At production fleet scale the shared-template working set vastly
exceeds one replica's HBM: the prefix tree's LRU reclaim (ISSUE 9)
throws away exactly the pages that earn the banked -34% prefill win,
and the next request paying a cold miss re-prefills the whole template.
This module is the capacity lever between those two outcomes — a
bounded HOST-memory tier the prefix cache spills reclaimed pages into
instead of discarding them, and readmits from on the next hit:

- SPILL: when LRU pressure evicts a refcount-0 leaf node, the page's
  covered token chunk + an integrity stamp (and, under an engine, the
  device page's KV rows) move to the host tier before the device page
  is freed. The tier is keyed by the CUMULATIVE token prefix the page
  covers — the same pure-function-of-token-ids property the handoff
  protocol rests on (serve/handoff.py), so a later request matching
  that prefix can find the entry with no tree state surviving.
- READMIT: a prefix walk that misses in the device tree consults the
  tier; a hit re-allocates a device page, restores the KV rows
  (engine) or just the accounting (sim), re-inserts the tree node, and
  the walk continues — the request's prefill drops to its suffix
  exactly as if the page had never been evicted.
- REFUSE: the tier crossing is guarded by the handoff protocol's
  seal/CRC/adopt discipline. Each spill stamps the crc32 of the int32
  token ids the page covers (`handoff.page_crcs`' law, one page's
  slice); readmission recomputes the expected stamp from the REQUESTING
  prompt and refuses on any mismatch — a torn or corrupt spill
  (modeled by `kv_corrupt@tier.spill`) is dropped, counted, and
  degrades to a plain miss: the request re-prefills, garbage is never
  decoded.

The tier is bounded (`host_pages`) with its own LRU: spilling into a
full tier evicts the oldest host entry first (counted — at that point
the bytes are genuinely gone). A sim tier (no spill/readmit callbacks)
is accounting-only: entries carry stamps but no KV payload, which is
what lets the fleet's 10^5 sim storms exercise the full spill/readmit/
refusal schedule with devices absent.

Everything here is host-side, jax-free (`mctpu lint` MCT001), and
deterministic: spill order is the LRU reclaim order, readmission order
is the request stream's, so two identical-seed runs produce
bitwise-identical tier schedules — the property the CI gates pin, and
the reason the tier's counters fold into the per-tick `state_crc`
digest (scheduler.state_digest / obs.replay.SchedMirror mirror the
same tuple).
"""

from __future__ import annotations

import zlib

import numpy as np

__all__ = ["HostTier", "TIER_SPILL_SITE", "chunk_crc", "empty_tier_fields"]

# The polled fault site (faults.SITES): trigger value = the tier's own
# spill sequence number, kind kv_corrupt flips the stamped CRC.
TIER_SPILL_SITE = "tier.spill"

# The stamp-corruption idiom shared with the handoff/resume kv_corrupt
# sites: flip known bits so the verify arithmetic, not luck, refuses.
_CORRUPT_MASK = 0x5A5A5A5A


def chunk_crc(tokens: np.ndarray) -> int:
    """One page's integrity stamp: crc32 over the int32 token ids whose
    KV rows the page holds — `handoff.page_crcs`' per-page law applied
    to a single full page (the only granularity the prefix tree
    spills)."""
    return zlib.crc32(np.asarray(tokens, np.int32).tobytes())


def empty_tier_fields() -> dict:
    """The zero-valued summary block a spill-off run stamps, so every
    gated tier metric exists in every run (the fleet/spec/disagg-gate
    contract, same as prefix_cache.empty_prefix_fields)."""
    return {"tier_spills": 0, "tier_readmits": 0, "tier_refusals": 0,
            "tier_host_evictions": 0}


class _Entry:
    """One spilled page: the prefix-path key it answers to, the chunk's
    token ids (the readmitted node's content), the seal-time CRC, and
    the opaque host KV payload (None under a sim tier)."""

    __slots__ = ("key", "tokens", "crc", "payload", "seq")

    def __init__(self, key: bytes, tokens: np.ndarray, crc: int,
                 payload, seq: int):
        self.key = key
        self.tokens = tokens
        self.crc = crc
        self.payload = payload
        self.seq = seq


class HostTier:
    """The bounded host tier, one per scheduler/pool pair (per replica
    in the fleet — a cold restart rebuilds the replica and the tier
    dies with the incarnation, like its PagePool).

    `spill_fn(page) -> payload` fetches a device page's KV rows to host
    memory at spill time; `readmit_fn(page, payload)` restores them
    into a freshly allocated device page at readmission. Both None =
    the sim tier (pure accounting). `fault_poll(seq) -> faults` is the
    injection hook (wired to FaultInjector.poll("tier.spill", seq) by
    the bench surfaces); kv_corrupt flips the stored stamp.
    """

    def __init__(self, host_pages: int, *, spill_fn=None, readmit_fn=None,
                 fault_poll=None, route_keys: set | None = None):
        if host_pages < 1:
            raise ValueError(f"host_pages must be >= 1 (got {host_pages})")
        self.host_pages = host_pages
        self.spill_fn = spill_fn
        self.readmit_fn = readmit_fn
        self.fault_poll = fault_poll
        # Optional fleet-owned routing digest (ISSUE 18): the same set
        # the replica's PrefixCache maintains for its tree paths; the
        # tier registers keys it holds (spill) and unregisters keys
        # that are genuinely gone (host-LRU eviction, CRC refusal).
        # take() does NOT unregister — the key moves back to the tree,
        # whose insert hook already holds it. Never digested.
        self.route_keys = route_keys
        self._entries: dict[bytes, _Entry] = {}
        self._seq = 0          # spill sequence number (the fault trigger)
        self._clock = 0        # host-LRU clock
        self.stats = {"spills": 0, "readmits": 0, "refusals": 0,
                      "host_evictions": 0}

    @property
    def host_used(self) -> int:
        return len(self._entries)

    # -- spill ----------------------------------------------------------

    def spill(self, path_key: bytes, tokens: np.ndarray, page: int) -> None:
        """Accept one evicted page: seal (stamp + optional device
        fetch), store under the cumulative prefix key, evicting the
        host-LRU entry first when full. Called by PrefixCache._evict
        BEFORE it frees the device page."""
        crc = chunk_crc(tokens)
        if self.fault_poll is not None:
            for f in self.fault_poll(self._seq):
                if f.kind != "kv_corrupt":
                    raise ValueError(
                        f"fault kind {f.kind!r} is inert at tier.spill"
                    )
                crc ^= _CORRUPT_MASK
        self._seq += 1
        payload = self.spill_fn(page) if self.spill_fn is not None else None
        if path_key in self._entries:
            # Re-spill of a readmission-then-re-eviction: replace in
            # place (the newer seal wins; occupancy unchanged).
            old = self._entries.pop(path_key)
            del old
        elif len(self._entries) >= self.host_pages:
            victim = min(self._entries.values(), key=lambda e: e.seq)
            del self._entries[victim.key]
            self.stats["host_evictions"] += 1
            if self.route_keys is not None:
                self.route_keys.discard(victim.key)
        self._clock += 1
        self._entries[path_key] = _Entry(path_key, tokens.copy(), crc,
                                         payload, self._clock)
        if self.route_keys is not None:
            self.route_keys.add(path_key)
        self.stats["spills"] += 1

    # -- readmission ----------------------------------------------------

    def lookup(self, path_key: bytes, expected: np.ndarray):
        """The prefix walk's tier consult: the entry under `path_key`,
        CRC-verified against the REQUESTING prompt's chunk (the
        authoritative expected token ids). A miss returns None; a stamp
        mismatch (torn/corrupt spill) drops the entry, counts a
        refusal, and returns None — the caller treats it as a plain
        miss and the request re-prefills, never decodes the payload."""
        entry = self._entries.get(path_key)
        if entry is None:
            return None
        if entry.crc != chunk_crc(expected):
            del self._entries[entry.key]
            self.stats["refusals"] += 1
            if self.route_keys is not None:
                self.route_keys.discard(entry.key)
            return None
        return entry

    def take(self, entry: _Entry, page: int) -> None:
        """Complete a readmission: restore the payload into the freshly
        allocated device `page` (engine) and drop the host entry — the
        page lives in the device tree again."""
        if self.readmit_fn is not None and entry.payload is not None:
            self.readmit_fn(page, entry.payload)
        del self._entries[entry.key]
        self.stats["readmits"] += 1

    # -- digest ---------------------------------------------------------

    def digest_tuple(self) -> tuple:
        """The tier's contribution to the per-tick state digest — ONE
        spelling, consumed by scheduler.scheduler_digest and mirrored
        by obs.replay.SchedMirror from the tick record's cumulative
        counters."""
        return (self.stats["spills"], self.stats["readmits"],
                self.stats["refusals"], self.stats["host_evictions"],
                self.host_used)

    def summary_fields(self) -> dict:
        return {"tier_spills": self.stats["spills"],
                "tier_readmits": self.stats["readmits"],
                "tier_refusals": self.stats["refusals"],
                "tier_host_evictions": self.stats["host_evictions"]}
