"""Paged KV cache — virtual memory for decode (PagedAttention, Kwon et
al., SOSP '23; layout per the TPU paged-attention kernel notes).

The contiguous cache (models/generate.init_cache) sizes every sequence
at max_seq: a batch of B requests pins B * max_seq * Hkv * hd * 2 cache
bytes per layer no matter how short each request actually is, finished
sequences hold their extent until the whole batch drains, and a new
request cannot be admitted mid-flight because the buffers are indexed by
batch row. PERF.md's decode table shows tokens/s tracks cache bytes
almost linearly — so idle cache extent is directly lost throughput.

This module replaces the per-sequence extent with FIXED-SIZE TOKEN PAGES
in one global pool:

- per layer, `k`/`v` pools of shape (num_pages, page_size, Hkv, hd)
  (+ f32 absmax scales (num_pages, page_size, Hkv, 1) for the int8
  form — the same quantization contract as the contiguous cache);
- a per-slot BLOCK TABLE (slots, pages_per_slot) of page indices maps a
  sequence's logical positions to physical pages — position p lives in
  page block_table[s, p // page_size] at offset p % page_size;
- PAGE 0 IS RESERVED as a scratch page: host-side invariants route every
  write from a dead slot or a padding token there, so a freed page can
  be re-issued to another sequence without a stale writer corrupting it.

The device-side ops are pure functions of (pages, block_table): the
scatter write + gathered read (`paged_update_attend`) and the
generate-compatible forward (`paged_decode_block` — models/generate's
decode_step/decode_block accept a PagedKVCache and land here). The
attention read itself is models/generate.attend_kv, shared with the
contiguous path — the parity tests rest on the two layouts differing
only in how cache rows are materialized, never in the attention math.
Host-side page accounting (alloc/free/ownership) is `PagePool`; policy
(who gets pages when) lives in scheduler.py.

TPU note: the gather materializes (B, L, Hkv, hd) rows per layer — the
XLA formulation of the paged read. The fused form SHIPPED as
ops/pallas_paged_attention.paged_attend (ISSUE 12): pages stream
HBM -> VMEM behind scalar-prefetched block tables with the Pallas
pipeline double-buffering the per-page copies, and the gathered rows
never exist outside VMEM. `paged_update_attend(kernel="pallas")`
dispatches to it (the write stays shared); PagedKVCache carries the
choice as static metadata so one engine never mixes layouts. Parity is
bitwise vs this gather in f32, <= 1e-5 in bf16/int8
(tests/test_paged_kernel.py, interpret mode on CPU).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from ..models.generate import _quant_kv, attend_kv, token_forward
from ..models.transformer import TransformerLM

# Host-side page accounting lives in pool.py (jax-free — the policy
# layer imports it without pulling this module's device stack);
# re-exported here so device-side callers keep one import surface.
from .pool import PagePool, pages_for  # noqa: F401


@dataclasses.dataclass
class PagedKVCache:
    """Device-side paged cache state: per-layer page pools + the block
    table mapping each slot's logical positions to physical pages.
    `page_size` is static metadata (it shapes the compiled program), as
    is `kernel` — "gather" (the XLA formulation) or "pallas" (the fused
    ops/pallas_paged_attention read); carrying the choice on the cache
    keeps ONE decode implementation with a leaf-level dispatch, the
    QuantW pattern applied to the attention read."""

    pages: list[dict]
    block_table: jnp.ndarray      # (slots, pages_per_slot) int32
    page_size: int
    kernel: str = "gather"

    @property
    def num_pages(self) -> int:
        return self.pages[0]["k"].shape[0]

    @property
    def slots(self) -> int:
        return self.block_table.shape[0]


jax.tree_util.register_dataclass(
    PagedKVCache, data_fields=["pages", "block_table"],
    meta_fields=["page_size", "kernel"],
)

_KERNELS = ("gather", "pallas")


def init_paged_cache(model: TransformerLM, *, slots: int, num_pages: int,
                     page_size: int, dtype=jnp.float32,
                     max_len: int | None = None,
                     kernel: str = "gather") -> PagedKVCache:
    """Empty page pools + an all-scratch block table.

    num_pages INCLUDES the reserved scratch page 0, so num_pages - 1
    pages are allocatable; max_len (default model.max_seq) bounds any
    one sequence and fixes the block-table width. Total cache bytes are
    num_pages * page_size tokens per layer — the pool is sized to the
    MEMORY BUDGET, not to slots * max_seq (the contiguous cache's
    forced extent; the whole point of paging).
    """
    if num_pages < 2:
        raise ValueError(f"num_pages {num_pages} < 2 (page 0 is scratch)")
    if page_size < 1:
        raise ValueError(f"page_size must be >= 1, got {page_size}")
    if kernel not in _KERNELS:
        raise ValueError(f"kernel {kernel!r}: want one of {_KERNELS}")
    max_len = max_len or model.max_seq
    shape = (num_pages, page_size, model.n_kv, model.head_dim)
    int8 = jnp.dtype(dtype) == jnp.int8
    sshape = shape[:-1] + (1,)
    pages = []
    for _ in range(model.depth):
        if int8:
            pages.append({
                "k": jnp.zeros(shape, jnp.int8),
                "ks": jnp.zeros(sshape, jnp.float32),
                "v": jnp.zeros(shape, jnp.int8),
                "vs": jnp.zeros(sshape, jnp.float32),
            })
        else:
            pages.append({"k": jnp.zeros(shape, dtype),
                          "v": jnp.zeros(shape, dtype)})
    table = jnp.zeros((slots, pages_for(max_len, page_size)), jnp.int32)
    return PagedKVCache(pages=pages, block_table=table,
                        page_size=page_size, kernel=kernel)


def paged_update_attend(c: dict, q, k, v, positions, valid, block_table,
                        page_size: int, kernel: str = "gather"):
    """One layer's paged write + attention read.

    q: (B, kk, H, hd); k/v: (B, kk, Hkv, hd); positions: (B, kk)
    absolute positions; valid: (B, kk) bool — invalid tokens (padding
    beyond a prompt's length, dead slots) write to scratch page 0 at
    offset 0 instead, so they can never touch a page owned by a live
    sequence. Writes land FIRST (in-chunk causality: row i then reads
    rows <= i through the read), then the read runs per `kernel`:
    "gather" materializes each slot's pages into (B, L, Hkv, hd) rows
    for the shared attend_kv read; "pallas" streams the same pages
    HBM -> VMEM inside ops/pallas_paged_attention.paged_attend (bitwise
    vs the gather in f32, <= 1e-5 in bf16/int8). Either way the read is
    masked to key positions <= the row's own position; positions beyond
    a slot's written extent read whatever the (possibly scratch/stale)
    rows hold — the mask keeps them out of the softmax.
    Returns (o: (B, kk, H*hd) f32, new_c).
    """
    b, kk = positions.shape
    hkv, hd = k.shape[2], k.shape[3]
    page_idx = jnp.take_along_axis(block_table, positions // page_size,
                                   axis=1)                  # (B, kk)
    off = positions % page_size
    page_idx = jnp.where(valid, page_idx, 0)
    off = jnp.where(valid, off, 0)
    pi, of = page_idx.reshape(-1), off.reshape(-1)
    int8 = c["k"].dtype == jnp.int8
    if int8:
        qk8, sk8 = _quant_kv(k)
        qv8, sv8 = _quant_kv(v)
        new_c = {
            "k": c["k"].at[pi, of].set(qk8.reshape(b * kk, hkv, hd)),
            "ks": c["ks"].at[pi, of].set(sk8.reshape(b * kk, hkv, 1)),
            "v": c["v"].at[pi, of].set(qv8.reshape(b * kk, hkv, hd)),
            "vs": c["vs"].at[pi, of].set(sv8.reshape(b * kk, hkv, 1)),
        }
    else:
        cdt = c["k"].dtype
        new_c = {
            "k": c["k"].at[pi, of].set(
                k.astype(cdt).reshape(b * kk, hkv, hd)),
            "v": c["v"].at[pi, of].set(
                v.astype(cdt).reshape(b * kk, hkv, hd)),
        }
    if kernel == "pallas":
        from ..ops.pallas_paged_attention import paged_attend

        o = paged_attend(q, new_c, positions, block_table, page_size)
        return o, new_c
    # Gather this slot's pages into contiguous logical rows. L =
    # pages_per_slot * page_size — the engine sizes the table to the
    # serving max_len, not to the pool (reads scale with the SEQUENCE
    # bound; pool size only bounds total residency).
    npages = block_table.shape[1]
    gathered = {
        name: new_c[name][block_table].reshape(
            b, npages * page_size, *new_c[name].shape[2:]
        )
        for name in new_c
    }
    mask = (jnp.arange(npages * page_size)[None, None, :]
            <= positions[:, :, None])         # (B, kk, L)
    o = attend_kv(q, gathered["k"], gathered["v"], mask,
                  cks=gathered.get("ks"), cvs=gathered.get("vs"))
    return o, new_c


def paged_forward(model: TransformerLM, params, toks, positions, valid,
                  cache: PagedKVCache):
    """toks (B, kk) through the model against the paged cache — the
    paged twin of decode_block's contiguous path, same token_forward
    skeleton, attend swapped. positions/valid: (B, kk).
    Returns (logits (B, kk, vocab) f32, new PagedKVCache)."""
    new_pages: list[dict] = []

    def attend(i, q, k, v):
        o, new_c = paged_update_attend(
            cache.pages[i], q, k, v, positions, valid,
            cache.block_table, cache.page_size, kernel=cache.kernel,
        )
        new_pages.append(new_c)
        return o

    logits = token_forward(model, params, toks, positions, attend)
    return logits, dataclasses.replace(cache, pages=new_pages)


def paged_decode_block(model: TransformerLM, params, toks, pos,
                       cache: PagedKVCache):
    """The generate-surface adapter: decode_step/decode_block semantics
    over a PagedKVCache. pos may be a scalar start (all rows at the same
    depth, the static-batch form) or a (B,) per-slot vector (the
    continuous-batching form). All tokens are valid writes — padding /
    dead-slot routing is the engine's concern (paged_forward + explicit
    `valid`). Concrete out-of-range positions raise, mirroring the
    contiguous path's guard — past the block-table extent the gathered
    page index would CLAMP to the last column and silently scatter over
    the sequence's final legitimate cache rows (traced positions cannot
    be checked, exactly as in contiguous decode_block).
    Returns (logits (B, k, vocab), new cache)."""
    b, kk = toks.shape
    limit = cache.block_table.shape[1] * cache.page_size
    if not isinstance(pos, jax.core.Tracer):
        hi = int(np.max(np.asarray(pos))) + kk
        if hi > limit:
            raise ValueError(
                f"block reaching position {hi} out of range (block table "
                f"covers {limit} = {cache.block_table.shape[1]} pages x "
                f"{cache.page_size})"
            )
    pos = jnp.asarray(pos)
    if pos.ndim == 0:
        positions = jnp.broadcast_to(pos + jnp.arange(kk), (b, kk))
    else:
        positions = pos[:, None] + jnp.arange(kk)[None, :]
    logits, cache = paged_forward(
        model, params, toks, positions, jnp.ones((b, kk), bool), cache
    )
    return logits, cache
