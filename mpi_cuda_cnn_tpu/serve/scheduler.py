"""Iteration-level serving schedulers (Orca, Yu et al., OSDI '22).

Static batching admits a batch, runs it to full drain, then admits the
next: every request pays the longest request's residency, and vacated
slots do no work until the batch ends. Continuous batching reconsiders
the batch EVERY iteration: a finished sequence frees its pages and its
slot immediately, a queued request is admitted into the vacated slot
between ticks, and long prompts prefill in fixed-size chunks interleaved
with decode ticks so token emission never stalls behind an admission.

This module is the POLICY layer and is deliberately jax-free: it moves
Requests between a queue, fixed engine slots, and the PagePool, and the
engine (engine.py) executes whatever the policy exposes each iteration
(`prefill_slot()`, `decode_slots()`). Determinism is part of the
contract — FCFS admission, lowest-admission-order prefill first,
preempt-latest — so the tick-count comparisons in tests/test_serve.py
and the bench are exactly reproducible.

Preemption: when a decoding sequence needs its next page and the pool is
dry, the LATEST-admitted occupied slot is evicted — its pages are freed,
its request (prompt + tokens generated so far) returns to the queue
head, and readmission recomputes the grown context via the normal
chunked prefill (recompute-style preemption: pages-over-wire swapping
has nowhere to go on one chip). Emitted tokens stay emitted; TTFT is
unaffected; only tail latency pays.

Failure-awareness (ISSUE 4): every request carries a terminal `status`.
Orca assumes requests can be aborted mid-flight — here that is real:
per-request deadlines and client cancellation abort queued AND in-flight
requests (`sweep()` — pages ownership-checked back into the pool), a
bounded admission queue rejects arrivals past `max_queue` (backpressure
instead of unbounded memory), admission refuses requests whose prompt
alone exceeds the pool (they could only ever preempt-loop), and a
request whose GROWN context can never fit is failed with a terminal
status instead of being requeued forever (the preemption-livelock
guard). Elastic-serving systems (Varuna, Athlur et al., EuroSys '22)
treat this abort/resume traffic as the steady state, not the exception.
"""

from __future__ import annotations

import dataclasses
import zlib
from array import array as _pack
from collections import deque
from collections.abc import Iterable

import numpy as np

from .pool import PagePool, pages_for
from .prefix_cache import PrefixCache


# -- per-tick state digests (ISSUE 15) ----------------------------------
#
# The deterministic flight recorder: every producer (engine tick,
# ReplicaCore tick, fleet/router record) stamps a `state_crc` — a crc32
# of a canonical, jax-free projection of its full host-side serving
# state — so a failed 0%/equal determinism gate localizes to the first
# divergent TICK instead of "trace_crc differs" over a 10^5 storm.
# obs/replay.py reconstructs the same projection purely from the trail
# events and recomputes this digest at every tick; obs/diverge.py diffs
# two trails at their first digest disagreement. BOTH sides call the
# ONE spelling below, so producer and replayer can never drift on what
# "the state" means.

def _rid_sig(rid: int) -> int:
    """Order-insensitive per-rid mixer for the queue-membership
    signature (Knuth multiplicative hash; xor-combined so the
    scheduler maintains it in O(1) per queue mutation)."""
    return (rid * 2654435761 ^ 0x9E3779B9) & 0xFFFFFFFF


def state_digest(queue_len: int, queue_head: int, queue_tail: int,
                 queue_sig: int, slots_flat, free_pages: int,
                 prefix=None, extra=(0, 0)) -> int:
    """THE canonical state digest (crc32), shared by every producer and
    the replayer. `slots_flat` is the FLAT int sequence of
    per-occupied-slot sextets (idx, rid, cached, target, block-table
    pages, shared refs) in idx order — page OWNERSHIP as counts
    (physical indices are an engine layout detail; the logical state
    is what replays). The queue is projected to (length, head rid,
    tail rid, membership signature): exact membership and the
    FCFS-relevant order anchors in O(1) per tick — a mid-queue
    permutation alone is not captured, but any such divergence changes
    the very next admission and lands in `slots_flat` one tick later.
    `prefix` is the prefix-tree stat tuple (or None — a sharing-off
    run; length-framed so the two can never alias), `extra` static
    config (spec on/width). Serialized as a packed int64 array, not
    repr: this runs once per replica per tick of a 10^5 storm, and the
    byte layout is part of the digest contract."""
    parts = [queue_len, queue_head, queue_tail, queue_sig, free_pages,
             len(slots_flat)]
    parts.extend(slots_flat)
    if prefix is None:
        parts.append(-1)
    else:
        parts.append(len(prefix))
        parts.extend(prefix)
    parts.extend(extra)
    return zlib.crc32(_pack("q", parts).tobytes())


def scheduler_digest(sched, extra=(0, 0)) -> int:
    """Producer-side binding of state_digest over a live scheduler:
    queue order anchors + per-slot extents/pages/refs + pool free count
    + prefix-tree stats. O(slots) per call — the storm-scale budget
    (the queue signature is maintained incrementally by the mutation
    helpers below, never recomputed by scan)."""
    q = sched.queue
    flat: list[int] = []
    ext = flat.extend
    for s in sched.slots:
        r = s.req
        if r is not None:
            ext((s.idx, r.rid, s.cached, s.target, len(s.pages),
                 len(s.refs)))
    prefix = None
    pc = sched.prefix
    if pc is not None:
        # ONE spelling (PrefixCache.digest_tuple): the ISSUE-9
        # seven-tuple, plus the host tier's five when one is attached
        # (ISSUE 17) — length-framed by state_digest, so tier-on and
        # tier-off digests can never alias.
        prefix = pc.digest_tuple()
    return state_digest(len(q), q[0].rid if q else -1,
                        q[-1].rid if q else -1, sched.queue_sig, flat,
                        sched.pool.free_pages, prefix, extra)


def validate_request(r: Request, *, max_len: int, page_size: int,
                     usable: int) -> None:
    """THE structural-admissibility check, shared by scheduler submit
    and the fleet's up-front workload validation (one spelling, so the
    fleet can never accept a request a replica's submit would then
    raise on mid-run):

    - prompt + max_new_tokens past max_len (block table can't hold it)
    - a prompt alone needing more pages than the pool owns (it could
      never be admitted, let alone decode)
    """
    if r.prompt.size + r.max_new_tokens > max_len:
        raise ValueError(
            f"request {r.rid}: prompt {r.prompt.size} + "
            f"{r.max_new_tokens} new exceeds max_len {max_len}"
        )
    if pages_for(r.prompt.size + 1, page_size) > usable:
        raise ValueError(
            f"request {r.rid}: prompt of {r.prompt.size} tokens "
            f"needs {pages_for(r.prompt.size + 1, page_size)} "
            f"pages but the pool owns {usable} — it can "
            "never be admitted (size the pool or shrink the prompt)"
        )

# A request leaves the system in exactly one of these states.
TERMINAL_STATUSES = ("finished", "expired", "cancelled", "rejected", "failed")


def terminal_fields(r: Request) -> dict:
    """One terminal request as the compact per-tick `terminal` entry
    (ISSUE 8): what the streaming SLO/alert layer folds good/bad events
    from, emitted INSIDE the run (the end-of-run `request` records are
    too late for a burn-rate alert to be actionable). Latency formulas
    match engine.request_record exactly — the two views of one request
    can never disagree. jax-free on purpose: the fleet's sim path and
    the alert engine consume this without importing the engine."""
    return {
        "id": r.rid,
        "tenant": r.tenant or "default",
        "status": r.status,
        "ttft_ms": (None if r.first_token_at is None
                    else round(1e3 * (r.first_token_at - r.arrival), 3)),
        "tpot_ms": (None if r.status != "finished"
                    else round(1e3 * (r.finished_at - r.first_token_at)
                               / max(len(r.out) - 1, 1), 3)),
        "queue_wait_ms": (None if r.admitted_at is None
                          else round(1e3 * (r.admitted_at - r.arrival), 3)),
    }


def tenant_block(requests: Iterable[Request]) -> dict[str, dict]:
    """Per-tenant status/latency counts for a run summary (ISSUE 8),
    shared by ServeResult.summary and FleetResult.summary so the two
    surfaces flatten identically in `mctpu compare`. Untagged requests
    aggregate under "default". Percentiles follow the one serving
    convention (obs.metrics.pct_nearest — jax-free, so this module's
    fleet sim path stays jax-free; `mctpu lint` MCT001 pins it)."""
    from ..obs.metrics import pct_nearest

    by_tenant: dict[str, list[Request]] = {}
    for r in requests:
        by_tenant.setdefault(r.tenant or "default", []).append(r)
    out: dict[str, dict] = {}
    for tenant, rs in sorted(by_tenant.items()):
        statuses: dict[str, int] = {}
        for r in rs:
            statuses[r.status] = statuses.get(r.status, 0) + 1
        fin = [r for r in rs if r.status == "finished"]
        ttft = [1e3 * (r.first_token_at - r.arrival) for r in fin]
        tpot = [1e3 * (r.finished_at - r.first_token_at)
                / max(len(r.out) - 1, 1) for r in fin]
        out[tenant] = {
            "requests": len(rs),
            "statuses": statuses,
            "output_tokens": sum(len(r.out) for r in rs),
            "ttft_p50_ms": pct_nearest(ttft, 50),
            "ttft_p99_ms": pct_nearest(ttft, 99),
            "tpot_p50_ms": pct_nearest(tpot, 50),
            "tpot_p99_ms": pct_nearest(tpot, 99),
        }
    return out


@dataclasses.dataclass
class Request:
    """One serving request plus its runtime bookkeeping. `prompt` is a
    1-D int32 array; `out` accumulates emitted tokens (they survive
    preemption — recompute re-prefills prompt + out). `deadline` is an
    absolute time on the engine's clock (same timeline as `arrival`);
    past it the request is dropped/aborted with status "expired".
    `cancel()` requests client-side abort at the next tick boundary.
    `session` is an opaque affinity key (ISSUE 7): the fleet router's
    session-affinity policy keeps one session's requests on one replica
    so its prefix cache stays hot; None means no affinity. `tenant` is
    the traffic-class identity (ISSUE 8): the SLO accounting layer
    buckets good/bad events, latency histograms, and health verdicts by
    it; None renders as "default" in every record and table — a
    single-tenant run needs no tagging. `priority` (ISSUE 9) is the
    request's priority class for the SLO-aware scheduler: higher is
    more protected (admitted first, preempted last); the FCFS
    schedulers ignore it."""

    rid: int
    prompt: np.ndarray
    max_new_tokens: int
    arrival: float = 0.0
    deadline: float | None = None
    session: int | str | None = None
    tenant: str | None = None
    priority: int = 0
    out: list[int] = dataclasses.field(default_factory=list)
    status: str = "queued"
    fail_reason: str | None = None
    cancel_requested: bool = False
    admitted_at: float | None = None
    first_token_at: float | None = None
    finished_at: float | None = None
    preemptions: int = 0
    # Queue-wait seconds spent quota-blocked under SLOScheduler
    # (ISSUE 11): the skip-over share of queue_wait, so the split
    # registry metric can tell policy waits from capacity waits.
    quota_wait_s: float = 0.0

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt, np.int32).reshape(-1)
        if self.prompt.size < 1:
            raise ValueError(f"request {self.rid}: empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError(f"request {self.rid}: max_new_tokens < 1")

    @property
    def context_len(self) -> int:
        return self.prompt.size + len(self.out)

    @property
    def done(self) -> bool:
        return len(self.out) >= self.max_new_tokens

    @property
    def terminal(self) -> bool:
        return self.status in TERMINAL_STATUSES

    def cancel(self) -> None:
        """Client cancellation: the scheduler aborts the request at the
        next sweep (queued: dropped; in-flight: slot + pages released)."""
        self.cancel_requested = True

    def expired_by(self, now: float) -> bool:
        return self.deadline is not None and now >= self.deadline


@dataclasses.dataclass
class Slot:
    """One fixed batch row of the engine. `cached` counts cache rows
    written; while cached < target the slot is prefilling (target =
    the request's context length at admission), after that it decodes —
    the current token (last emitted, not yet cached) goes in at row
    `cached` on the next tick.

    Prefix sharing (ISSUE 9): `pages` stays THE ordered block-table
    source; `refs` is the subset of those pages that are shared
    read-only prefix pages this slot holds reader references on
    (`prefix_nodes` the matching tree nodes), and a prefix hit binds
    with cached = matched tokens so prefill covers only the suffix.
    `cow` is a pending (src, dst) copy-on-write: the engine copies the
    shared src page into the private dst page before the slot's first
    write (`cow_node` holds the transient source reference)."""

    idx: int
    req: Request | None = None
    pages: list[int] = dataclasses.field(default_factory=list)
    cached: int = 0
    target: int = 0
    admit_seq: int = -1
    refs: list[int] = dataclasses.field(default_factory=list)
    prefix_nodes: list = dataclasses.field(default_factory=list)
    cow: tuple[int, int] | None = None
    cow_node: object = None

    @property
    def free(self) -> bool:
        return self.req is None

    @property
    def prefilling(self) -> bool:
        return self.req is not None and self.cached < self.target

    @property
    def decoding(self) -> bool:
        return self.req is not None and self.cached >= self.target


class _SchedulerBase:
    def __init__(self, *, slots: int, pool: PagePool, page_size: int,
                 max_len: int, max_queue: int | None = None,
                 prefix: PrefixCache | None = None):
        if slots < 1:
            raise ValueError(f"need at least one slot, got {slots}")
        if max_queue is not None and max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self.slots = [Slot(i) for i in range(slots)]
        self.pool = pool
        self.page_size = page_size
        self.max_len = max_len
        self.max_queue = max_queue
        self.prefix = prefix
        self.queue: deque[Request] = deque()
        # Incremental queue-membership signature (ISSUE 15): xor of
        # _rid_sig over queued rids, maintained by the _q_* helpers at
        # every mutation site so the per-tick state digest stays O(slots)
        # even when a storm's backlog holds tens of thousands of rids.
        self.queue_sig = 0
        self.finished: list[Request] = []
        # Terminal non-finished requests (expired/cancelled/rejected/
        # failed) — with `finished`, every submitted request lands in
        # exactly one of the two lists.
        self.dropped: list[Request] = []
        self.preemptions = 0
        # (victim rid, beneficiary rid | None) pairs preempted since the
        # last drain_preempted() — the engine folds them into the tick
        # record it emits for the timeline, and the beneficiary is the
        # causal edge `mctpu explain` blames the wait on (ISSUE 11).
        self.preempted_log: list[tuple[int, int | None]] = []
        # (blocked rid, reason, holder rids) admission attempts that
        # failed since the last drain_blocked() (ISSUE 11): reason is
        # "pages" / "slots" / "quota", holders the rids occupying the
        # resource the candidate waited on — the blocker edges of the
        # causal DAG. Appended only for candidates actually TRIED this
        # tick (the head under FCFS; every skipped candidate under the
        # SLO scheduler, whose quota skip-overs are their own edge kind).
        self.blocked_log: list[tuple[int, str, list[int]]] = []
        self._admit_seq = 0
        # True once any submitted request carried a deadline: lets a
        # caller (the fleet's per-replica step loop) skip the O(queue)
        # sweep() scan on ticks where nothing can possibly expire.
        self.has_deadlines = False

    def submit(self, requests: Iterable[Request]) -> None:
        """Enqueue requests (FCFS by arrival). Structurally impossible
        requests raise ValueError at submission — a clear error beats a
        request that can only ever preempt-loop:

        - prompt + max_new_tokens past max_len (block table can't hold it)
        - a prompt alone needing more pages than the pool owns (it could
          never be admitted, let alone decode)
        """
        reqs = sorted(requests, key=lambda r: (r.arrival, r.rid))
        for r in reqs:
            validate_request(r, max_len=self.max_len,
                             page_size=self.page_size,
                             usable=self.pool.usable)
            if r.deadline is not None:
                self.has_deadlines = True
            self._q_append(r)

    @property
    def unfinished(self) -> int:
        return len(self.queue) + sum(not s.free for s in self.slots)

    def next_arrival(self) -> float | None:
        return min((r.arrival for r in self.queue), default=None)

    # The queue mutation helpers every site below goes through, so the
    # digest signature can never drift from the deque (ISSUE 15).
    def _q_append(self, r: Request) -> None:
        self.queue.append(r)
        self.queue_sig ^= _rid_sig(r.rid)

    def _q_appendleft(self, r: Request) -> None:
        self.queue.appendleft(r)
        self.queue_sig ^= _rid_sig(r.rid)

    def _q_popleft(self) -> Request:
        r = self.queue.popleft()
        self.queue_sig ^= _rid_sig(r.rid)
        return r

    def _q_rebuild(self, kept: deque[Request]) -> None:
        """Wholesale queue replacement (sweep / queue bound / SLO admit
        — sites that already paid an O(queue) scan)."""
        self.queue = kept
        sig = 0
        for r in kept:
            sig ^= _rid_sig(r.rid)
        self.queue_sig = sig

    def drain_preempted(self) -> list[tuple[int, int | None]]:
        """(victim, beneficiary) pairs preempted since the last call
        (tick-record bookkeeping; beneficiary None when the eviction
        had no single requesting slot)."""
        out, self.preempted_log = self.preempted_log, []
        return out

    def drain_blocked(self) -> list[tuple[int, str, list[int]]]:
        """(rid, reason, holders) admission blocks since the last call
        — the tick record's `blocked` field (ISSUE 11)."""
        out, self.blocked_log = self.blocked_log, []
        return out

    def _occupants(self, tenant: str | None = None) -> list[int]:
        """rids currently holding slots (and therefore pages), sorted —
        the holder set a blocked admission queued behind. With `tenant`,
        only that tenant's occupants (the quota-block holder set)."""
        return sorted(
            s.req.rid for s in self.slots
            if not s.free
            and (tenant is None or (s.req.tenant or "default") == tenant)
        )

    def _note_blocked(self, req: Request, reason: str,
                      holders: list[int]) -> None:
        self.blocked_log.append((req.rid, reason, holders))

    def prefill_backlog(self) -> int:
        """Prompt tokens admitted but not yet cached — the chunked-
        prefill backlog gauge (how far admissions are ahead of the
        prefill interleave)."""
        return sum(s.target - s.cached for s in self.slots
                   if s.prefilling and not s.req.terminal)

    def prefill_slot(self) -> Slot | None:
        """The earliest-admitted slot still prefilling (FCFS: one
        sequence's prompt finishes before the next's starts, so TTFT
        ordering follows admission ordering). Aborted requests whose
        slot is still held (static's reserve-until-drain) never
        prefill."""
        cands = [s for s in self.slots
                 if s.prefilling and not s.req.terminal]
        return min(cands, key=lambda s: s.admit_seq, default=None)

    def decode_slots(self) -> list[Slot]:
        return [s for s in self.slots if s.decoding]

    def _bind(self, slot: Slot, req: Request, pages: list[int],
              now: float, acq=None) -> None:
        slot.req = req
        slot.pages = pages
        slot.cached = 0
        slot.target = req.context_len
        slot.refs = []
        slot.prefix_nodes = []
        slot.cow = None
        slot.cow_node = None
        if acq is not None:
            # Prefix hit (ISSUE 9): shared pages lead the block table,
            # cached starts at the matched depth — prefill covers only
            # the suffix. A partial match copies-on-write into the
            # slot's FIRST private page (the engine performs the device
            # copy before the slot's first write). Stats count HERE
            # (admission), not at acquire: a page-blocked head retried
            # every tick must leave no phantom hit counts.
            self.prefix.note_admitted(acq, req.rid)
            if acq.matched > 0:
                slot.pages = [n.page for n in acq.nodes] + pages
                slot.refs = [n.page for n in acq.nodes]
                slot.prefix_nodes = list(acq.nodes)
                slot.cached = acq.matched
                if acq.cow is not None:
                    slot.cow = (acq.cow.page, pages[0])
                    slot.cow_node = acq.cow
        slot.admit_seq = self._admit_seq
        self._admit_seq += 1
        req.status = "running"
        if req.admitted_at is None:
            req.admitted_at = now

    def _release(self, slot: Slot) -> None:
        rid = slot.req.rid
        if slot.cow_node is not None:
            # Released before the first write: the pending copy never
            # happened; just return the transient source reference.
            self.prefix.cow_abandon(slot.cow_node, rid)
            slot.cow = None
            slot.cow_node = None
        if slot.prefix_nodes:
            self.prefix.release(slot.prefix_nodes, rid)
        refset = set(slot.refs)
        private = [p for p in slot.pages if p not in refset]
        if private:
            self.pool.free(private, rid)
        slot.req = None
        slot.pages = []
        slot.refs = []
        slot.prefix_nodes = []
        slot.cached = 0
        slot.target = 0
        slot.admit_seq = -1

    def cow_complete(self, slot: Slot) -> None:
        """The engine copied slot.cow's src page into its private dst:
        release the transient source reference (the copy is counted by
        the prefix cache)."""
        self.prefix.cow_done(slot.cow_node, slot.req.rid)
        slot.cow = None
        slot.cow_node = None

    def note_prefill_complete(self, slot: Slot) -> None:
        """Prefill just reached target: adopt the slot's full prompt
        pages into the prefix tree (ISSUE 9) so later same-prefix
        requests hit. No-op without a prefix cache."""
        if self.prefix is not None and slot.req is not None:
            self.prefix.insert(slot.req.prompt, slot)

    # -- cross-pool KV handoff (ISSUE 13) -------------------------------

    def detach_for_handoff(self, slot: Slot, owner) -> tuple:
        """Seal a completed prefill's page set for a cross-pool KV
        handoff: the slot's private pages transfer ownership to the
        handoff token, its prefix reader references move to the same
        token (so LRU reclaim cannot evict a shared page mid-transfer),
        and the slot is cleared WITHOUT freeing anything — the pages
        stay resident until the transfer completes or aborts. Returns
        (ordered block-table pages, private pages, prefix nodes)."""
        req = slot.req
        assert slot.cow is None and slot.cow_node is None, (
            "detach with a pending COW — prefill cannot have completed"
        )
        assert slot.cached >= slot.target, "detach of a prefilling slot"
        pages = list(slot.pages)
        refset = set(slot.refs)
        private = [p for p in pages if p not in refset]
        for p in private:
            self.pool.adopt(p, req.rid, owner)
        nodes = list(slot.prefix_nodes)
        for node in nodes:
            self.pool.unshare(node.page, req.rid)
            self.pool.share(node.page, owner)
        slot.req = None
        slot.pages = []
        slot.refs = []
        slot.prefix_nodes = []
        slot.cached = 0
        slot.target = 0
        slot.admit_seq = -1
        return pages, private, nodes

    def release_handoff(self, private: list[int], nodes: list,
                        owner) -> None:
        """Return a handoff's sealed sender-side resources (transfer
        complete or aborted, sender incarnation still live): private
        pages freed through the ownership check, prefix reader
        references returned so the tree pages become reclaimable."""
        if nodes:
            self.prefix.release(nodes, owner)
        if private:
            self.pool.free(private, owner)

    def transfer_quota_ok(self, req: Request) -> bool:
        """Whether this scheduler's pool-admission policy accepts a
        handed-off request right now. The FCFS schedulers always do;
        the SLOScheduler enforces its per-tenant slot quota — the
        decode pool's admission control, owned separately from the
        prefill pool's (ISSUE 13)."""
        return True

    def bind_transfer(self, req: Request, pages: list[int], cached: int,
                      owner, now: float) -> Slot | None:
        """Bind a completed cross-pool handoff into a free slot: the
        pages (allocated under the handoff token at transfer start,
        content already adopted) become the request's private block
        table, and the slot starts DECODE-READY — cached = target =
        the sealed extent; the next decode tick writes the in-flight
        token at row `cached`. Returns None (and changes nothing) when
        no slot is free or the quota refuses — the handoff waits."""
        slot = next((s for s in self.slots if s.free), None)
        if slot is None or not self.transfer_quota_ok(req):
            return None
        for p in pages:
            self.pool.adopt(p, owner, req.rid)
        self._bind(slot, req, list(pages), now)
        slot.cached = cached
        slot.target = cached
        return slot

    def check(self) -> None:
        """Pool invariant + the slot-level sharing invariants: every
        shared page a slot references sits strictly below its written
        extent (no writable-shared page from the block table's point
        of view), and any pending COW destination is private."""
        self.pool.check()
        ps = self.page_size
        for s in self.slots:
            if s.free:
                assert not s.refs and s.cow is None
                continue
            refset = set(s.refs)
            assert len(refset) == len(s.refs), "duplicate slot ref"
            for i, p in enumerate(s.pages):
                if p in refset:
                    assert self.pool.is_shared(p), (
                        f"slot ref page {p} is not a shared pool page"
                    )
                    assert (i + 1) * ps <= s.cached, (
                        f"shared page {p} extends into slot {s.idx}'s "
                        "writable region"
                    )
            if s.cow is not None:
                assert s.cow[1] in s.pages and s.cow[1] not in refset, (
                    "COW destination is not a private slot page"
                )

    def _on_terminal(self, req: Request, now: float) -> None:
        """Hook: a request just reached a terminal status (finished or
        dropped). The SLO-aware scheduler folds it into its live
        per-tenant accountant; the FCFS schedulers do nothing."""

    def finish(self, slot: Slot, now: float) -> None:
        slot.req.status = "finished"
        slot.req.finished_at = now
        self.finished.append(slot.req)
        self._on_terminal(slot.req, now)
        self._release(slot)

    def _drop(self, req: Request, status: str, now: float,
              reason: str | None = None) -> Request:
        req.status = status
        req.fail_reason = reason
        req.finished_at = now
        self.dropped.append(req)
        self._on_terminal(req, now)
        return req

    # Whether sweep() releases an in-flight aborted request's slot and
    # pages immediately (continuous) or holds the reservation until the
    # batch drains (static — the reserve-until-drain discipline; the
    # aborted row just stops decoding).
    release_on_abort = True

    def sweep(self, now: float) -> list[Request]:
        """Abort expired and cancelled requests, queued AND in-flight.

        Queued ones are dropped before ever holding a page; in-flight
        ones have their slot aborted and (under continuous batching)
        their pages ownership-checked back into the pool. Returns the
        requests dropped by THIS call, for event logging."""
        dropped = []
        kept: deque[Request] = deque()
        for r in self.queue:
            if r.cancel_requested:
                dropped.append(self._drop(r, "cancelled", now))
            elif r.expired_by(now):
                dropped.append(self._drop(r, "expired", now, "deadline"))
            else:
                kept.append(r)
        self._q_rebuild(kept)
        for slot in self.slots:
            if slot.free or slot.req.terminal:
                continue  # terminal slot awaiting static drain
            r = slot.req
            status = ("cancelled" if r.cancel_requested
                      else "expired" if r.expired_by(now) else None)
            if status is None:
                continue
            dropped.append(self._drop(r, status, now,
                                      None if status == "cancelled"
                                      else "deadline"))
            if self.release_on_abort:
                self._release(slot)
        return dropped

    def enforce_queue_bound(self, now: float) -> list[Request]:
        """Backpressure: keep at most max_queue ARRIVED requests waiting;
        later arrivals beyond the bound are rejected with a terminal
        status (explicit rejection instead of unbounded queue memory).
        Returns the requests rejected by this call.

        Only NEVER-ADMITTED requests count toward (and can be evicted
        by) the bound: a preempted request back in the queue is not an
        arrival — rejecting it would silently drop work the engine
        already served tokens for."""
        if self.max_queue is None:
            return []
        arrived = [r for r in self.queue
                   if r.arrival <= now and r.admitted_at is None]
        excess = len(arrived) - self.max_queue
        if excess <= 0:
            return []
        victims = set(id(r) for r in arrived[-excess:])
        rejected = []
        kept: deque[Request] = deque()
        for r in self.queue:
            if id(r) in victims:
                rejected.append(self._drop(r, "rejected", now, "queue full"))
            else:
                kept.append(r)
        self._q_rebuild(kept)
        return rejected


class ContinuousScheduler(_SchedulerBase):
    """FCFS iteration-level scheduling with recompute preemption."""

    _ACQUIRE = object()  # sentinel: _admit_one acquires for itself

    def _admit_one(self, slot: Slot, req: Request, now: float,
                   acq=_ACQUIRE) -> bool:
        """Try to bind `req` into `slot`: prefix-match (ISSUE 9 — a
        hit shares matched pages and starts cached at the matched
        depth), cover the remaining extent + one decode row from the
        pool (reclaiming LRU-retained prefix pages before giving up),
        bind. Returns False (and leaves no trace) when the pool cannot
        cover the request. A caller that already acquired (the SLO
        scheduler's quota check needs the match depth first) passes
        its acquisition in; on failure it is released either way."""
        if acq is ContinuousScheduler._ACQUIRE:
            acq = None
            if self.prefix is not None:
                acq = self.prefix.acquire(req.prompt, req.rid,
                                          max_tokens=req.context_len - 1)
        f = len(acq.nodes) if acq is not None else 0
        need = pages_for(req.context_len + 1, self.page_size) - f
        if need > self.pool.free_pages and self.prefix is not None:
            self.prefix.reclaim(need - self.pool.free_pages)
        if need > self.pool.free_pages:
            if acq is not None:
                self._release_acq(acq, req.rid)
            return False
        pages = self.pool.try_alloc(
            pages_for(req.context_len, self.page_size) - f, req.rid
        )
        assert pages is not None
        self._bind(slot, req, pages, now, acq=acq)
        return True

    def _release_acq(self, acq, rid) -> None:
        """Undo an acquisition whose admission did not go through."""
        if acq.cow is not None:
            self.prefix.cow_abandon(acq.cow, rid)
        self.prefix.release(acq.nodes, rid)

    def admit(self, now: float) -> list[Slot]:
        """Move arrived queue-head requests into free slots, bounded by
        free pages: a request is admitted only when the pool covers its
        whole prefill extent AND its first decode row (so an admission
        can never preempt an existing sequence on its very first decode
        token). Head-of-line FCFS: if the head doesn't fit, nothing
        behind it jumps ahead — except a head whose grown context can
        NEVER fit the pool (a preempted-and-requeued request that kept
        generating): that one is failed terminally, the livelock guard's
        admission half."""
        bound = []
        for slot in self.slots:
            if not slot.free or not self.queue:
                continue
            req = self.queue[0]
            if req.arrival > now:
                break
            need = pages_for(req.context_len + 1, self.page_size)
            if need > self.pool.usable:
                # Livelock guard: no sequence of preemptions can ever
                # free enough pages — requeueing forever would starve
                # the head-of-line forever. Terminal failure.
                self._q_popleft()
                self._drop(req, "failed", now,
                           f"context of {req.context_len} tokens needs "
                           f"{need} pages; pool owns {self.pool.usable}")
                continue
            if not self._admit_one(slot, req, now):
                # Page-blocked head: record whom it queued behind — the
                # occupants holding the pages whose release will unblock
                # it (the ISSUE 11 blocker edge).
                self._note_blocked(req, "pages", self._occupants())
                break
            self._q_popleft()
            bound.append(slot)
        if (self.queue and self.queue[0].arrival <= now
                and not any(s.free for s in self.slots)):
            # Slot-blocked head: every engine slot is occupied — the
            # head waits on a slot release, not on pages.
            self._note_blocked(self.queue[0], "slots", self._occupants())
        return bound

    def spec_width(self, slot: Slot, k: int) -> int:
        """How many candidate tokens this slot's speculative round may
        verify THIS tick (ISSUE 14): capped by k, by the tokens the
        request still owes (overshooting the budget would write cache
        rows for tokens that can never be emitted), and by the rows the
        slot's pages actually cover (grow_for_decode extends toward k
        opportunistically — a dry pool narrows the round instead of
        preempting; width 1 is exactly the spec-off tick). Always >= 1:
        the spec-off growth loop guaranteed the next row's page."""
        avail = len(slot.pages) * self.page_size - slot.cached
        remaining = slot.req.max_new_tokens - len(slot.req.out)
        return max(1, min(k, remaining, avail))

    def commit_spec(self, slot: Slot, j: int) -> None:
        """Commit a speculative round's j accepted tokens (ISSUE 14):
        advance the written extent, then ROLL BACK pages that now hold
        only rejected-draft rows — freed through the ownership check,
        so a rejected token's KV is never live (never readable through
        any block table, never transferable by a handoff, and the pool
        invariant keeps proving zero leaks). Stale rejected rows inside
        the kept tail page are overwritten by the next round's writes
        before any row can read them (the decode_block write-then-read
        discipline) and masked off until then. Iteration-level only —
        static batching's up-front reservations are never trimmed (the
        engine refuses spec + static)."""
        slot.cached += j
        keep = pages_for(slot.cached, self.page_size)
        if len(slot.pages) > keep:
            surplus = slot.pages[keep:]
            del slot.pages[keep:]
            self.pool.free(surplus, slot.req.rid)

    def preempt(self, slot: Slot, for_rid: int | None = None) -> None:
        """Evict `slot`: free its pages, requeue its request at the
        HEAD (it keeps FCFS priority and its emitted tokens; the grown
        context recomputes via chunked prefill on readmission).
        `for_rid` names the beneficiary — the decoding request whose
        page need forced the eviction (the preempted-by causal edge)."""
        req = slot.req
        req.preemptions += 1
        self.preemptions += 1
        self.preempted_log.append((req.rid, for_rid))
        req.status = "queued"
        self._q_appendleft(req)
        self._release(slot)

    def _choose_victim(self, victims: list[Slot]) -> Slot:
        """FCFS preemption policy: evict the latest-admitted sequence.
        The SLO-aware scheduler overrides this with priority + burn-
        driven choice."""
        return max(victims, key=lambda s: s.admit_seq)

    def grow_for_decode(self, now: float = 0.0,
                        spec_k: int = 1) -> list[Slot]:
        """Give every decoding slot the page its next cache row needs,
        reclaiming LRU-retained prefix pages first (ISSUE 9 — evicted
        cache beats evicted work), then preempting victim sequences
        while the pool is dry. Returns the decoding slots that
        survived, oldest-first (the engine's tick order). A slot that
        is dry and ALONE can never grow — no victim remains — so its
        request is failed terminally (the livelock guard's decode
        half) instead of raising: the engine keeps serving everything
        else.

        spec_k > 1 (ISSUE 14): after the guaranteed next-row growth,
        each survivor is OPPORTUNISTICALLY extended toward the pages
        its speculative verify block wants (k candidate rows, capped at
        the request's remaining budget) — try_alloc + LRU prefix
        reclaim only, NEVER preemption: speculation is a bet, and a bet
        must not evict committed work. Whatever width the pool covers
        is what spec_width reports for the round; a dry pool degrades
        to width 1, which is exactly the spec-off tick — so the
        livelock guard, the preemption policy, and the survivor set are
        bitwise those of a spec-off run."""
        survivors = []
        for slot in sorted(self.decode_slots(), key=lambda s: s.admit_seq):
            if slot.free or not slot.decoding:
                continue  # preempted by an earlier iteration below
            stalled = False
            while slot.pages and len(slot.pages) * self.page_size <= slot.cached:
                got = self.pool.try_alloc(1, slot.req.rid)
                if (got is None and self.prefix is not None
                        and self.prefix.reclaim(1)):
                    got = self.pool.try_alloc(1, slot.req.rid)
                if got is not None:
                    slot.pages.extend(got)
                    continue
                victims = [s for s in self.slots if not s.free]
                victim = self._choose_victim(victims)
                if victim is slot and len(victims) == 1:
                    req = slot.req
                    if pages_for(slot.cached + 1,
                                 self.page_size) > self.pool.usable:
                        # STRUCTURALLY impossible: even owning every
                        # usable page it could not hold the next row.
                        self._drop(
                            req, "failed", now,
                            f"context of {req.context_len} tokens cannot "
                            f"fit the pool ({self.pool.usable} usable "
                            f"pages of {self.page_size}) even alone",
                        )
                        self._release(slot)
                    else:
                        # Transiently dry (e.g. an injected squeeze or a
                        # concurrent prefill holds pages): sit out this
                        # tick — writing without the page would land in
                        # the scratch page and corrupt the read mask.
                        stalled = True
                    break
                self.preempt(victim, for_rid=slot.req.rid)
            if not stalled and not slot.free and slot.decoding:
                survivors.append(slot)
        if spec_k > 1:
            for slot in survivors:
                remaining = slot.req.max_new_tokens - len(slot.req.out)
                want = pages_for(slot.cached + min(spec_k, remaining),
                                 self.page_size)
                while len(slot.pages) < want:
                    got = self.pool.try_alloc(1, slot.req.rid)
                    if (got is None and self.prefix is not None
                            and self.prefix.reclaim(1)):
                        got = self.pool.try_alloc(1, slot.req.rid)
                    if got is None:
                        break  # speculate narrower, never preempt
                    slot.pages.extend(got)
        return survivors


class StaticScheduler(_SchedulerBase):
    """Classic static batching over the same paged storage: admit a
    batch only when ALL slots are free, reserve each request's
    worst-case page extent up front (the contiguous cache's reservation
    discipline, expressed in pages — what makes the tick/latency
    comparison against ContinuousScheduler apples-to-apples), never
    preempt, and hold every slot until the whole batch drains. Aborted
    (expired/cancelled) in-flight rows keep their reservation until the
    drain — they only stop decoding."""

    release_on_abort = False

    def admit(self, now: float) -> list[Slot]:
        if any(not s.free for s in self.slots):
            if self.queue and self.queue[0].arrival <= now:
                # The in-flight batch holds every slot until it drains:
                # the arrived head queues behind ALL of it (ISSUE 11).
                self._note_blocked(self.queue[0], "slots",
                                   self._occupants())
            return []
        bound = []
        for slot in self.slots:
            if not self.queue or self.queue[0].arrival > now:
                break
            req = self.queue[0]
            # Worst-case rows: full context less the final emitted
            # token (which is never written back).
            need = pages_for(req.context_len + req.max_new_tokens - 1,
                             self.page_size)
            if need > self.pool.usable:
                # Even an empty pool could never reserve it: terminal
                # failure (static's livelock-guard analog).
                self._q_popleft()
                self._drop(req, "failed", now,
                           f"worst-case extent of {need} pages exceeds "
                           f"the pool's {self.pool.usable}")
                continue
            pages = self.pool.try_alloc(need, req.rid)
            if pages is None:
                # Reservation-blocked behind the rows already bound into
                # THIS batch (static reserves worst case up front); an
                # empty holder list means no request holds the pages —
                # an injected squeeze does.
                self._note_blocked(req, "pages", self._occupants())
                break
            self._q_popleft()
            self._bind(slot, req, pages, now)
            bound.append(slot)
        return bound

    def grow_for_decode(self, now: float = 0.0,
                        spec_k: int = 1) -> list[Slot]:
        """No growth, no preemption — pages were reserved at admission.
        (spec_k is signature compatibility only: speculation is
        iteration-level — the engine refuses spec + static.)
        Decoding slots whose request is already done (or aborted) still
        HOLD their slot and pages (the batch drains as one); the engine
        keeps them out of the tick's valid mask."""
        return [s for s in self.decode_slots()
                if not s.req.done and not s.req.terminal]

    def batch_done(self) -> bool:
        occupied = [s for s in self.slots if not s.free]
        return bool(occupied) and all(
            s.req.terminal or (s.req.done and s.decoding) for s in occupied
        )

    def drain(self, now: float) -> None:
        for slot in self.slots:
            if slot.free:
                continue
            if slot.req.terminal:
                # Aborted mid-batch: already in `dropped`, only the
                # reservation remained.
                self._release(slot)
            else:
                self.finish(slot, now)


# -- SLO-aware scheduling (ISSUE 9) -------------------------------------


def parse_tenant_priorities(spec: str) -> dict[str, int]:
    """The --tenant-priority grammar: 't0=2,t1=0' -> {'t0': 2,
    't1': 0}. Higher is more protected."""
    out: dict[str, int] = {}
    for part in filter(None, (p.strip() for p in spec.split(","))):
        try:
            tenant, prio = part.split("=")
            out[tenant.strip()] = int(prio)
        except ValueError as e:
            raise ValueError(
                f"--tenant-priority entry {part!r}: want tenant=int "
                "(e.g. 't0=2,t1=0')"
            ) from e
    return out


def parse_tenant_quotas(spec: str) -> tuple[dict[str, int], dict[str, int]]:
    """The --tenant-quota grammar: 't0=pages:8/slots:2,t1=slots:1' ->
    (slot_quota, page_quota) dicts. A dimension left out of a tenant's
    entry is unbounded for that tenant."""
    slot_q: dict[str, int] = {}
    page_q: dict[str, int] = {}
    for part in filter(None, (p.strip() for p in spec.split(","))):
        try:
            tenant, dims = part.split("=")
        except ValueError as e:
            raise ValueError(
                f"--tenant-quota entry {part!r}: want "
                "tenant=dim:int[/dim:int] (e.g. 't0=pages:8/slots:2')"
            ) from e
        for dim in filter(None, (d.strip() for d in dims.split("/"))):
            try:
                kind, bound = dim.split(":")
                bound = int(bound)
            except ValueError as e:
                raise ValueError(
                    f"--tenant-quota {part!r}: bad dimension {dim!r}"
                ) from e
            if kind == "slots":
                slot_q[tenant.strip()] = bound
            elif kind == "pages":
                page_q[tenant.strip()] = bound
            else:
                raise ValueError(
                    f"--tenant-quota {part!r}: dimension {kind!r} must "
                    "be 'slots' or 'pages'"
                )
    return slot_q, page_q


@dataclasses.dataclass(frozen=True)
class SLOPolicy:
    """Configuration for SLOScheduler: per-tenant priority classes
    (higher = more protected; a request's own nonzero `priority`
    overrides its tenant's class), per-tenant admission quotas (slots
    = concurrent engine slots; pages = PRIVATE pages reserved at
    admission — shared prefix pages are free capacity and don't
    count), and the SLO spec whose objectives drive the live burn
    accounting (obs.slo grammar; None = the default availability-only
    spec)."""

    priorities: dict = dataclasses.field(default_factory=dict)
    slot_quota: dict = dataclasses.field(default_factory=dict)
    page_quota: dict = dataclasses.field(default_factory=dict)
    slo_spec: object = None


class SLOScheduler(ContinuousScheduler):
    """SLO-aware admission and preemption over the continuous-batching
    machinery (ISSUE 9, ROADMAP item 2).

    FCFS treats every request identically; at production scale tenants
    carry different objectives and an over-subscribed tenant can starve
    everyone else's SLOs. This scheduler folds every terminal request
    into a live obs.slo.Accountant (the PR-8 measurement layer) and
    lets the numbers drive policy, all host-side and deterministic:

    - ADMISSION reorders arrived requests by (priority class desc,
      tenant burn-rate pressure desc, arrival, rid): protected classes
      first, and within a class the tenant currently burning its error
      budget fastest gets capacity first. Per-tenant quotas bound what
      one tenant can hold (slots and admission-time private pages); a
      quota-blocked tenant is SKIPPED — no head-of-line blocking — but
      a page-blocked top candidate waits (lower-ranked work never
      jumps the page queue).
    - PREEMPTION victims are picked by (priority class asc, tenant
      pressure asc, latest-admitted): the worst-burning tenant's work
      is protected, and FCFS's replace-latest rule only breaks ties.

    Burn pressure is a pure fold over event times the scheduler itself
    stamped, so two identical-seed runs make bitwise-identical
    decisions (the CI determinism gate covers the fleet form)."""

    def __init__(self, *, policy: SLOPolicy | None = None, **kw):
        super().__init__(**kw)
        # Lazy obs import: this module stays light for the fleet's
        # jax-free sim path (obs.slo is itself stdlib-only).
        from ..obs.slo import Accountant, default_spec

        self.policy = policy or SLOPolicy()
        self.acct = Accountant(self.policy.slo_spec or default_spec())
        # Previous admit() moment: the inter-attempt gap is what a
        # quota-blocked candidate's quota_wait_s accrues per skipped
        # attempt (ISSUE 11 — the skip-over share of queue wait).
        self._prev_admit_now: float | None = None

    def _on_terminal(self, req: Request, now: float) -> None:
        for _ in self.acct.observe(terminal_fields(req), now):
            pass

    def _prio(self, req: Request) -> int:
        if req.priority:
            return req.priority
        return self.policy.priorities.get(req.tenant or "default", 0)

    def pressure(self, tenant: str) -> float:
        """The tenant's worst CURRENT burn-rate multiple across its
        objectives and windows — the live 'how close to paging' number
        admission and victim choice read."""
        worst = 0.0
        for (t, metric), we in self.acct.events.items():
            if t != tenant:
                continue
            obj = next(o for o in self.acct.spec.objectives(t)
                       if o.metric == metric)
            for w in we.windows_s:
                worst = max(worst, we.burn_rate(w, obj.target))
        return worst

    def _choose_victim(self, victims: list[Slot]) -> Slot:
        """Victims by (priority class asc, tenant burn pressure asc,
        latest-admitted): the worst-burning tenant's work is protected;
        FCFS's replace-latest rule only breaks ties."""
        return min(victims, key=lambda s: (
            self._prio(s.req),
            self.pressure(s.req.tenant or "default"),
            -s.admit_seq,
        ))

    def transfer_quota_ok(self, req: Request) -> bool:
        """Decode-pool admission for a handed-off request (ISSUE 13):
        the tenant's slot quota binds here exactly as at prefill-pool
        admission — each pool's SLOScheduler owns its own budget, so a
        quota-saturated tenant's transfers wait without blocking other
        tenants' handoffs (the fleet retries placement each tick)."""
        sq = self.policy.slot_quota.get(req.tenant or "default")
        if sq is None:
            return True
        held_slots, _ = self._usage(req.tenant or "default")
        return held_slots < sq

    def _usage(self, tenant: str) -> tuple[int, int]:
        """(slots held, private pages held) by `tenant` right now.
        Shared prefix pages don't count — they are deduplicated
        capacity, not the tenant's reservation."""
        slots_held = pages_held = 0
        for s in self.slots:
            if s.free or (s.req.tenant or "default") != tenant:
                continue
            slots_held += 1
            pages_held += len(s.pages) - len(s.refs)
        return slots_held, pages_held

    def admit(self, now: float) -> list[Slot]:
        bound: list[Slot] = []
        prev, self._prev_admit_now = self._prev_admit_now, now
        delta = max(now - prev, 0.0) if prev is not None else 0.0
        free_slots = deque(s for s in self.slots if s.free)
        if not self.queue:
            return bound
        arrived = [r for r in self.queue if r.arrival <= now]
        if not arrived:
            return bound
        if not free_slots:
            # Slot-blocked: every arrived candidate waits on a slot
            # release. One representative blocked entry (the highest-
            # priority earliest arrival — pressure left out: computing
            # it on every saturated tick is the cost the early return
            # exists to skip) keeps the record volume bounded.
            head = min(arrived, key=lambda r: (-self._prio(r),
                                               r.arrival, r.rid))
            self._note_blocked(head, "slots", self._occupants())
            return bound
        # One sort per tick: pressures are a pure fold over already-
        # observed terminals, so neither the ordering key nor the
        # priority changes mid-admit — only quota USAGE does, and that
        # is updated incrementally below (O(n log n) per tick instead
        # of a re-scan per admitted slot: the storm-scale requirement).
        pressures = {t: self.pressure(t) for t in
                     {r.tenant or "default" for r in arrived}}
        order = sorted(arrived, key=lambda r: (
            -self._prio(r), -pressures[r.tenant or "default"],
            r.arrival, r.rid))
        usage = {t: self._usage(t) for t in pressures}
        taken: set[int] = set()
        for req in order:
            if not free_slots:
                # Ran out of slots mid-order: the next-ranked candidate
                # is slot-blocked behind everything now running.
                self._note_blocked(req, "slots", self._occupants())
                break
            tenant = req.tenant or "default"
            need = pages_for(req.context_len + 1, self.page_size)
            if need > self.pool.usable:
                # The livelock guard, verbatim from the FCFS form.
                taken.add(id(req))
                self._drop(req, "failed", now,
                           f"context of {req.context_len} tokens needs "
                           f"{need} pages; pool owns {self.pool.usable}")
                continue
            sq = self.policy.slot_quota.get(tenant)
            pq = self.policy.page_quota.get(tenant)
            held_slots, held_pages = usage[tenant]
            if sq is not None and held_slots >= sq:
                # Quota skip-over (ISSUE 11): its own causal edge kind —
                # the candidate waits on ITS OWN tenant's occupancy, not
                # on fleet capacity — and its own queue-wait split (the
                # inter-attempt gap accrues as quota_wait_s, clamped to
                # the request's own presence so a late arrival never
                # inherits the whole gap and the quota share stays a
                # subset of its queue wait).
                req.quota_wait_s += min(delta, max(now - req.arrival, 0.0))
                self._note_blocked(req, "quota", self._occupants(tenant))
                continue  # quota-blocked: skip, don't block others
            # The page quota counts PRIVATE pages only (the SLOPolicy
            # contract: shared prefix pages are deduplicated capacity)
            # — so acquire first to learn the match depth, and release
            # if the quota still blocks.
            acq = (self.prefix.acquire(req.prompt, req.rid,
                                       max_tokens=req.context_len - 1)
                   if self.prefix is not None else None)
            alloc_n = (pages_for(req.context_len, self.page_size)
                       - (len(acq.nodes) if acq is not None else 0))
            if pq is not None and held_pages + alloc_n > pq:
                if acq is not None:
                    self._release_acq(acq, req.rid)
                req.quota_wait_s += min(delta, max(now - req.arrival, 0.0))
                self._note_blocked(req, "quota", self._occupants(tenant))
                continue
            slot = free_slots[0]
            if not self._admit_one(slot, req, now, acq=acq):
                # Page-blocked: the top-ranked admissible request
                # waits; nothing below it jumps the page queue.
                self._note_blocked(req, "pages", self._occupants())
                break
            free_slots.popleft()
            taken.add(id(req))
            bound.append(slot)
            usage[tenant] = (held_slots + 1,
                             held_pages + len(slot.pages) - len(slot.refs))
        if taken:
            self._q_rebuild(deque(r for r in self.queue
                                  if id(r) not in taken))
        return bound
