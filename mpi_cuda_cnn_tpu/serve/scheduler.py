"""Iteration-level serving schedulers (Orca, Yu et al., OSDI '22).

Static batching admits a batch, runs it to full drain, then admits the
next: every request pays the longest request's residency, and vacated
slots do no work until the batch ends. Continuous batching reconsiders
the batch EVERY iteration: a finished sequence frees its pages and its
slot immediately, a queued request is admitted into the vacated slot
between ticks, and long prompts prefill in fixed-size chunks interleaved
with decode ticks so token emission never stalls behind an admission.

This module is the POLICY layer and is deliberately jax-free: it moves
Requests between a queue, fixed engine slots, and the PagePool, and the
engine (engine.py) executes whatever the policy exposes each iteration
(`prefill_slot()`, `decode_slots()`). Determinism is part of the
contract — FCFS admission, lowest-admission-order prefill first,
preempt-latest — so the tick-count comparisons in tests/test_serve.py
and the bench are exactly reproducible.

Preemption: when a decoding sequence needs its next page and the pool is
dry, the LATEST-admitted occupied slot is evicted — its pages are freed,
its request (prompt + tokens generated so far) returns to the queue
head, and readmission recomputes the grown context via the normal
chunked prefill (recompute-style preemption: pages-over-wire swapping
has nowhere to go on one chip). Emitted tokens stay emitted; TTFT is
unaffected; only tail latency pays.

Failure-awareness (ISSUE 4): every request carries a terminal `status`.
Orca assumes requests can be aborted mid-flight — here that is real:
per-request deadlines and client cancellation abort queued AND in-flight
requests (`sweep()` — pages ownership-checked back into the pool), a
bounded admission queue rejects arrivals past `max_queue` (backpressure
instead of unbounded memory), admission refuses requests whose prompt
alone exceeds the pool (they could only ever preempt-loop), and a
request whose GROWN context can never fit is failed with a terminal
status instead of being requeued forever (the preemption-livelock
guard). Elastic-serving systems (Varuna, Athlur et al., EuroSys '22)
treat this abort/resume traffic as the steady state, not the exception.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Iterable

import numpy as np

from .paged_cache import PagePool, pages_for


def validate_request(r: Request, *, max_len: int, page_size: int,
                     usable: int) -> None:
    """THE structural-admissibility check, shared by scheduler submit
    and the fleet's up-front workload validation (one spelling, so the
    fleet can never accept a request a replica's submit would then
    raise on mid-run):

    - prompt + max_new_tokens past max_len (block table can't hold it)
    - a prompt alone needing more pages than the pool owns (it could
      never be admitted, let alone decode)
    """
    if r.prompt.size + r.max_new_tokens > max_len:
        raise ValueError(
            f"request {r.rid}: prompt {r.prompt.size} + "
            f"{r.max_new_tokens} new exceeds max_len {max_len}"
        )
    if pages_for(r.prompt.size + 1, page_size) > usable:
        raise ValueError(
            f"request {r.rid}: prompt of {r.prompt.size} tokens "
            f"needs {pages_for(r.prompt.size + 1, page_size)} "
            f"pages but the pool owns {usable} — it can "
            "never be admitted (size the pool or shrink the prompt)"
        )

# A request leaves the system in exactly one of these states.
TERMINAL_STATUSES = ("finished", "expired", "cancelled", "rejected", "failed")


def terminal_fields(r: Request) -> dict:
    """One terminal request as the compact per-tick `terminal` entry
    (ISSUE 8): what the streaming SLO/alert layer folds good/bad events
    from, emitted INSIDE the run (the end-of-run `request` records are
    too late for a burn-rate alert to be actionable). Latency formulas
    match engine.request_record exactly — the two views of one request
    can never disagree. jax-free on purpose: the fleet's sim path and
    the alert engine consume this without importing the engine."""
    return {
        "id": r.rid,
        "tenant": r.tenant or "default",
        "status": r.status,
        "ttft_ms": (None if r.first_token_at is None
                    else round(1e3 * (r.first_token_at - r.arrival), 3)),
        "tpot_ms": (None if r.status != "finished"
                    else round(1e3 * (r.finished_at - r.first_token_at)
                               / max(len(r.out) - 1, 1), 3)),
        "queue_wait_ms": (None if r.admitted_at is None
                          else round(1e3 * (r.admitted_at - r.arrival), 3)),
    }


def tenant_block(requests: Iterable[Request]) -> dict[str, dict]:
    """Per-tenant status/latency counts for a run summary (ISSUE 8),
    shared by ServeResult.summary and FleetResult.summary so the two
    surfaces flatten identically in `mctpu compare`. Untagged requests
    aggregate under "default". Percentiles follow the one serving
    convention (obs.report.pct_nearest, imported lazily — this module
    stays jax-free for the fleet's sim path)."""
    from ..obs.report import pct_nearest

    by_tenant: dict[str, list[Request]] = {}
    for r in requests:
        by_tenant.setdefault(r.tenant or "default", []).append(r)
    out: dict[str, dict] = {}
    for tenant, rs in sorted(by_tenant.items()):
        statuses: dict[str, int] = {}
        for r in rs:
            statuses[r.status] = statuses.get(r.status, 0) + 1
        fin = [r for r in rs if r.status == "finished"]
        ttft = [1e3 * (r.first_token_at - r.arrival) for r in fin]
        tpot = [1e3 * (r.finished_at - r.first_token_at)
                / max(len(r.out) - 1, 1) for r in fin]
        out[tenant] = {
            "requests": len(rs),
            "statuses": statuses,
            "output_tokens": sum(len(r.out) for r in rs),
            "ttft_p50_ms": pct_nearest(ttft, 50),
            "ttft_p99_ms": pct_nearest(ttft, 99),
            "tpot_p50_ms": pct_nearest(tpot, 50),
            "tpot_p99_ms": pct_nearest(tpot, 99),
        }
    return out


@dataclasses.dataclass
class Request:
    """One serving request plus its runtime bookkeeping. `prompt` is a
    1-D int32 array; `out` accumulates emitted tokens (they survive
    preemption — recompute re-prefills prompt + out). `deadline` is an
    absolute time on the engine's clock (same timeline as `arrival`);
    past it the request is dropped/aborted with status "expired".
    `cancel()` requests client-side abort at the next tick boundary.
    `session` is an opaque affinity key (ISSUE 7): the fleet router's
    session-affinity policy keeps one session's requests on one replica
    so its prefix cache stays hot; None means no affinity. `tenant` is
    the traffic-class identity (ISSUE 8): the SLO accounting layer
    buckets good/bad events, latency histograms, and health verdicts by
    it; None renders as "default" in every record and table — a
    single-tenant run needs no tagging."""

    rid: int
    prompt: np.ndarray
    max_new_tokens: int
    arrival: float = 0.0
    deadline: float | None = None
    session: int | str | None = None
    tenant: str | None = None
    out: list[int] = dataclasses.field(default_factory=list)
    status: str = "queued"
    fail_reason: str | None = None
    cancel_requested: bool = False
    admitted_at: float | None = None
    first_token_at: float | None = None
    finished_at: float | None = None
    preemptions: int = 0

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt, np.int32).reshape(-1)
        if self.prompt.size < 1:
            raise ValueError(f"request {self.rid}: empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError(f"request {self.rid}: max_new_tokens < 1")

    @property
    def context_len(self) -> int:
        return self.prompt.size + len(self.out)

    @property
    def done(self) -> bool:
        return len(self.out) >= self.max_new_tokens

    @property
    def terminal(self) -> bool:
        return self.status in TERMINAL_STATUSES

    def cancel(self) -> None:
        """Client cancellation: the scheduler aborts the request at the
        next sweep (queued: dropped; in-flight: slot + pages released)."""
        self.cancel_requested = True

    def expired_by(self, now: float) -> bool:
        return self.deadline is not None and now >= self.deadline


@dataclasses.dataclass
class Slot:
    """One fixed batch row of the engine. `cached` counts cache rows
    written; while cached < target the slot is prefilling (target =
    the request's context length at admission), after that it decodes —
    the current token (last emitted, not yet cached) goes in at row
    `cached` on the next tick."""

    idx: int
    req: Request | None = None
    pages: list[int] = dataclasses.field(default_factory=list)
    cached: int = 0
    target: int = 0
    admit_seq: int = -1

    @property
    def free(self) -> bool:
        return self.req is None

    @property
    def prefilling(self) -> bool:
        return self.req is not None and self.cached < self.target

    @property
    def decoding(self) -> bool:
        return self.req is not None and self.cached >= self.target


class _SchedulerBase:
    def __init__(self, *, slots: int, pool: PagePool, page_size: int,
                 max_len: int, max_queue: int | None = None):
        if slots < 1:
            raise ValueError(f"need at least one slot, got {slots}")
        if max_queue is not None and max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self.slots = [Slot(i) for i in range(slots)]
        self.pool = pool
        self.page_size = page_size
        self.max_len = max_len
        self.max_queue = max_queue
        self.queue: deque[Request] = deque()
        self.finished: list[Request] = []
        # Terminal non-finished requests (expired/cancelled/rejected/
        # failed) — with `finished`, every submitted request lands in
        # exactly one of the two lists.
        self.dropped: list[Request] = []
        self.preemptions = 0
        # rids preempted since the last drain_preempted() — the engine
        # folds them into the tick record it emits for the timeline.
        self.preempted_log: list[int] = []
        self._admit_seq = 0
        # True once any submitted request carried a deadline: lets a
        # caller (the fleet's per-replica step loop) skip the O(queue)
        # sweep() scan on ticks where nothing can possibly expire.
        self.has_deadlines = False

    def submit(self, requests: Iterable[Request]) -> None:
        """Enqueue requests (FCFS by arrival). Structurally impossible
        requests raise ValueError at submission — a clear error beats a
        request that can only ever preempt-loop:

        - prompt + max_new_tokens past max_len (block table can't hold it)
        - a prompt alone needing more pages than the pool owns (it could
          never be admitted, let alone decode)
        """
        reqs = sorted(requests, key=lambda r: (r.arrival, r.rid))
        for r in reqs:
            validate_request(r, max_len=self.max_len,
                             page_size=self.page_size,
                             usable=self.pool.usable)
            if r.deadline is not None:
                self.has_deadlines = True
            self.queue.append(r)

    @property
    def unfinished(self) -> int:
        return len(self.queue) + sum(not s.free for s in self.slots)

    def next_arrival(self) -> float | None:
        return min((r.arrival for r in self.queue), default=None)

    def drain_preempted(self) -> list[int]:
        """rids preempted since the last call (tick-record bookkeeping)."""
        out, self.preempted_log = self.preempted_log, []
        return out

    def prefill_backlog(self) -> int:
        """Prompt tokens admitted but not yet cached — the chunked-
        prefill backlog gauge (how far admissions are ahead of the
        prefill interleave)."""
        return sum(s.target - s.cached for s in self.slots
                   if s.prefilling and not s.req.terminal)

    def prefill_slot(self) -> Slot | None:
        """The earliest-admitted slot still prefilling (FCFS: one
        sequence's prompt finishes before the next's starts, so TTFT
        ordering follows admission ordering). Aborted requests whose
        slot is still held (static's reserve-until-drain) never
        prefill."""
        cands = [s for s in self.slots
                 if s.prefilling and not s.req.terminal]
        return min(cands, key=lambda s: s.admit_seq, default=None)

    def decode_slots(self) -> list[Slot]:
        return [s for s in self.slots if s.decoding]

    def _bind(self, slot: Slot, req: Request, pages: list[int],
              now: float) -> None:
        slot.req = req
        slot.pages = pages
        slot.cached = 0
        slot.target = req.context_len
        slot.admit_seq = self._admit_seq
        self._admit_seq += 1
        req.status = "running"
        if req.admitted_at is None:
            req.admitted_at = now

    def _release(self, slot: Slot) -> None:
        if slot.pages:
            self.pool.free(slot.pages, slot.req.rid)
        slot.req = None
        slot.pages = []
        slot.cached = 0
        slot.target = 0
        slot.admit_seq = -1

    def finish(self, slot: Slot, now: float) -> None:
        slot.req.status = "finished"
        slot.req.finished_at = now
        self.finished.append(slot.req)
        self._release(slot)

    def _drop(self, req: Request, status: str, now: float,
              reason: str | None = None) -> Request:
        req.status = status
        req.fail_reason = reason
        req.finished_at = now
        self.dropped.append(req)
        return req

    # Whether sweep() releases an in-flight aborted request's slot and
    # pages immediately (continuous) or holds the reservation until the
    # batch drains (static — the reserve-until-drain discipline; the
    # aborted row just stops decoding).
    release_on_abort = True

    def sweep(self, now: float) -> list[Request]:
        """Abort expired and cancelled requests, queued AND in-flight.

        Queued ones are dropped before ever holding a page; in-flight
        ones have their slot aborted and (under continuous batching)
        their pages ownership-checked back into the pool. Returns the
        requests dropped by THIS call, for event logging."""
        dropped = []
        kept: deque[Request] = deque()
        for r in self.queue:
            if r.cancel_requested:
                dropped.append(self._drop(r, "cancelled", now))
            elif r.expired_by(now):
                dropped.append(self._drop(r, "expired", now, "deadline"))
            else:
                kept.append(r)
        self.queue = kept
        for slot in self.slots:
            if slot.free or slot.req.terminal:
                continue  # terminal slot awaiting static drain
            r = slot.req
            status = ("cancelled" if r.cancel_requested
                      else "expired" if r.expired_by(now) else None)
            if status is None:
                continue
            dropped.append(self._drop(r, status, now,
                                      None if status == "cancelled"
                                      else "deadline"))
            if self.release_on_abort:
                self._release(slot)
        return dropped

    def enforce_queue_bound(self, now: float) -> list[Request]:
        """Backpressure: keep at most max_queue ARRIVED requests waiting;
        later arrivals beyond the bound are rejected with a terminal
        status (explicit rejection instead of unbounded queue memory).
        Returns the requests rejected by this call.

        Only NEVER-ADMITTED requests count toward (and can be evicted
        by) the bound: a preempted request back in the queue is not an
        arrival — rejecting it would silently drop work the engine
        already served tokens for."""
        if self.max_queue is None:
            return []
        arrived = [r for r in self.queue
                   if r.arrival <= now and r.admitted_at is None]
        excess = len(arrived) - self.max_queue
        if excess <= 0:
            return []
        victims = set(id(r) for r in arrived[-excess:])
        rejected = []
        kept: deque[Request] = deque()
        for r in self.queue:
            if id(r) in victims:
                rejected.append(self._drop(r, "rejected", now, "queue full"))
            else:
                kept.append(r)
        self.queue = kept
        return rejected


class ContinuousScheduler(_SchedulerBase):
    """FCFS iteration-level scheduling with recompute preemption."""

    def admit(self, now: float) -> list[Slot]:
        """Move arrived queue-head requests into free slots, bounded by
        free pages: a request is admitted only when the pool covers its
        whole prefill extent AND its first decode row (so an admission
        can never preempt an existing sequence on its very first decode
        token). Head-of-line FCFS: if the head doesn't fit, nothing
        behind it jumps ahead — except a head whose grown context can
        NEVER fit the pool (a preempted-and-requeued request that kept
        generating): that one is failed terminally, the livelock guard's
        admission half."""
        bound = []
        for slot in self.slots:
            if not slot.free or not self.queue:
                continue
            req = self.queue[0]
            if req.arrival > now:
                break
            need = pages_for(req.context_len + 1, self.page_size)
            if need > self.pool.usable:
                # Livelock guard: no sequence of preemptions can ever
                # free enough pages — requeueing forever would starve
                # the head-of-line forever. Terminal failure.
                self.queue.popleft()
                self._drop(req, "failed", now,
                           f"context of {req.context_len} tokens needs "
                           f"{need} pages; pool owns {self.pool.usable}")
                continue
            if need > self.pool.free_pages:
                break
            pages = self.pool.try_alloc(
                pages_for(req.context_len, self.page_size), req.rid
            )
            assert pages is not None
            self.queue.popleft()
            self._bind(slot, req, pages, now)
            bound.append(slot)
        return bound

    def preempt(self, slot: Slot) -> None:
        """Evict `slot`: free its pages, requeue its request at the
        HEAD (it keeps FCFS priority and its emitted tokens; the grown
        context recomputes via chunked prefill on readmission)."""
        req = slot.req
        req.preemptions += 1
        self.preemptions += 1
        self.preempted_log.append(req.rid)
        req.status = "queued"
        self.queue.appendleft(req)
        self._release(slot)

    def grow_for_decode(self, now: float = 0.0) -> list[Slot]:
        """Give every decoding slot the page its next cache row needs,
        preempting latest-admitted sequences while the pool is dry.
        Returns the decoding slots that survived, oldest-first (the
        engine's tick order). A slot that is dry and ALONE can never
        grow — no victim remains — so its request is failed terminally
        (the livelock guard's decode half) instead of raising: the
        engine keeps serving everything else."""
        survivors = []
        for slot in sorted(self.decode_slots(), key=lambda s: s.admit_seq):
            if slot.free or not slot.decoding:
                continue  # preempted by an earlier iteration below
            stalled = False
            while slot.pages and len(slot.pages) * self.page_size <= slot.cached:
                got = self.pool.try_alloc(1, slot.req.rid)
                if got is not None:
                    slot.pages.extend(got)
                    continue
                victims = [s for s in self.slots if not s.free]
                victim = max(victims, key=lambda s: s.admit_seq)
                if victim is slot and len(victims) == 1:
                    req = slot.req
                    if pages_for(slot.cached + 1,
                                 self.page_size) > self.pool.usable:
                        # STRUCTURALLY impossible: even owning every
                        # usable page it could not hold the next row.
                        self._drop(
                            req, "failed", now,
                            f"context of {req.context_len} tokens cannot "
                            f"fit the pool ({self.pool.usable} usable "
                            f"pages of {self.page_size}) even alone",
                        )
                        self._release(slot)
                    else:
                        # Transiently dry (e.g. an injected squeeze or a
                        # concurrent prefill holds pages): sit out this
                        # tick — writing without the page would land in
                        # the scratch page and corrupt the read mask.
                        stalled = True
                    break
                self.preempt(victim)
            if not stalled and not slot.free and slot.decoding:
                survivors.append(slot)
        return survivors


class StaticScheduler(_SchedulerBase):
    """Classic static batching over the same paged storage: admit a
    batch only when ALL slots are free, reserve each request's
    worst-case page extent up front (the contiguous cache's reservation
    discipline, expressed in pages — what makes the tick/latency
    comparison against ContinuousScheduler apples-to-apples), never
    preempt, and hold every slot until the whole batch drains. Aborted
    (expired/cancelled) in-flight rows keep their reservation until the
    drain — they only stop decoding."""

    release_on_abort = False

    def admit(self, now: float) -> list[Slot]:
        if any(not s.free for s in self.slots):
            return []
        bound = []
        for slot in self.slots:
            if not self.queue or self.queue[0].arrival > now:
                break
            req = self.queue[0]
            # Worst-case rows: full context less the final emitted
            # token (which is never written back).
            need = pages_for(req.context_len + req.max_new_tokens - 1,
                             self.page_size)
            if need > self.pool.usable:
                # Even an empty pool could never reserve it: terminal
                # failure (static's livelock-guard analog).
                self.queue.popleft()
                self._drop(req, "failed", now,
                           f"worst-case extent of {need} pages exceeds "
                           f"the pool's {self.pool.usable}")
                continue
            pages = self.pool.try_alloc(need, req.rid)
            if pages is None:
                break
            self.queue.popleft()
            self._bind(slot, req, pages, now)
            bound.append(slot)
        return bound

    def grow_for_decode(self, now: float = 0.0) -> list[Slot]:
        """No growth, no preemption — pages were reserved at admission.
        Decoding slots whose request is already done (or aborted) still
        HOLD their slot and pages (the batch drains as one); the engine
        keeps them out of the tick's valid mask."""
        return [s for s in self.decode_slots()
                if not s.req.done and not s.req.terminal]

    def batch_done(self) -> bool:
        occupied = [s for s in self.slots if not s.free]
        return bool(occupied) and all(
            s.req.terminal or (s.req.done and s.decoding) for s in occupied
        )

    def drain(self, now: float) -> None:
        for slot in self.slots:
            if slot.free:
                continue
            if slot.req.terminal:
                # Aborted mid-batch: already in `dropped`, only the
                # reservation remained.
                self._release(slot)
            else:
                self.finish(slot, now)
