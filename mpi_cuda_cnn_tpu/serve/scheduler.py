"""Iteration-level serving schedulers (Orca, Yu et al., OSDI '22).

Static batching admits a batch, runs it to full drain, then admits the
next: every request pays the longest request's residency, and vacated
slots do no work until the batch ends. Continuous batching reconsiders
the batch EVERY iteration: a finished sequence frees its pages and its
slot immediately, a queued request is admitted into the vacated slot
between ticks, and long prompts prefill in fixed-size chunks interleaved
with decode ticks so token emission never stalls behind an admission.

This module is the POLICY layer and is deliberately jax-free: it moves
Requests between a queue, fixed engine slots, and the PagePool, and the
engine (engine.py) executes whatever the policy exposes each iteration
(`prefill_slot()`, `decode_slots()`). Determinism is part of the
contract — FCFS admission, lowest-admission-order prefill first,
preempt-latest — so the tick-count comparisons in tests/test_serve.py
and the bench are exactly reproducible.

Preemption: when a decoding sequence needs its next page and the pool is
dry, the LATEST-admitted occupied slot is evicted — its pages are freed,
its request (prompt + tokens generated so far) returns to the queue
head, and readmission recomputes the grown context via the normal
chunked prefill (recompute-style preemption: pages-over-wire swapping
has nowhere to go on one chip). Emitted tokens stay emitted; TTFT is
unaffected; only tail latency pays.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Iterable

import numpy as np

from .paged_cache import PagePool, pages_for


@dataclasses.dataclass
class Request:
    """One serving request plus its runtime bookkeeping. `prompt` is a
    1-D int32 array; `out` accumulates emitted tokens (they survive
    preemption — recompute re-prefills prompt + out)."""

    rid: int
    prompt: np.ndarray
    max_new_tokens: int
    arrival: float = 0.0
    out: list[int] = dataclasses.field(default_factory=list)
    admitted_at: float | None = None
    first_token_at: float | None = None
    finished_at: float | None = None
    preemptions: int = 0

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt, np.int32).reshape(-1)
        if self.prompt.size < 1:
            raise ValueError(f"request {self.rid}: empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError(f"request {self.rid}: max_new_tokens < 1")

    @property
    def context_len(self) -> int:
        return self.prompt.size + len(self.out)

    @property
    def done(self) -> bool:
        return len(self.out) >= self.max_new_tokens


@dataclasses.dataclass
class Slot:
    """One fixed batch row of the engine. `cached` counts cache rows
    written; while cached < target the slot is prefilling (target =
    the request's context length at admission), after that it decodes —
    the current token (last emitted, not yet cached) goes in at row
    `cached` on the next tick."""

    idx: int
    req: Request | None = None
    pages: list[int] = dataclasses.field(default_factory=list)
    cached: int = 0
    target: int = 0
    admit_seq: int = -1

    @property
    def free(self) -> bool:
        return self.req is None

    @property
    def prefilling(self) -> bool:
        return self.req is not None and self.cached < self.target

    @property
    def decoding(self) -> bool:
        return self.req is not None and self.cached >= self.target


class _SchedulerBase:
    def __init__(self, *, slots: int, pool: PagePool, page_size: int,
                 max_len: int):
        if slots < 1:
            raise ValueError(f"need at least one slot, got {slots}")
        self.slots = [Slot(i) for i in range(slots)]
        self.pool = pool
        self.page_size = page_size
        self.max_len = max_len
        self.queue: deque[Request] = deque()
        self.finished: list[Request] = []
        self.preemptions = 0
        self._admit_seq = 0

    def submit(self, requests: Iterable[Request]) -> None:
        reqs = sorted(requests, key=lambda r: (r.arrival, r.rid))
        for r in reqs:
            total = r.prompt.size + r.max_new_tokens
            if total > self.max_len:
                raise ValueError(
                    f"request {r.rid}: prompt {r.prompt.size} + "
                    f"{r.max_new_tokens} new exceeds max_len {self.max_len}"
                )
            self.queue.append(r)

    @property
    def unfinished(self) -> int:
        return len(self.queue) + sum(not s.free for s in self.slots)

    def next_arrival(self) -> float | None:
        return min((r.arrival for r in self.queue), default=None)

    def prefill_slot(self) -> Slot | None:
        """The earliest-admitted slot still prefilling (FCFS: one
        sequence's prompt finishes before the next's starts, so TTFT
        ordering follows admission ordering)."""
        cands = [s for s in self.slots if s.prefilling]
        return min(cands, key=lambda s: s.admit_seq, default=None)

    def decode_slots(self) -> list[Slot]:
        return [s for s in self.slots if s.decoding]

    def _bind(self, slot: Slot, req: Request, pages: list[int],
              now: float) -> None:
        slot.req = req
        slot.pages = pages
        slot.cached = 0
        slot.target = req.context_len
        slot.admit_seq = self._admit_seq
        self._admit_seq += 1
        if req.admitted_at is None:
            req.admitted_at = now

    def _release(self, slot: Slot) -> None:
        if slot.pages:
            self.pool.free(slot.pages, slot.req.rid)
        slot.req = None
        slot.pages = []
        slot.cached = 0
        slot.target = 0
        slot.admit_seq = -1

    def finish(self, slot: Slot, now: float) -> None:
        slot.req.finished_at = now
        self.finished.append(slot.req)
        self._release(slot)


class ContinuousScheduler(_SchedulerBase):
    """FCFS iteration-level scheduling with recompute preemption."""

    def admit(self, now: float) -> list[Slot]:
        """Move arrived queue-head requests into free slots, bounded by
        free pages: a request is admitted only when the pool covers its
        whole prefill extent AND its first decode row (so an admission
        can never preempt an existing sequence on its very first decode
        token). Head-of-line FCFS: if the head doesn't fit, nothing
        behind it jumps ahead."""
        bound = []
        for slot in self.slots:
            if not slot.free or not self.queue:
                continue
            req = self.queue[0]
            if req.arrival > now:
                break
            if pages_for(req.context_len + 1,
                         self.page_size) > self.pool.free_pages:
                break
            pages = self.pool.try_alloc(
                pages_for(req.context_len, self.page_size), req.rid
            )
            assert pages is not None
            self.queue.popleft()
            self._bind(slot, req, pages, now)
            bound.append(slot)
        return bound

    def preempt(self, slot: Slot) -> None:
        """Evict `slot`: free its pages, requeue its request at the
        HEAD (it keeps FCFS priority and its emitted tokens; the grown
        context recomputes via chunked prefill on readmission)."""
        req = slot.req
        req.preemptions += 1
        self.preemptions += 1
        self.queue.appendleft(req)
        self._release(slot)

    def grow_for_decode(self) -> list[Slot]:
        """Give every decoding slot the page its next cache row needs,
        preempting latest-admitted sequences while the pool is dry.
        Returns the decoding slots that survived, oldest-first (the
        engine's tick order)."""
        survivors = []
        for slot in sorted(self.decode_slots(), key=lambda s: s.admit_seq):
            if slot.free or not slot.decoding:
                continue  # preempted by an earlier iteration below
            while slot.pages and len(slot.pages) * self.page_size <= slot.cached:
                got = self.pool.try_alloc(1, slot.req.rid)
                if got is not None:
                    slot.pages.extend(got)
                    continue
                victims = [s for s in self.slots if not s.free]
                victim = max(victims, key=lambda s: s.admit_seq)
                if victim is slot and len(victims) == 1:
                    raise RuntimeError(
                        f"page pool ({self.pool.num_pages} pages of "
                        f"{self.page_size}) cannot hold request "
                        f"{slot.req.rid} alone — size the pool for at "
                        "least one max_len sequence"
                    )
                self.preempt(victim)
            if not slot.free and slot.decoding:
                survivors.append(slot)
        return survivors


class StaticScheduler(_SchedulerBase):
    """Classic static batching over the same paged storage: admit a
    batch only when ALL slots are free, reserve each request's
    worst-case page extent up front (the contiguous cache's reservation
    discipline, expressed in pages — what makes the tick/latency
    comparison against ContinuousScheduler apples-to-apples), never
    preempt, and hold every slot until the whole batch drains."""

    def admit(self, now: float) -> list[Slot]:
        if any(not s.free for s in self.slots):
            return []
        bound = []
        for slot in self.slots:
            if not self.queue or self.queue[0].arrival > now:
                break
            req = self.queue[0]
            # Worst-case rows: full context less the final emitted
            # token (which is never written back).
            need = pages_for(req.context_len + req.max_new_tokens - 1,
                             self.page_size)
            pages = self.pool.try_alloc(need, req.rid)
            if pages is None:
                if not bound:
                    raise RuntimeError(
                        f"page pool ({self.pool.num_pages} pages) cannot "
                        f"hold request {req.rid}'s worst case — static "
                        "batching reserves max extent up front"
                    )
                break
            self.queue.popleft()
            self._bind(slot, req, pages, now)
            bound.append(slot)
        return bound

    def grow_for_decode(self) -> list[Slot]:
        """No growth, no preemption — pages were reserved at admission.
        Decoding slots whose request is already done still HOLD their
        slot and pages (the batch drains as one); the engine keeps
        them out of the tick's valid mask."""
        return [s for s in self.decode_slots() if not s.req.done]

    def batch_done(self) -> bool:
        occupied = [s for s in self.slots if not s.free]
        return bool(occupied) and all(
            s.req.done and s.decoding for s in occupied
        )

    def drain(self, now: float) -> None:
        for slot in self.slots:
            if not slot.free:
                self.finish(slot, now)
