"""Host-side page accounting — the jax-free half of the paged KV cache.

Split out of paged_cache.py (ISSUE 10): the scheduler/prefix-cache
policy layer is declared jax-free (`mctpu lint` MCT001 — it must run in
the fleet's sim storms and offline tools without pulling jax), but its
page-accounting primitive used to live next to the device-side
pools/kernels, so importing PagePool imported jax transitively. The
accounting is pure host bookkeeping; it moves here, and paged_cache
re-exports it so device-side callers keep one import surface.
"""

from __future__ import annotations


def pages_for(tokens: int, page_size: int) -> int:
    """Pages needed to hold `tokens` cache entries (ceil)."""
    return -(-tokens // page_size)


class PagePool:
    """Host-side page accounting: which physical page belongs to which
    owner. Page 0 is the reserved scratch page and is never issued.

    The pool is the safety layer under the scheduler: alloc hands out
    each page exactly once, free verifies ownership (a double free or a
    free of someone else's page raises instead of silently corrupting a
    neighbor sequence), and `check()` asserts the global invariant
    free + allocated == usable after any admit/finish/preempt sequence
    (tests/test_serve.py drives it through all three).

    Prefix sharing (ISSUE 9) adds REFCOUNTED READ-ONLY pages on top of
    the exclusive-owner model: `adopt(..., readonly=True)` transfers a
    full prompt page to the prefix cache and freezes it, `share`/
    `unshare` grant and return per-reader references, and `free`
    refuses any page with live readers. `check()` now also proves
    refcount conservation (every reader entry sits on an owned,
    read-only page, no duplicate grants) and that no writable page is
    ever shared — the copy-on-write safety story in one invariant.
    """

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError(f"num_pages {num_pages} < 2 (page 0 is scratch)")
        self.num_pages = num_pages
        # Pop from the end -> pages issue in ascending order
        # (deterministic layouts for tests and debugging).
        self._free = list(range(num_pages - 1, 0, -1))
        self._owner: dict[int, object] = {}
        self._readers: dict[int, list] = {}   # page -> live reader refs
        self._ro: set[int] = set()            # read-only (shareable) pages

    @property
    def usable(self) -> int:
        return self.num_pages - 1

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def owned_by(self, owner) -> list[int]:
        return [p for p, o in self._owner.items() if o == owner]

    def try_alloc(self, n: int, owner) -> list[int] | None:
        """n pages for `owner`, or None (and no change) if the pool
        cannot cover the request — admission control's primitive."""
        if n < 0:
            raise ValueError(f"cannot allocate {n} pages")
        if n > len(self._free):
            return None
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            self._owner[p] = owner
        return pages

    def free(self, pages: list[int], owner) -> None:
        for p in pages:
            got = self._owner.get(p)
            if got is None:
                raise RuntimeError(f"double free of page {p} (owner {owner})")
            if got != owner:
                raise RuntimeError(
                    f"page {p} is owned by {got}, not {owner} — refusing "
                    "to free another sequence's page"
                )
            if self._readers.get(p):
                raise RuntimeError(
                    f"page {p} still has {len(self._readers[p])} live "
                    f"reader(s) — refusing to free a shared page"
                )
        for p in pages:
            del self._owner[p]
            self._ro.discard(p)
            self._free.append(p)

    # -- refcounted sharing (ISSUE 9) -----------------------------------

    def adopt(self, page: int, old_owner, new_owner, *,
              readonly: bool = False) -> None:
        """Transfer one page's ownership (slot -> prefix cache at
        insert time). readonly=True freezes it: from here on it can be
        shared but never written or handed to a writer again."""
        got = self._owner.get(page)
        if got != old_owner:
            raise RuntimeError(
                f"page {page} is owned by {got}, not {old_owner} — "
                "refusing the ownership transfer"
            )
        self._owner[page] = new_owner
        if readonly:
            self._ro.add(page)

    def freeze(self, page: int, owner) -> None:
        """Mark an owned page read-only WITHOUT an ownership transfer —
        the host-tier readmission primitive (ISSUE 17): the prefix
        cache allocates a fresh device page under its own owner and
        freezes it before restoring spilled content, so the page enters
        the shareable set under the same no-writable-page-shared
        invariant adopt(readonly=True) provides at insert time."""
        got = self._owner.get(page)
        if got != owner:
            raise RuntimeError(
                f"page {page} is owned by {got}, not {owner} — "
                "refusing to freeze it"
            )
        self._ro.add(page)

    def share(self, page: int, reader) -> None:
        """Grant `reader` one reference on a read-only page. Sharing a
        writable page is the corruption this layer exists to prevent —
        it raises."""
        if page not in self._owner:
            raise RuntimeError(f"cannot share unowned page {page}")
        if page not in self._ro:
            raise RuntimeError(
                f"page {page} is writable — refusing to share it "
                "(adopt it read-only first)"
            )
        rl = self._readers.setdefault(page, [])
        if reader in rl:
            raise RuntimeError(
                f"reader {reader} already holds a reference on page {page}"
            )
        rl.append(reader)

    def unshare(self, page: int, reader) -> None:
        """Return `reader`'s reference on a shared page (ownership-
        checked like free: a foreign or double unshare raises)."""
        rl = self._readers.get(page)
        if rl is None or reader not in rl:
            raise RuntimeError(
                f"reader {reader} holds no reference on page {page}"
            )
        rl.remove(reader)
        if not rl:
            del self._readers[page]

    def refs(self, page: int) -> int:
        return len(self._readers.get(page, ()))

    def is_shared(self, page: int) -> bool:
        return page in self._ro

    def check(self) -> None:
        """The no-leak / no-double-book invariant, extended (ISSUE 9)
        with refcount conservation and the no-writable-shared-page
        guarantee."""
        assert len(self._free) + len(self._owner) == self.usable, (
            f"page leak: {len(self._free)} free + {len(self._owner)} "
            f"owned != {self.usable} usable"
        )
        assert not (set(self._free) & set(self._owner)), "page double-booked"
        assert 0 not in self._owner and 0 not in self._free, (
            "scratch page 0 entered circulation"
        )
        # Refcount conservation: every reader entry sits on an owned
        # page, lists are non-empty (emptied lists are deleted), and no
        # reader holds two references on one page.
        for p, rl in self._readers.items():
            assert p in self._owner, f"readers on unowned page {p}"
            assert rl, f"empty reader list retained for page {p}"
            assert len(rl) == len({id(r) if isinstance(r, (list, dict))
                                   else r for r in rl}), (
                f"duplicate reader reference on page {p}"
            )
        # No writable page is ever shared; read-only pages are owned.
        assert set(self._readers) <= self._ro, "writable page shared"
        assert self._ro <= set(self._owner), "read-only page not owned"
