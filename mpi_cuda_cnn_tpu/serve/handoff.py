"""Page-granular KV handoff protocol (ISSUE 13, ROADMAP item 4).

Disaggregated serving splits the fleet by phase — prefill replicas chew
chunked prompts, decode replicas stream tokens (DistServe, Zhong et
al., OSDI '24; Splitwise, Patel et al., ISCA '24) — so TTFT and TPOT
stop contending for the same tick. The seam that split creates is the
KV HANDOFF: a completed prefill's page set must move from the sender's
PagePool to a decode replica's, and a crash of EITHER end mid-transfer
must resolve to exactly-once (the PR-7 fence + re-dispatch contract,
extended to the handoff site).

This module is the protocol's jax-free half (`mctpu lint` MCT001): the
`Handoff` record serve/fleet.py drives through its states, the
per-page content CRCs stamped at seal time and verified at adoption,
and the committed-context CRC the failover resume path now verifies
(it used to re-adopt committed tokens unchecked). The state machine:

    pending  — pages sealed on the sender (slot detached, private pages
               owned by the handoff token, prefix reader references
               transferred to it), per-page CRCs stamped, the rid's
               generation fence REVOKED (nobody may commit in flight);
               waiting for a decode replica with page capacity.
    copying  — receiver chosen, destination pages allocated under the
               handoff token in ITS pool; the transfer is in flight
               for `ticks_left` fleet ticks (the crash window the
               mid-handoff tests aim at).
    done     — CRCs verified, content adopted (cross-engine page copy
               under EngineCompute; pure accounting under SimCompute),
               the request bound decode-ready into a receiver slot, a
               fresh fence epoch granted to the receiver, the sender's
               sealed pages released.
    aborted  — any failure (sender dead, receiver dead, dropped
               transfer, CRC refusal, cancel): both ends' pages are
               released/revoked on whichever incarnations still live,
               and the request re-enters the fleet's re-dispatch queue
               exactly once — it re-prefills elsewhere; a corrupted
               page is refused, never decoded.

The CRC contract: a page's KV rows are a pure function of the token
ids whose positions it covers (the property that makes cross-replica
re-prefill output-exact), so the integrity stamp is the crc32 of that
token slice — computable on both ends host-side, with no device sync.
`kv_corrupt` faults flip a stamped CRC to model a corrupted transfer;
verification at adoption refuses the page set and the request
re-prefills instead of decoding garbage.
"""

from __future__ import annotations

import dataclasses
import zlib

import numpy as np

__all__ = [
    "Handoff", "context_crc", "context_tokens", "handoff_owner",
    "page_crcs", "parse_pools", "verify_page_crcs",
]

POOL_PHASES = ("prefill", "decode")


def parse_pools(spec: str) -> dict[str, int]:
    """The --pools grammar: 'prefill:2,decode:2' -> {"prefill": 2,
    "decode": 2}. Both phases must appear with at least one replica
    each — a pool declared empty is a config error, not a degradation
    (degradation is for pools that EMPTY at runtime)."""
    out: dict[str, int] = {}
    for part in filter(None, (p.strip() for p in spec.split(","))):
        try:
            phase, n = part.split(":")
            phase = phase.strip()
            n = int(n)
        except ValueError as e:
            raise ValueError(
                f"--pools entry {part!r}: want phase:int "
                "(e.g. 'prefill:2,decode:2')"
            ) from e
        if phase not in POOL_PHASES:
            raise ValueError(
                f"--pools phase {phase!r}: want one of {POOL_PHASES}"
            )
        if phase in out:
            raise ValueError(f"--pools phase {phase!r} given twice")
        if n < 1:
            raise ValueError(f"--pools {part!r}: need at least 1 replica")
        out[phase] = n
    missing = [p for p in POOL_PHASES if p not in out]
    if missing:
        raise ValueError(
            f"--pools must name every phase; missing {', '.join(missing)}"
        )
    return out


def handoff_owner(rid: int, hid: int) -> tuple:
    """The PagePool ownership token one handoff's sealed/destination
    pages live under — unique per (request, handoff attempt), so an
    aborted attempt's release can never touch a later attempt's pages."""
    return ("handoff", rid, hid)


def context_tokens(prompt: np.ndarray, out: list[int]) -> np.ndarray:
    return np.concatenate(
        [np.asarray(prompt, np.int32).reshape(-1),
         np.asarray(out, np.int32).reshape(-1)]
    )


def page_crcs(tokens: np.ndarray, cached: int, page_size: int) -> list[int]:
    """Per-page integrity stamps: crc32 over the int32 token ids whose
    KV rows each page holds (rows 0..cached-1; the last emitted token
    is NOT yet written — it rides in the request record and lands in
    the cache on the receiver's first decode tick)."""
    toks = np.asarray(tokens, np.int32).reshape(-1)[:cached]
    return [
        zlib.crc32(toks[i * page_size:(i + 1) * page_size].tobytes())
        for i in range(-(-cached // page_size))
    ]


def verify_page_crcs(stamped: list[int], tokens: np.ndarray, cached: int,
                     page_size: int) -> bool:
    """The receiver's adoption check: recompute the expected stamps
    from the authoritative token stream and compare. Any mismatch —
    count, order, or content — refuses the whole page set."""
    return list(stamped) == page_crcs(tokens, cached, page_size)


def context_crc(prompt: np.ndarray, out: list[int]) -> int:
    """Integrity stamp over a request's committed context (prompt +
    emitted tokens) — stamped when a failover strands the request and
    verified before a resume re-dispatch re-prefills it (the backfill
    of the path that used to re-adopt committed tokens unchecked). A
    mismatch falls back to discard semantics: the committed tokens are
    dropped and regenerated from the prompt, never decoded as-is."""
    return zlib.crc32(context_tokens(prompt, out).tobytes())


@dataclasses.dataclass
class Handoff:
    """One in-flight prefill->decode KV transfer (module doc). The
    fleet owns the state transitions; everything here is data plus the
    two incarnation references the abort path needs to release the
    right pools (a crashed incarnation's pool dies with it — releasing
    into a restarted namesake's pool would corrupt a stranger)."""

    hid: int
    rid: int
    src: str                 # sender replica name
    src_rep: object          # sender Replica INCARNATION
    pages: list              # full ordered block table (content source)
    private: list            # sender pages owned by the handoff token
    nodes: list              # sender prefix nodes (reader refs held)
    cached: int              # KV rows sealed — the receiver's decode start
    crcs: list               # per-page integrity stamps (seal-time)
    owner: tuple             # PagePool ownership token (handoff_owner)
    state: str = "pending"   # pending -> copying -> done | aborted
    dst: str | None = None
    dst_rep: object = None
    dst_pages: list = dataclasses.field(default_factory=list)
    ticks_left: int = 0
    copied: bool = False     # content adopted (bind may still be waiting)
    drop: bool = False       # a handoff_drop fault claimed this transfer
    cancelled: bool = False  # a client cancel landed mid-handoff
