"""Fleet router policy: dispatch, membership, health, and fences.

One engine is one chip; the ROADMAP's north star needs N replicas
behind a router that keeps serving when replicas die (ISSUE 7). This
module is the fleet's POLICY half and is deliberately jax-free and
engine-free: it decides WHERE a request goes and WHOSE outputs count,
while serve/fleet.py owns the replicas that do the work. Everything
here is deterministic — sorted membership, pure hash functions, an
injectable jitter — so a seeded fleet storm produces a bitwise-equal
dispatch trace run to run (the FakeClock contract from PRs 4-6).

Three concerns, one per class group:

- **Dispatch** (`Router.pick`): least-loaded reads each replica's
  queue/slot/page telemetry (the PR-6 MetricsRegistry gauges the
  replica's step loop maintains) and picks the smallest backlog;
  session-affinity uses RENDEZVOUS (highest-random-weight) hashing on
  (session, replica) so one session's requests land on one replica —
  its prefix/KV locality survives other replicas joining or leaving,
  because only keys owned by a departed replica move. Cache-aware
  routing (ISSUE 18, after SGLang's cache-aware load balancer) scores
  candidates by EXPECTED PREFIX OVERLAP: each replica exposes
  `route_keys`, the host-side set of page-aligned prefix keys its
  device tree + host tier currently hold (maintained incrementally by
  serve/prefix_cache.py / serve/host_tier.py from the same
  insert/evict/spill/readmit events they already account), and the
  router walks the request's cumulative chunk keys until the first
  miss — matched chunks × page_size is the prefill the fleet will NOT
  redo. Highest overlap wins, ties break least-loaded-then-name, and
  a zero-overlap request falls back to rendezvous hash affinity (when
  it carries a session) or least-loaded, so membership churn still
  moves only the dead replica's sessions.

- **Membership + health**: replicas heartbeat every tick they step; a
  replica that misses `heartbeat_miss` consecutive ticks is declared
  dead (crashed replicas simply stop beating — detection is the
  router's, not the fault's). Restarts are paced by
  utils/retry.backoff_delay and a replica that keeps flapping has its
  circuit OPENED after `max_flaps` crashes: it is permanently removed
  instead of bouncing the same failure through the fleet forever.

- **Generation-token fences** (`Router.grant` / `fence_ok`): every
  dispatch of a request carries a monotonically increasing epoch;
  exactly ONE (replica, epoch) pair holds a request's fence at a time.
  Re-dispatch bumps the epoch, so a partitioned "zombie" replica that
  keeps generating after failover has every commit refused — no token
  position can ever be generated twice into the authoritative output
  (the exactly-once contract the fleet tests pin).
"""

from __future__ import annotations

import dataclasses
import zlib

import numpy as np

from ..utils.retry import backoff_delay

POLICIES = ("least_loaded", "session", "cache_aware")


def fence_chain(crc: int, *op) -> int:
    """THE fence-epoch chain step (ISSUE 15): one grant/revoke op
    folded into the running crc32 — `("g", rid, name, epoch)` /
    `("r", rid)`. Shared by Router.grant/revoke (producer) and
    obs/replay.py's FleetMirror (reconstruction) so the two can never
    drift on the op serialization."""
    return zlib.crc32(repr(op).encode(), crc)


def fleet_state_digest(members, handoffs, pending: int, redispatch,
                       fence_crc: int, transport=None) -> int:
    """THE canonical fleet/router state digest (ISSUE 15), shared by
    serve/fleet.py (producer) and obs/replay.py (reconstruction):
    `members` is an iterable of (name, phase, draining, alive) in name
    order, `handoffs` of (rid, state, src, dst) in rid order, `pending`
    the undispatched-arrival count, `redispatch` the re-dispatch queue's
    rids in order, and `fence_crc` the router's running generation-fence
    chain (Router.fence_crc — every grant/revoke in commit order, so the
    whole epoch history folds into one number without serializing the
    O(total rids) fence map per tick). `transport` is
    `serve.transport.transport_digest_tuple` of the message bus's
    record block when the fleet runs over the lossy bus (ISSUE 20) —
    None (transport off) preserves the historical 5-tuple spelling
    bit-for-bit."""
    parts = (tuple(members), tuple(handoffs), pending,
             tuple(redispatch), fence_crc)
    if transport is not None:
        parts = parts + (transport,)
    return zlib.crc32(repr(parts).encode())


def stable_hash(*parts) -> int:
    """32-bit FNV-1a over the parts' string forms — a process-stable,
    seed-independent mixer (Python's str hash is randomized per
    process, which would unseat every session on restart)."""
    h = 2166136261
    for part in parts:
        for b in str(part).encode():
            h = ((h ^ b) * 16777619) & 0xFFFFFFFF
        h = (h ^ 0x2E) & 0xFFFFFFFF  # field separator
    return h


@dataclasses.dataclass
class Member:
    """One replica as the router sees it: health bookkeeping. The
    replica object itself (serve/fleet.py) hangs off `replica`; flap
    counts live in Router._flap_history (one authority — they must
    survive deregistration, so a per-Member copy could only go stale)."""

    name: str
    replica: object
    joined_tick: int = 0
    last_beat: int = 0
    draining: bool = False


class CircuitOpen(Exception):
    """Raised by record_crash when a replica exhausts its flap budget."""


class Router:
    """Deterministic dispatch + membership + fencing (see module doc).

    `jitter` has the random.random call shape and feeds
    backoff_delay's de-synchronization term; every current surface
    keeps the default 0.5 — restart pacing stays deterministic, the
    FakeClock contract. The hook exists so a real multi-host deploy
    can de-synchronize restarts without touching the pacing logic."""

    def __init__(self, policy: str = "least_loaded", *,
                 heartbeat_miss: int = 3, backoff_base: float = 0.0,
                 max_flaps: int = 3, jitter=None, page_size: int = 0):
        if policy not in POLICIES:
            raise ValueError(f"policy {policy!r}: want one of {POLICIES}")
        if heartbeat_miss < 1:
            raise ValueError(f"heartbeat_miss must be >= 1, got "
                             f"{heartbeat_miss}")
        if policy == "cache_aware" and page_size < 1:
            raise ValueError("policy 'cache_aware' needs page_size >= 1 "
                             "(the prefix keys are page-aligned)")
        self.policy = policy
        self.page_size = page_size
        # Matched prefix tokens of the LAST cache_aware pick (0 on
        # fallback / other policies): the fleet reads it right after
        # pick() to account route hits. Observability only — never part
        # of any digest, so replay (which re-applies recorded routing,
        # not pick()) is untouched.
        self.last_route_overlap = 0
        self.heartbeat_miss = heartbeat_miss
        self.backoff_base = backoff_base
        self.max_flaps = max_flaps
        self.jitter = jitter if jitter is not None else (lambda: 0.5)
        self.members: dict[str, Member] = {}
        # Flap counts survive deregistration: a restarted replica keeps
        # its crash history, which is what makes the circuit breaker a
        # breaker and not a per-incarnation counter.
        self._flap_history: dict[str, int] = {}
        self.circuit_open: set[str] = set()
        # rid -> (replica name, epoch): the generation-token fence.
        self._fence: dict[int, tuple[str, int]] = {}
        self._epoch: dict[int, int] = {}
        # Running crc32 chain over every grant/revoke in commit order
        # (ISSUE 15): the fence-epoch component of the per-tick fleet
        # state digest. O(1) per fence op; obs/replay.py mirrors the
        # same ops from the trail and must land on the same number.
        self.fence_crc = 0

    # -- membership ----------------------------------------------------

    def register(self, replica, tick: int = 0) -> Member:
        name = replica.name
        if name in self.members:
            raise ValueError(f"replica {name!r} already registered")
        if name in self.circuit_open:
            raise ValueError(f"replica {name!r} is circuit-open")
        m = Member(name=name, replica=replica, joined_tick=tick,
                   last_beat=tick)
        self.members[name] = m
        return m

    def deregister(self, name: str) -> Member:
        return self.members.pop(name)

    def beat(self, name: str, tick: int) -> None:
        self.members[name].last_beat = tick

    def stale(self, tick: int) -> list[Member]:
        """Members that have MISSED `heartbeat_miss` consecutive beats
        — the router's failure detector (a crashed replica stops
        beating; detection lags the crash by the miss budget). The
        check runs BEFORE the current tick's beats land, so a healthy
        member's lag is already 1: missed beats = lag - 1, hence the
        strict comparison (a replica crashed at tick T is declared
        dead at tick T + heartbeat_miss)."""
        return [m for m in sorted(self.members.values(),
                                  key=lambda m: m.name)
                if tick - m.last_beat > self.heartbeat_miss]

    def record_crash(self, name: str) -> float:
        """Account one crash of `name`; returns the backoff delay (s)
        before its restart may rejoin, or raises CircuitOpen once the
        flap budget is exhausted (the replica never comes back)."""
        flaps = self._flap_history.get(name, 0) + 1
        self._flap_history[name] = flaps
        if flaps > self.max_flaps:
            self.circuit_open.add(name)
            raise CircuitOpen(
                f"replica {name} crashed {flaps} times "
                f"(max_flaps={self.max_flaps}); circuit opened"
            )
        return backoff_delay(flaps - 1, self.backoff_base, self.jitter)

    def dispatchable(self, phase: str | None = None) -> list[Member]:
        """Members that may receive NEW work, in deterministic order.
        `phase` restricts to one pool of a disaggregated fleet
        (ISSUE 13): only members whose replica carries that phase tag —
        an empty result is how the fleet detects a collapsed pool and
        degrades to unified serving instead of stalling."""
        return [m for m in sorted(self.members.values(),
                                  key=lambda m: m.name)
                if not m.draining
                and (phase is None
                     or getattr(m.replica, "phase", None) == phase)]

    # -- dispatch ------------------------------------------------------

    def _chunk_keys(self, req) -> list[bytes]:
        """The request's cumulative page-aligned prefix keys, in depth
        order — THE SAME key spelling serve/prefix_cache.py inserts
        (`toks[:(i+1)*ps].tobytes()` over full chunks only), so a
        membership test against a replica's route_keys is exact."""
        toks = np.asarray(req.prompt, np.int32).reshape(-1)
        ps = self.page_size
        return [toks[:(i + 1) * ps].tobytes()
                for i in range(len(toks) // ps)]

    def _overlap(self, member, keys) -> int:
        """Expected prefix-hit tokens of dispatching onto `member`:
        walk the cumulative keys in depth order, stop at the first one
        the replica holds in neither its device tree nor its host tier
        (a deeper chunk can't hit without its parent — the tree is
        prefix-closed, and readmission re-walks from the root)."""
        route = getattr(member.replica, "route_keys", None)
        if not route:
            return 0
        n = 0
        for k in keys:
            if k not in route:
                break
            n += 1
        return n * self.page_size

    def pick(self, req, phase: str | None = None) -> Member | None:
        """The replica `req` should run on, or None when nothing can
        take work. Least-loaded reads each replica's load() (backed by
        its PR-6 registry gauges); session requests rendezvous-hash
        onto the surviving membership; cache_aware (ISSUE 18) takes the
        highest expected prefix overlap, ties broken least-loaded, and
        falls back to hash affinity / least-loaded at zero overlap;
        ties break on name, so identical fleets make identical choices.
        `phase` restricts the candidate set to one pool (ISSUE 13) —
        session affinity then rendezvous-hashes over that pool's
        membership only."""
        self.last_route_overlap = 0
        cands = self.dispatchable(phase)
        if not cands:
            return None
        if self.policy == "cache_aware":
            keys = self._chunk_keys(req)
            if keys:
                scored = [(self._overlap(m, keys), m) for m in cands]
                best = max(s for s, _ in scored)
                if best > 0:
                    self.last_route_overlap = best
                    return min((m for s, m in scored if s == best),
                               key=lambda m: (m.replica.load(), m.name))
            # Zero overlap: deterministic fallback. Hash affinity keeps
            # a cold session pinned (its SECOND turn then scores), and
            # membership changes still move only the dead replica's
            # sessions — the rendezvous property cache scoring alone
            # would not give.
            if req.session is not None:
                return max(cands,
                           key=lambda m: (stable_hash(req.session, m.name),
                                          m.name))
            return min(cands, key=lambda m: (m.replica.load(), m.name))
        if self.policy == "session" and req.session is not None:
            return max(cands,
                       key=lambda m: (stable_hash(req.session, m.name),
                                      m.name))
        return min(cands, key=lambda m: (m.replica.load(), m.name))

    # -- generation-token fences ---------------------------------------

    def grant(self, rid: int, name: str) -> int:
        """Fence `rid`'s generation to replica `name`; returns the new
        epoch. Every dispatch and re-dispatch goes through here —
        epochs only ever move forward."""
        epoch = self._epoch.get(rid, -1) + 1
        self._epoch[rid] = epoch
        self._fence[rid] = (name, epoch)
        self.fence_crc = fence_chain(self.fence_crc, "g", rid, name, epoch)
        return epoch

    def fence_ok(self, rid: int, name: str, epoch: int) -> bool:
        """Whether (name, epoch) still holds `rid`'s fence — checked on
        every token commit and terminal claim; a stale holder (zombie
        or superseded dispatch) is refused."""
        return self._fence.get(rid) == (name, epoch)

    def revoke(self, rid: int) -> None:
        """Invalidate `rid`'s fence IMMEDIATELY (failover harvest, rid
        awaiting re-dispatch): nobody may commit until the next grant —
        the window where a zombie could otherwise race the failover
        shut. The epoch counter is untouched, so the next grant still
        moves forward."""
        self._fence.pop(rid, None)
        self.fence_crc = fence_chain(self.fence_crc, "r", rid)

    def fence_of(self, rid: int) -> tuple[str, int] | None:
        return self._fence.get(rid)
