"""Failure-aware multi-replica serving fleet (ISSUE 7, ROADMAP item 4).

One PagedEngine is one chip. This module puts N single-engine replicas
behind serve/router.py's deterministic policy layer and makes replica
DEATH a scheduled, tested event rather than an outage:

- Each `Replica` wraps its own scheduler + PagePool (the PR-3 policy
  machinery, unchanged) and a pluggable `compute`: `EngineCompute`
  drives a real PagedEngine's jitted prefill/decode programs (each
  replica its own page pools — the one-chip-per-replica model), while
  `SimCompute` replaces the device math with a pure token function of
  (request, position) so a 10^5-request storm runs on CPU in seconds
  with the SCHEDULING — dispatch, paging, preemption, re-dispatch —
  exercised for real. Both computes produce per-request outputs that
  are a pure function of (prompt, params|salt), which is what makes
  the crash-vs-crash-free output-equality proof meaningful.

- `ReplicaCore.step` is the PagedEngine.run loop body restructured as
  one scheduler iteration (sweep -> admit -> one prefill chunk -> one
  decode tick) so the fleet can interleave N replicas on one clock.
  The deadline sweep is skipped on ticks where no submitted request
  carries a deadline and no cancel is pending — the O(queue) scan is
  what would otherwise dominate a storm.

- The `Fleet` loop advances a FakeClock by `tick_s` per tick; every
  decision (router policy, failure detection, backoff, fencing) is
  host-side and deterministic, so two identical-seed runs produce
  bitwise-equal dispatch traces and per-status totals — the property
  CI gates by running the seeded storm twice and `mctpu compare`-ing
  the structural counts at exact equality.

Failure semantics (the exactly-once contract):

- A `replica_crash@fleet.tick:T?replica=K` fault stops replica K. The
  router notices via heartbeat staleness (`heartbeat_miss` ticks), then
  FAILS OVER: the dead replica's non-terminal requests have their
  generation fence revoked, are harvested with their COMMITTED tokens,
  and are re-dispatched exactly once each to surviving replicas —
  `redispatch="resume"` re-prefills prompt + committed output (the
  recompute-preemption path, now across replicas), `"discard"` drops
  the partial output and restarts from the prompt.
- Every token and terminal claim a replica makes passes the router's
  generation-token fence. A crashed-but-partitioned replica
  (``zombie_ticks=N``) keeps stepping after failover; every commit it
  attempts is refused — zero double-generated tokens, pinned by test.
- The crashed replica restarts after utils/retry.backoff_delay and
  rejoins with empty pools; a replica that keeps flapping is
  circuit-opened (permanently removed). `replica_join` scales the
  fleet out elastically; `replica_leave` drains one gracefully.

Disaggregated prefill/decode serving (ISSUE 13, serve/handoff.py):
`pools={"prefill": N, "decode": M}` splits the fleet by phase — the
router dispatches arrivals to the prefill pool, and a completed
prefill's page set moves to a decode replica through a page-granular
handoff (sealed pages under a per-handoff ownership token, per-page
content CRCs verified at adoption, the rid's generation fence revoked
in flight and re-granted to the receiver). A crash of either end
mid-handoff resolves to exactly-once via the same re-dispatch path a
replica crash uses; a pool that EMPTIES (crashes, circuit breaker,
leave, `pool_crash`) degrades affected requests to unified serving on
whatever can take work — with a `degraded` obs event — instead of
stalling, and a repopulated pool logs `restored`.
"""

from __future__ import annotations

import dataclasses
import functools
import json
import zlib
from collections import deque

from ..faults import FakeClock
from ..obs.metrics import MetricsRegistry
from .host_tier import TIER_SPILL_SITE, HostTier
from .handoff import (
    Handoff,
    context_crc,
    context_tokens,
    handoff_owner,
    page_crcs,
    parse_pools,
    verify_page_crcs,
)
from .pool import PagePool
from .prefix_cache import PrefixCache, empty_prefix_fields
from .router import CircuitOpen, Router, fleet_state_digest
from .spec import LookupProposer, empty_spec_fields, run_round
from .transport import TRANSPORT_SITE, TransportBus, transport_digest_tuple
from .scheduler import (
    ContinuousScheduler,
    Request,
    SLOScheduler,
    scheduler_digest,
    tenant_block,
    terminal_fields,
    validate_request,
)

__all__ = [
    "EngineCompute", "Fleet", "FleetResult", "Replica", "ReplicaCore",
    "SimCompute", "parse_pools",
]


# Test-only chaos target (ISSUE 19). When set to "skip-revoke",
# _harvest skips the LAST stranded request's fence revoke on every
# failover: the run itself still behaves (the re-dispatch grant bumps
# the epoch, so the zombie's commits stay refused) but the producer's
# fence_crc chain silently diverges from what the dead-replica record
# advertises — exactly the class of one-op bookkeeping drift the replay
# oracle exists to catch. Nothing in production code paths ever sets
# it; `mctpu chaos --plant` and the planted-bug test flip it via
# chaos.episode's try/finally, and the chaos search must both FIND the
# violation and shrink it to a minimal plan (pinning that the sampler
# reaches the failover site and the shrinker converges).
#
# "skip-dedup" (ISSUE 20) is the transport twin: the bus skips the
# receiver-side seen-check for COMMIT keys, so a duplicated commit
# message applies twice and the authoritative output diverges from the
# SimCompute closed form — the exactly-once canary a single sampled
# msg_dup must expose.
CHAOS_PLANT: str | None = None


def _chaos_plant() -> str | None:
    """Late-bound CHAOS_PLANT read for the transport bus (the chaos
    harness flips the module global AFTER the Fleet — and its bus — is
    constructed)."""
    return CHAOS_PLANT


class SimCompute:
    """Device-free compute: the next token is a pure 32-bit mix of
    (rid, output position, salt) mod vocab. Identical on every replica,
    so a re-dispatched request regenerates exactly the tokens the dead
    replica would have — the sim twin of greedy decode under shared
    weights — while costing nothing, which is what lets the 10^5 storm
    run on this box."""

    def __init__(self, vocab: int = 512, chunk: int = 32, salt: int = 0):
        self.vocab = vocab
        self.chunk = chunk
        self.salt = salt

    def _tok_at(self, req: Request, j: int) -> int:
        h = (req.rid * 1000003 + j * 2654435761 + self.salt * 97
             + int(req.prompt.size) * 8191) & 0xFFFFFFFF
        return h % self.vocab

    def _tok(self, req: Request) -> int:
        return self._tok_at(req, len(req.out))

    def prefill_chunk(self, slot) -> tuple[int, int]:
        n = min(self.chunk, slot.target - slot.cached)
        return n, self._tok(slot.req)

    def decode(self, dslots) -> dict[int, int]:
        return {s.idx: self._tok(s.req) for s in dslots}

    def verify(self, rounds):
        """Speculative verify, sim form (ISSUE 14): the target's pick
        for verify row i is the pure token mix at output position
        len(out) + i — exactly the token the spec-off tick stream would
        emit there, so sim spec-on outputs are bitwise spec-off's for
        any proposer while the variable-length commit/rollback
        machinery runs for real."""
        return [
            [self._tok_at(s.req, len(s.req.out) + i) for i in range(w)]
            for s, _u, w in rounds
        ]

    def copy_page(self, src: int, dst: int) -> None:
        """Sim COW is pure bookkeeping: tokens are a function of
        (rid, position), not of cache contents — the page accounting
        is exercised for real, the device copy has nothing to copy."""

    def adopt_pages(self, src_compute, src_pages, dst_pages) -> None:
        """Sim cross-pool KV transfer (ISSUE 13): accounting-only, like
        COW — tokens are a pure function of (rid, position), so the
        protocol (seal, CRC, adopt, release) is exercised for real
        while the content copy has nothing to move."""


class EngineCompute:
    """Model-backed compute: one PagedEngine (its own page pools) per
    replica; prefill/decode go through the engine's two jitted
    programs via the same run_prefill_chunk/run_decode_tick path
    engine.run uses — one implementation, two drivers."""

    def __init__(self, engine):
        self.engine = engine

    def prefill_chunk(self, slot) -> tuple[int, int]:
        return self.engine.run_prefill_chunk(slot)

    def decode(self, dslots):
        return self.engine.run_decode_tick(dslots)

    def copy_page(self, src: int, dst: int) -> None:
        self.engine.copy_page(src, dst)

    def adopt_pages(self, src_compute, src_pages, dst_pages) -> None:
        """Cross-engine KV page transfer (ISSUE 13): copy the sender
        engine's page rows into this engine's pools at the destination
        indices — the device half of the prefill->decode handoff."""
        self.engine.adopt_pages(src_compute.engine, src_pages, dst_pages)

    def verify(self, rounds):
        """Speculative verify, engine form (ISSUE 14): the batched
        verify program — the engine must have been constructed with
        spec="lookup"/"draft" (the fleet bench's compute factory
        threads --spec through)."""
        return self.engine.run_spec_tick(rounds)


class ReplicaCore:
    """One replica's steppable engine loop over the PR-3 scheduler.

    `on_emit(req, tok, now)` is the fleet's fenced commit hook, called
    AFTER the token lands in the replica-local request (the local copy
    always advances — a zombie replica keeps generating; only the
    fence decides whether the authoritative output accepts it)."""

    def __init__(self, compute, *, slots: int, num_pages: int,
                 page_size: int, max_len: int, max_queue: int | None = None,
                 on_emit=None, check_every: int = 1, prefix: bool = False,
                 policy=None, spec: str = "off", spec_k: int = 8,
                 spec_ngram: int = 2, host_pages: int = 0,
                 tier_fault_poll=None):
        if spec not in ("off", "lookup"):
            # Fleet speculation is the draft-free form: a per-replica
            # draft model is an engine-construction concern (the bench
            # factory could thread one), and the sim storms have no
            # draft to run — "lookup" is the serving-fleet contract.
            raise ValueError(
                f"fleet spec {spec!r}: want 'off' or 'lookup'")
        self.spec = spec
        self.spec_k = spec_k
        self.proposer = (LookupProposer(spec_ngram) if spec != "off"
                         else None)
        self.spec_stats = empty_spec_fields()
        pool = PagePool(num_pages)
        if host_pages > 0 and not prefix:
            raise ValueError(
                "host_pages > 0 without prefix=True — the host tier "
                "spills prefix-tree pages; there is nothing to spill "
                "without the tree"
            )
        self.tier = None
        # Cache-aware routing digest (ISSUE 18): the host-side set of
        # cumulative prefix keys this replica can serve a hit from —
        # device-tree paths plus host-tier keys, maintained
        # incrementally by the cache/tier at their insert/readmit/
        # evict/spill seams. Router.pick's cache_aware scoring reads it
        # via Replica.route_keys; it is NEVER digested (replay
        # re-applies recorded routing decisions, not pick()).
        self.route_keys: set | None = set() if prefix else None
        if host_pages > 0:
            # Per-incarnation tier (ISSUE 17): it dies with the replica
            # like its PagePool — a cold restart comes back with the
            # host tier EMPTY, same as the device tree. Under
            # EngineCompute the tier carries real KV payloads via the
            # replica engine's spill/readmit programs; the sim tier is
            # accounting-only (same schedule, no device rows).
            engine = getattr(compute, "engine", None)
            self.tier = HostTier(
                host_pages,
                spill_fn=engine.spill_page if engine is not None else None,
                readmit_fn=(engine.readmit_page if engine is not None
                            else None),
                fault_poll=tier_fault_poll,
                route_keys=self.route_keys,
            )
        self.prefix = (PrefixCache(pool, page_size, self.tier,
                                   route_keys=self.route_keys)
                       if prefix else None)
        sched_kw = dict(slots=slots, pool=pool, page_size=page_size,
                        max_len=max_len, max_queue=max_queue,
                        prefix=self.prefix)
        if policy is not None:
            self.sched = SLOScheduler(policy=policy, **sched_kw)
        else:
            self.sched = ContinuousScheduler(**sched_kw)
        self.compute = compute
        # Precomputed digest config (ISSUE 15): built once — step()
        # stamps a state digest per tick of a 10^5 storm.
        self._digest_extra = ((1, spec_k) if spec != "off" else (0, 0))
        self.on_emit = on_emit
        # Disaggregated serving hook (ISSUE 13): called when a slot's
        # prefill completes with decode work remaining; returning True
        # means the fleet DETACHED the slot for a cross-pool handoff
        # (prefill was this replica's whole job for the rid).
        self.on_prefill_done = None
        self.check_every = check_every
        self.steps = 0
        self.decode_ticks = 0
        self.prefill_chunks = 0
        self._cancel_pending = False
        self._n_fin = 0
        self._n_drop = 0

    def submit(self, req: Request) -> None:
        self.sched.submit([req])

    def flag_cancel(self) -> None:
        """A cancel() landed on one of this core's requests: force the
        sweep on the next step even with no deadlines in play."""
        self._cancel_pending = True

    @property
    def unfinished(self) -> int:
        return self.sched.unfinished

    def _emit(self, req: Request, tok: int, now: float) -> None:
        req.out.append(tok)
        if req.first_token_at is None:
            req.first_token_at = now
        if self.on_emit is not None:
            self.on_emit(req, tok, now)

    def step(self, now: float):
        """One scheduler iteration (the engine.run body, minus the
        idle/fault/watchdog handling the fleet owns). Returns
        (tick-record fields, newly finished locals, newly dropped
        locals) — the fleet syncs terminal statuses from the tails."""
        sched = self.sched
        self.steps += 1
        progressed = False
        if sched.has_deadlines or self._cancel_pending:
            progressed = bool(sched.sweep(now))
            self._cancel_pending = False
        admitted = [[s.idx, s.req.rid] for s in sched.admit(now)]
        if sched.max_queue is not None:
            progressed |= bool(sched.enforce_queue_bound(now))
        prefill_rec = None
        slot = sched.prefill_slot()
        if slot is not None:
            if slot.cow is not None:
                # COW (ISSUE 9): duplicate the partially matched shared
                # page before the slot's first write (engine.run's rule;
                # SimCompute's copy is accounting-only).
                self.compute.copy_page(*slot.cow)
                sched.cow_complete(slot)
            n, nxt = self.compute.prefill_chunk(slot)
            slot.cached += n
            self.prefill_chunks += 1
            prefill_rec = [slot.idx, slot.req.rid, n]
            progressed = True
            if slot.cached >= slot.target:
                # Prefill complete: adopt the prompt's pages into the
                # prefix tree (ISSUE 9); the first generated token is
                # due now (TTFT at prefill completion — engine.run's
                # rule).
                sched.note_prefill_complete(slot)
                # Sanctioned sync (engine.run's rule): int() only on
                # the completing chunk, where the token is emitted.
                # mctpu: disable=MCT007
                self._emit(slot.req, int(nxt), now)
                prefill_rec.append("emit")
                if slot.req.done:
                    sched.finish(slot, now)
                elif (self.on_prefill_done is not None
                        and self.on_prefill_done(self, slot, now)):
                    # Handed off (ISSUE 13): the fleet sealed the page
                    # set and detached the slot — decode happens on the
                    # receiving pool's replica.
                    pass
        dslots = sched.grow_for_decode(
            now, spec_k=self.spec_k if self.spec != "off" else 1)
        decoded = [[s.idx, s.req.rid] for s in dslots]
        spec_rec = None
        if dslots and self.spec != "off":
            # Speculative round (ISSUE 14): the SAME spec.run_round
            # scaffold engine.run drives — proposal + one batched
            # verify (compute.verify: jitted block on engine replicas,
            # the pure token mix on sim) + greedy acceptance, with
            # commit_spec rolling rejected-draft pages back.
            widths = [sched.spec_width(s, self.spec_k) for s in dslots]
            results = run_round(dslots, widths, self.proposer,
                                self.compute.verify)
            self.decode_ticks += 1
            progressed = True
            spec_rec = []
            for s, w, j, toks_out in results:
                sched.commit_spec(s, j)
                for t in toks_out:
                    self._emit(s.req, t, now)
                spec_rec.append([s.req.rid, w - 1, j - 1])
                self.spec_stats["spec_rounds"] += 1
                self.spec_stats["spec_proposed"] += w - 1
                self.spec_stats["spec_accepted"] += j - 1
                if s.req.done:
                    sched.finish(s, now)
        elif dslots:
            toks = self.compute.decode(dslots)
            self.decode_ticks += 1
            progressed = True
            for s in dslots:
                s.cached += 1
                self._emit(s.req, int(toks[s.idx]), now)
                if s.req.done:
                    sched.finish(s, now)
        preempted_pairs = sched.drain_preempted()
        blocked = sched.drain_blocked()
        prefix_tick = (self.prefix.drain_tick()
                       if self.prefix is not None else None)
        new_fin = sched.finished[self._n_fin:]
        new_drop = sched.dropped[self._n_drop:]
        self._n_fin, self._n_drop = len(sched.finished), len(sched.dropped)
        if self.check_every and self.steps % self.check_every == 0:
            sched.check()
        rec = {
            "queue": len(sched.queue),
            "running": sum(1 for s in sched.slots if not s.free),
            "free_pages": sched.pool.free_pages,
            "admitted": admitted, "prefill": prefill_rec,
            "decoded": decoded,
            "preempted": [v for v, _ in preempted_pairs],
            # Causal edges (ISSUE 11): blocked admission attempts and
            # preemption beneficiaries, same shape as engine.run's tick
            # record so `mctpu explain` folds both trails identically.
            "blocked": [[rid, reason, holders]
                        for rid, reason, holders in blocked],
            "preempted_for": [[v, b] for v, b in preempted_pairs
                              if b is not None],
            "finished": [r.rid for r in new_fin],
            "aborted": [[r.rid, r.status] for r in new_drop],
            "progressed": progressed or bool(admitted or new_fin or new_drop),
            # Flight recorder (ISSUE 15): this replica's end-of-step
            # state digest — the ONE scheduler_digest spelling, stamped
            # on every ReplicaCore tick (zombie steps included while
            # their records still flow) and chained into the fleet
            # summary's state_crc.
            "state_crc": scheduler_digest(sched, extra=self._digest_extra),
        }
        if prefix_tick is not None:
            rec["prefix_hits"] = prefix_tick["hits"]
            # Cumulative tree stats (ISSUE 15): the replay
            # reconstruction derives hit/miss counts itself and adopts
            # the cow/insert/eviction deltas from here (both feed the
            # digest's prefix tuple and the free-page conservation
            # audit).
            rec["prefix"] = {"shared_pages": self.prefix.shared_pages,
                             **self.prefix.stats}
            if self.tier is not None:
                # Host-tier fields (ISSUE 17): cumulative tier counters
                # + occupancy on the same dict, and the tick's
                # readmission markers — engine.run's spelling, so the
                # replay reconstruction and `mctpu trace` fold engine
                # and fleet trails identically.
                rec["prefix"].update(self.tier.stats)
                rec["prefix"]["host_used"] = self.tier.host_used
                rec["prefix_readmits"] = prefix_tick["readmits"]
        if spec_rec is not None:
            rec["spec"] = spec_rec
        return rec, new_fin, new_drop

    def prefix_stats(self) -> dict:
        """Cumulative prefix counters in the flat fleet-summary shape
        (zeros with sharing off — gated metrics exist in every run)."""
        if self.prefix is None:
            return empty_prefix_fields()
        return self.prefix.summary_fields()

    def reset_prefix_stats(self) -> None:
        """Zero the counters after they were banked (retirement at
        failover: a zombie's later activity must not re-bank)."""
        if self.prefix is not None:
            for k in self.prefix.stats:
                self.prefix.stats[k] = 0
        if self.tier is not None:
            for k in self.tier.stats:
                self.tier.stats[k] = 0

    def reset_spec_stats(self) -> None:
        """Spec-counter twin of reset_prefix_stats (retirement at
        failover — a zombie's later rounds must not re-bank)."""
        self.spec_stats = empty_spec_fields()


class Replica:
    """One fleet member: a named ReplicaCore plus the PR-6 registry its
    step loop keeps current — `load()` (what least-loaded dispatch
    reads) is queue depth + running slots FROM THE GAUGES, plus the
    dispatches routed here since the last step (so a burst arriving
    within one tick spreads instead of dog-piling the stalest gauge)."""

    def __init__(self, name: str, compute, *, slots: int, num_pages: int,
                 page_size: int, max_len: int, max_queue: int | None = None,
                 check_every: int = 1, on_emit=None, clock=None,
                 prefix: bool = False, policy=None, phase: str | None = None,
                 spec: str = "off", spec_k: int = 8, spec_ngram: int = 2,
                 host_pages: int = 0, tier_fault_poll=None):
        self.name = name
        # Pool membership of a disaggregated fleet (ISSUE 13):
        # "prefill" | "decode" | None (unified). A restarted
        # incarnation keeps its name's phase.
        self.phase = phase
        self.registry = MetricsRegistry(clock=clock)
        self.core = ReplicaCore(
            compute, slots=slots, num_pages=num_pages, page_size=page_size,
            max_len=max_len, max_queue=max_queue, check_every=check_every,
            on_emit=on_emit, prefix=prefix, policy=policy,
            spec=spec, spec_k=spec_k, spec_ngram=spec_ngram,
            host_pages=host_pages, tier_fault_poll=tier_fault_poll,
        )
        self.alive = True
        self.zombie_until = -1   # fleet tick a partitioned zombie stops at
        self.pending_dispatches = 0
        # Lossy-transport incarnation identity + lease (ISSUE 20): gen
        # distinguishes this object's bus endpoint ("<name>#<gen>")
        # from a restarted successor's; the replica refuses its OWN
        # commits once the fleet tick passes lease_until (renewed by
        # every hb_ack). Both are inert with the bus off.
        self.gen = 0
        self.lease_until = -1

    def _gauge(self, name: str) -> float:
        g = self.registry.gauges.get(name)
        return g.value if g is not None and g.value is not None else 0.0

    def load(self) -> float:
        return (self._gauge("serve.queue_depth")
                + self._gauge("serve.running_slots")
                + self.pending_dispatches)

    @property
    def route_keys(self):
        """The core's routing digest (ISSUE 18) — what Router.pick's
        cache_aware scoring reads; None with the prefix cache off."""
        return self.core.route_keys

    def step(self, now: float):
        rec, new_fin, new_drop = self.core.step(now)
        r = self.registry
        r.set("serve.queue_depth", rec["queue"])
        r.set("serve.running_slots", rec["running"])
        r.set("serve.free_pages", rec["free_pages"])
        if rec["decoded"]:
            r.inc("serve.decode_ticks")
        if rec["prefill"] is not None:
            r.inc("serve.prefill_chunks")
        if rec["preempted"]:
            r.inc("serve.preemptions", len(rec["preempted"]))
        if rec.get("prefix_hits"):
            r.inc("serve.prefix.hits", len(rec["prefix_hits"]))
            r.inc("serve.prefix.hit_tokens",
                  sum(m for _, m in rec["prefix_hits"]))
        if rec.get("spec"):
            r.inc("serve.spec.rounds", len(rec["spec"]))
            r.inc("serve.spec.proposed",
                  sum(p for _, p, _ in rec["spec"]))
            r.inc("serve.spec.accepted_total",
                  sum(a for _, _, a in rec["spec"]))
        self.pending_dispatches = 0
        return rec, new_fin, new_drop


@dataclasses.dataclass
class FleetResult:
    """One fleet run: every submitted request terminal, plus the
    structural counts the determinism gate compares at exact equality
    and the dispatch trace that IS the schedule (crc32-hashable)."""

    requests: list[Request]
    ticks: int
    duration_s: float
    dispatches: int
    redispatches: int
    fenced_discards: int
    crashes: int
    joins: int
    leaves: int
    restarts: int
    circuit_opens: int
    decode_ticks: int
    prefill_chunks: int
    preemptions: int
    replicas_final: int
    # Disaggregated serving (ISSUE 13): completed prefill->decode KV
    # handoffs (+ pages moved), aborted transfers (either end died, the
    # transfer dropped, or a CRC refused adoption), integrity refusals
    # (corrupted handoff pages or resume contexts — never decoded), and
    # requests served unified because a pool was empty. All stamped in
    # every run (zeros on a unified fleet) so the gates can pin them.
    handoffs: int = 0
    handoff_pages: int = 0
    handoffs_aborted: int = 0
    kv_refusals: int = 0
    degraded_unified: int = 0
    pools: dict | None = None
    handoff_log: list[dict] = dataclasses.field(default_factory=list)
    # (tick, rid, replica name, epoch, "dispatch" | "redispatch") —
    # every routing decision in order; bitwise-equal across
    # identical-seed runs (the determinism acceptance).
    dispatch_trace: list[tuple] = dataclasses.field(default_factory=list)
    events: list[dict] = dataclasses.field(default_factory=list)
    replica_log: list[dict] = dataclasses.field(default_factory=list)
    # Transport lifecycle records (ISSUE 20, bus on): partition
    # open/heal moments, logged as the obs `transport` event family.
    transport_log: list[dict] = dataclasses.field(default_factory=list)
    # Fleet-wide prefix-cache structural counters (ISSUE 9): summed
    # across every replica incarnation; zeros with sharing off so the
    # gated metrics exist in every fleet-bench run.
    prefix: dict = dataclasses.field(default_factory=empty_prefix_fields)
    # Fleet-wide speculative-decoding counters (ISSUE 14): same
    # contract — summed across incarnations, zeros with spec off.
    spec: dict = dataclasses.field(default_factory=empty_spec_fields)
    # Flight-recorder chain (ISSUE 15): crc32 chained over every
    # per-tick state digest (router record, then each stepped replica)
    # in emission order — the whole state trajectory as ONE gated
    # number, present on summary-only storms.
    state_crc: int = 0
    # Cache-aware routing counters (ISSUE 18): dispatches whose
    # cache_aware pick scored a positive expected prefix overlap
    # (route_hit_tokens sums the matched tokens). Zeros under any other
    # policy so the gated metrics exist in every fleet-bench run.
    route_hits: int = 0
    route_misses: int = 0
    route_hit_tokens: int = 0
    # Online-autoscaler counters (ISSUE 18): scale decisions applied,
    # the crc32 chain over the (tick, direction, name) decision log,
    # and the cumulative live-member step count the static-vs-
    # autoscaled capacity comparison reads. Zeros without --autoscale
    # (replica_ticks is always counted — a static fleet spends them
    # too).
    scale_ups: int = 0
    scale_downs: int = 0
    scale_crc: int = 0
    replica_ticks: int = 0
    # Lossy-transport counters (ISSUE 20): the message bus's wire
    # accounting plus the lease-refusal count (commits/terminals a
    # replica refused to SEND past its own lease — the isolated-replica
    # proof obligation). All stamped (zeros) with the bus off so the
    # transport gate can pin them in every fleet-bench run.
    msgs_sent: int = 0
    msgs_delivered: int = 0
    msgs_dropped: int = 0
    msgs_duped: int = 0
    msgs_delayed: int = 0
    msgs_deduped: int = 0
    retransmits: int = 0
    lease_refusals: int = 0
    partitions: int = 0
    lease_ticks: int = 0

    @property
    def output_tokens(self) -> int:
        return sum(len(r.out) for r in self.requests)

    @property
    def tokens_per_s(self) -> float:
        return self.output_tokens / max(self.duration_s, 1e-9)

    @functools.cached_property
    def trace_crc(self) -> int:
        """crc32 of the dispatch trace — one number `mctpu compare`
        can gate at exact equality to pin the whole schedule. Cached:
        the CI storm's trace holds ~10^5 tuples and the bench reads
        this twice (the trace is complete once the result exists)."""
        return zlib.crc32(json.dumps(self.dispatch_trace).encode())

    def status_counts(self) -> dict[str, int]:
        counts: dict[str, int] = {}
        for r in self.requests:
            counts[r.status] = counts.get(r.status, 0) + 1
        return counts

    def outputs(self) -> dict[int, list[int]]:
        """rid -> committed tokens (the authoritative, fenced output)."""
        return {r.rid: list(r.out) for r in self.requests}

    def finished_requests(self) -> list[Request]:
        return [r for r in self.requests if r.status == "finished"]

    def request_records(self) -> list[dict]:
        """Per-request obs `request` field dicts, mode="fleet" — built
        by engine.request_record, the ONE record shape report/trace
        consume for engine and fleet runs alike."""
        from .engine import request_record

        return [request_record(r, "fleet")
                for r in sorted(self.requests, key=lambda r: r.rid)]

    def summary(self) -> dict:
        from ..obs.metrics import pct_nearest

        fin = self.finished_requests()
        ttft = [1e3 * (r.first_token_at - r.arrival) for r in fin]
        tpot = [1e3 * (r.finished_at - r.first_token_at)
                / max(len(r.out) - 1, 1) for r in fin]
        return {
            "mode": "fleet",
            "requests": len(self.requests),
            "statuses": self.status_counts(),
            "output_tokens": self.output_tokens,
            "decode_ticks": self.decode_ticks,
            "prefill_chunks": self.prefill_chunks,
            "preemptions": self.preemptions,
            "duration_s": round(self.duration_s, 4),
            "tokens_per_s": round(self.tokens_per_s, 2),
            "ttft_p50_ms": pct_nearest(ttft, 50),
            "ttft_p99_ms": pct_nearest(ttft, 99),
            "tpot_p50_ms": pct_nearest(tpot, 50),
            "tpot_p99_ms": pct_nearest(tpot, 99),
            "replicas": self.replicas_final,
            "fleet_ticks": self.ticks,
            "dispatches": self.dispatches,
            "redispatches": self.redispatches,
            "fenced_discards": self.fenced_discards,
            "crashes": self.crashes,
            "joins": self.joins,
            "leaves": self.leaves,
            "restarts": self.restarts,
            "circuit_opens": self.circuit_opens,
            "trace_crc": self.trace_crc,
            # Per-tick state-digest chain (ISSUE 15): the determinism
            # gates pin it at 0%/equal next to trace_crc/blame_crc.
            "state_crc": self.state_crc,
            # Disaggregated-serving counters (ISSUE 13): flat keys the
            # disagg determinism gate pins at exact equality; zeros on
            # a unified fleet so they exist in every fleet-bench run.
            "handoffs": self.handoffs,
            "handoff_pages": self.handoff_pages,
            "handoffs_aborted": self.handoffs_aborted,
            "kv_refusals": self.kv_refusals,
            "degraded_unified": self.degraded_unified,
            # Cache-aware routing + autoscale counters (ISSUE 18): flat
            # keys the fleet/autoscale determinism gates pin at exact
            # equality; zeros under other policies / without the
            # autoscaler so they exist in every fleet-bench run.
            "route_hits": self.route_hits,
            "route_misses": self.route_misses,
            "route_hit_tokens": self.route_hit_tokens,
            "scale_ups": self.scale_ups,
            "scale_downs": self.scale_downs,
            "scale_crc": self.scale_crc,
            "replica_ticks": self.replica_ticks,
            # Lossy-transport counters (ISSUE 20): flat keys the
            # transport determinism gate pins at exact equality; zeros
            # with the bus off so they exist in every fleet-bench run.
            "msgs_sent": self.msgs_sent,
            "msgs_delivered": self.msgs_delivered,
            "msgs_dropped": self.msgs_dropped,
            "msgs_duped": self.msgs_duped,
            "msgs_delayed": self.msgs_delayed,
            "msgs_deduped": self.msgs_deduped,
            "retransmits": self.retransmits,
            "lease_refusals": self.lease_refusals,
            "partitions": self.partitions,
            "lease_ticks": self.lease_ticks,
            **({"pools": dict(self.pools)} if self.pools else {}),
            # Prefix-sharing counters (ISSUE 9): flat keys the fleet
            # determinism gate pins at exact equality.
            **self.prefix,
            # Speculative-decoding counters (ISSUE 14): flat keys the
            # fleet/spec determinism gates pin at exact equality.
            **self.spec,
            # Per-tenant status/latency counts (ISSUE 8) — same shape
            # and flattening as ServeResult.summary's block.
            "tenants": tenant_block(self.requests),
        }


class Fleet:
    """The router + N replicas on one deterministic clock (module doc).

    `compute_factory(name)` builds each replica's compute (fresh state
    per incarnation — a restarted replica comes back with empty pools).
    `faults` injects replica_crash / replica_join / replica_leave at
    the "fleet.tick" site. Telemetry is opt-in: `registry` aggregates
    fleet-level counters/latency histograms, `fleet_sink` receives one
    router record per tick, `replica_tick_sink` the per-replica tick
    records (mode "fleet/<name>") `mctpu trace` reconstructs from.
    """

    def __init__(self, compute_factory, *, replicas: int = 2,
                 slots: int = 4, num_pages: int = 64, page_size: int = 16,
                 max_len: int = 256, max_queue: int | None = None,
                 policy: str = "least_loaded", heartbeat_miss: int = 3,
                 backoff_base: float = 0.0, max_flaps: int = 3,
                 redispatch: str = "resume", tick_s: float = 1e-3,
                 check_every: int = 1, faults=None, clock: FakeClock | None = None,
                 registry: MetricsRegistry | None = None, fleet_sink=None,
                 replica_tick_sink=None, jitter=None, prefix: bool = False,
                 sched_policy=None, pools: dict[str, int] | str | None = None,
                 handoff_ticks: int = 1, log_handoffs: bool = True,
                 spec: str = "off", spec_k: int = 8, spec_ngram: int = 2,
                 host_pages: int = 0, autoscale=None,
                 transport: bool = False, lease_ticks: int = 0,
                 rto_base: float = 2.0):
        if isinstance(pools, str):
            pools = parse_pools(pools)
        if pools is not None:
            bad = [k for k, v in pools.items()
                   if k not in ("prefill", "decode") or v < 1]
            if bad or set(pools) != {"prefill", "decode"}:
                raise ValueError(
                    f"pools {pools!r}: want {{'prefill': N>=1, "
                    "'decode': M>=1}}"
                )
            replicas = pools["prefill"] + pools["decode"]
        if replicas < 1:
            raise ValueError(f"need at least one replica, got {replicas}")
        if handoff_ticks < 1:
            raise ValueError(f"handoff_ticks must be >= 1, got "
                             f"{handoff_ticks}")
        if redispatch not in ("resume", "discard"):
            raise ValueError(
                f"redispatch {redispatch!r}: want 'resume' or 'discard'")
        if pools is None and faults is not None:
            # The inert-fault contract (ISSUE 7 satellite), extended to
            # the handoff site: fleet.handoff is only polled on a
            # pooled fleet — a unified run would validate the plan and
            # then silently never fire it. (fleet.resume stays legal
            # everywhere: failover resume re-dispatches exist on
            # unified fleets too.)
            inert = [f"{f.kind}@{f.site}"
                     for f in faults.pending("fleet.handoff")]
            if inert:
                raise ValueError(
                    f"fault(s) {', '.join(sorted(set(inert)))} need a "
                    "disaggregated fleet (--pools) — on a unified fleet "
                    "they would silently never fire"
                )
        if host_pages == 0 and faults is not None:
            # Same inert-fault contract, tier leg: without a host tier
            # no spill ever happens, so a tier.spill fault would
            # silently never fire.
            inert = [f"{f.kind}@{f.site}"
                     for f in faults.pending(TIER_SPILL_SITE)]
            if inert:
                raise ValueError(
                    f"fault(s) {', '.join(sorted(set(inert)))} need a "
                    "host tier (--spill / host_pages > 0) — without one "
                    "they would silently never fire"
                )
        if policy == "cache_aware" and not prefix:
            # Inert-config contract, routing leg (ISSUE 18): without
            # the prefix cache no replica ever registers a route key,
            # so cache-aware scoring would silently always fall back.
            raise ValueError(
                "policy 'cache_aware' needs prefix=True "
                "(--prefix-cache) — without the prefix tree there are "
                "no cache keys to route on"
            )
        if transport and pools is not None:
            # Scope cut (ISSUE 20): the handoff control messages of a
            # disaggregated fleet are not bus-routed yet — running both
            # would silently leave the handoff path on the perfect
            # in-process channel, so the combination is refused loudly.
            raise ValueError(
                "transport=True (--transport) does not compose with "
                "--pools yet — the prefill->decode handoff control "
                "plane still uses direct calls"
            )
        if not transport and faults is not None:
            # Inert-fault contract, transport leg: the fleet.transport
            # site is only polled when the message bus exists — with
            # the bus off the fault would validate and silently never
            # fire.
            inert = [f"{f.kind}@{f.site}"
                     for f in faults.pending(TRANSPORT_SITE)]
            if inert:
                raise ValueError(
                    f"fault(s) {', '.join(sorted(set(inert)))} need the "
                    "lossy transport (--transport) — without the "
                    "message bus they would silently never fire"
                )
        if transport:
            if lease_ticks == 0:
                # Default: a lease outlives the detection window by two
                # ticks, so a replica never refuses its own commits
                # while the router still trusts its heartbeats.
                lease_ticks = heartbeat_miss + 2
            if lease_ticks <= heartbeat_miss:
                raise ValueError(
                    f"lease_ticks ({lease_ticks}) must exceed "
                    f"heartbeat_miss ({heartbeat_miss}): a lease "
                    "shorter than the detection window makes a healthy "
                    "replica refuse its own commits"
                )
        if redispatch == "discard" and faults is not None \
                and faults.pending("fleet.resume"):
            # Same contract, resume leg: discard re-dispatches never
            # verify a committed context (there is none to verify), so
            # a fleet.resume fault would silently never fire.
            raise ValueError(
                "kv_corrupt@fleet.resume needs --redispatch resume — "
                "discard re-dispatches carry no committed context, so "
                "the fault would silently never fire"
            )
        self.compute_factory = compute_factory
        # prefix/sched_policy (ISSUE 9): each replica gets its own
        # PrefixCache over its own pool (a restarted incarnation comes
        # back cold) and, with sched_policy, an SLOScheduler instead of
        # FCFS — the same upgrade engine.run applies single-engine.
        # spec (ISSUE 14): per-replica speculative decoding — same
        # geometry discipline as prefix: every replica (and every
        # restarted incarnation) speculates identically, so the
        # dispatch trace stays a pure function of (seed, plan, shape).
        # host_pages (ISSUE 17): per-replica host spill tier, part of
        # the common geometry like the page pool — every incarnation
        # gets its own bounded tier, and a cold restart drops it (the
        # tier dies with the replica, like its pools).
        self.geometry = dict(slots=slots, num_pages=num_pages,
                             page_size=page_size, max_len=max_len,
                             max_queue=max_queue, check_every=check_every,
                             prefix=prefix, policy=sched_policy,
                             spec=spec, spec_k=spec_k,
                             spec_ngram=spec_ngram, host_pages=host_pages)
        self.redispatch = redispatch
        self.tick_s = tick_s
        self.faults = faults
        self.clock = clock if clock is not None else FakeClock()
        self.registry = registry
        self.fleet_sink = fleet_sink
        self.replica_tick_sink = replica_tick_sink
        self.router = Router(policy, heartbeat_miss=heartbeat_miss,
                             backoff_base=backoff_base, max_flaps=max_flaps,
                             jitter=jitter, page_size=page_size)
        # Online autoscaler (ISSUE 18): an object with step()/
        # observe_terminal() (serve/autoscale.py's Autoscaler) or None.
        # It only ever acts through the SAME join/leave machinery the
        # fault plan drives, so replay needs no new event kinds. On a
        # pooled fleet it governs the decode pool (prefill sizing stays
        # the operator's — the autosize frontier picks the split).
        self.autoscaler = autoscale
        # Cache-aware routing counters (ISSUE 18): cumulative fleet-
        # wide hit accounting plus the per-replica split the ROUTER
        # top-panel bars read. Stamped (zeros) in every summary — the
        # gate contract.
        self.route_hits = self.route_misses = 0
        self.route_hit_tokens = 0
        self._route_by: dict[str, list[int]] = {}  # name -> [hits, disp]
        self._route_hits_tick: list[list] = []     # [rid, name, matched]
        # Autoscale counters (ISSUE 18): scale_crc chains every
        # (tick, direction, name) decision in commit order — the
        # scale-event log as ONE gated number.
        self.scale_ups = self.scale_downs = 0
        self.scale_crc = 0
        self.replica_ticks = 0
        self.events: list[dict] = []       # obs `fault` field dicts
        self.replica_log: list[dict] = []  # obs `replica` field dicts
        self.transport_log: list[dict] = []  # obs `transport` dicts
        self.dispatch_trace: list[tuple] = []
        self.dispatches = 0
        self.redispatches = 0
        self.fenced_discards = 0
        self.crashes = self.joins = self.leaves = 0
        self.restarts = self.circuit_opens = 0
        # Disaggregated serving (ISSUE 13): pool membership plan, the
        # in-flight handoff table, and the degradation latches.
        self.pools = pools
        self.handoff_ticks = handoff_ticks
        self._phase_of: dict[str, str | None] = {}
        self._handoffs: dict[int, Handoff] = {}
        self._handoff_seq = 0
        self._resume_seq = 0
        self.handoffs = self.handoff_pages = 0
        self.handoffs_aborted = self.kv_refusals = 0
        # Unique rids served unified because a pool was empty — a SET,
        # so a request that degrades repeatedly (handoff abort, then
        # again at its re-prefill's completion) counts once, matching
        # the summary key's "requests served unified" semantics.
        self._degraded_rids: set[int] = set()
        self._degraded = {"prefill": False, "decode": False}
        # obs `handoff` field dicts. log_handoffs=False keeps the list
        # EMPTY (summary-mode storms: ~2 retained dicts per transfer
        # would be the PR-11 retained-container GC cost all over again
        # for a log nothing reads); the summary counters and registry
        # increments are unaffected.
        self.log_handoffs = log_handoffs
        self.handoff_log: list[dict] = []
        self._handoff_started_tick: list[tuple[int, str]] = []
        self._handoff_done_tick: list[tuple[int, str]] = []
        self._handoff_aborted_tick: list[tuple[int, str]] = []
        # Placement/re-target markers (ISSUE 15): a placement allocates
        # the destination pages and an un-place (bind-time re-target)
        # releases them, both without any other trail event — the
        # replay reconstruction needs the moments to account the
        # receiver pool's free count.
        self._handoff_placed_tick: list[tuple[int, str]] = []
        self._handoff_unplaced_tick: list[tuple[int, str]] = []
        # Flight-recorder chain (ISSUE 15): crc32 chained over every
        # per-tick digest in emission order (fleet/router digest, then
        # each stepped replica's) — the summary's state_crc.
        self.state_chain = 0
        self._retired = [0, 0, 0]  # decode_ticks, prefill_chunks, preempts
        self._retired_prefix = empty_prefix_fields()
        self._retired_spec = empty_spec_fields()
        self._failed_over_tick: list[tuple[int, str]] = []
        self._auth: dict[int, Request] = {}
        # rid -> (holding replica, live local copy): where a cancel()
        # must land (the authoritative object the caller holds is a
        # different Request than the replica-local one in flight).
        self._holder: dict[int, tuple[Replica, Request]] = {}
        self._zombies: list[Replica] = []
        self._pending_restarts: list[tuple[float, str]] = []
        self._next_idx = 0
        self._tick = 0
        # Lossy transport (ISSUE 20): the deterministic message bus the
        # whole control plane speaks over when transport=True. All the
        # state below is inert (bus None, zeros) on a direct-call
        # fleet.
        self.lease_ticks = lease_ticks if transport else 0
        self.bus: TransportBus | None = None
        if transport:
            self.bus = TransportBus(faults=faults, rto_base=rto_base,
                                    plant=_chaos_plant,
                                    on_event=self._on_bus_event)
            self.bus.register("router", self._router_msg)
        self.lease_refusals = 0
        # Incarnation counter per NAME (the bus endpoint "<name>#<gen>"
        # — a restarted replica is a different destination).
        self._gen_of: dict[str, int] = {}
        # rid -> (epoch, {pos: (tok, now)}): commits that arrived ahead
        # of a gap (reordered/delayed); drained in order as the gap
        # fills. rid -> (epoch, payload): terminal claims waiting for
        # their trailing commits.
        self._commit_stash: dict[int, tuple[int, dict]] = {}
        self._pending_terms: dict[int, tuple[int, dict]] = {}
        # Terminal applications since the last drain (the bus delivers
        # inline mid-step; the loop drains these where the direct path
        # would have called _sync_terminal).
        self._synced_now: list[Request] = []
        # This tick's [rid, name] dispatch deliveries to CURRENT
        # incarnations — the fleet-record marker the replay mirror
        # sources queue membership from under transport.
        self._t_delivered: list[list] = []
        # False-positive failovers (ISSUE 20): (replica, name) pairs
        # declared dead by heartbeat staleness while actually ALIVE
        # behind a partition. They keep stepping off-trail (like
        # post-failover zombies) until their lease lapses — every
        # commit they attempt must be lease/fence-refused.
        self._isolated: list[tuple[Replica, str]] = []
        self._partition_events: list[dict] = []
        self._lease_refused_tick: list[list] = []
        if pools is None:
            phases: list[str | None] = [None] * replicas
        else:
            # Deterministic initial membership: r0..r{P-1} prefill,
            # then the decode pool — names keep their phase across
            # restarts (self._phase_of).
            phases = (["prefill"] * pools["prefill"]
                      + ["decode"] * pools["decode"])
        for phase in phases:
            self._join(tick=0, now=0.0, log=False, phase=phase)

    # -- membership ----------------------------------------------------

    def _new_replica(self, name: str) -> Replica:
        # The tier fault hook is fleet-shared (ISSUE 17): every
        # replica's tier polls the ONE injector, each with its own
        # spill sequence — a `kv_corrupt@tier.spill:N` fires on the
        # first tier to reach spill N (deterministic: the fleet steps
        # replicas in name order on one clock).
        poll = None
        if self.faults is not None and self.geometry["host_pages"] > 0:
            poll = functools.partial(self.faults.poll, TIER_SPILL_SITE)
        rep = Replica(name, self.compute_factory(name),
                      clock=self.clock, phase=self._phase_of.get(name),
                      tier_fault_poll=poll, **self.geometry)
        rep.core.on_emit = self._make_emit(rep)
        rep.core.on_prefill_done = self._make_prefill_done(rep)
        if self.bus is not None:
            # Fresh incarnation, fresh bus endpoint: a message in
            # flight to the previous incarnation can never reach this
            # one. The initial lease covers the joining tick (renewed
            # by the first hb_ack).
            rep.gen = self._gen_of.get(name, -1) + 1
            self._gen_of[name] = rep.gen
            rep.lease_until = self._tick + self.lease_ticks
            self.bus.register(self._endpoint(rep),
                              self._make_replica_msg(rep))
        return rep

    def _join(self, *, tick: int, now: float, log: bool = True,
              phase: str | None = None) -> Replica:
        name = f"r{self._next_idx}"
        self._next_idx += 1
        self._phase_of[name] = phase
        rep = self._new_replica(name)
        self.router.register(rep, tick=tick)
        self.joins += log
        if log:
            self._log_replica(name, "join", tick, now,
                              **({"pool": phase} if phase else {}))
        return rep

    def _log_replica(self, name: str, kind: str, tick: int, now: float,
                     **extra) -> None:
        self.replica_log.append({
            "name": name, "kind": kind, "tick": tick,
            "now": round(now, 4), **extra,
        })
        if self.registry is not None:
            self.registry.inc(f"fleet.replica_{kind}")

    # -- fenced commits ------------------------------------------------

    def _make_emit(self, replica: Replica):
        name = replica.name

        def emit(local: Request, tok: int, now: float) -> None:
            if self.bus is not None:
                # Lease fence, sender side (ISSUE 20): past its lease a
                # replica refuses its OWN commit — it does not even
                # send. ReplicaCore._emit appended tok to local.out
                # before calling us, so the commit's position is
                # len-1; the router applies commits in position order
                # (gap-stashed), so reordered delivery cannot misfile
                # a token.
                if self._tick >= replica.lease_until:
                    self.lease_refusals += 1
                    self._lease_refused_tick.append([local.rid, name])
                    return
                self.bus.send(
                    "commit", self._endpoint(replica), "router",
                    {"rid": local.rid, "epoch": local._fleet_epoch,
                     "pos": len(local.out) - 1, "tok": tok, "now": now,
                     "name": name},
                    tick=self._tick,
                    key=(local.rid, "c", local._fleet_epoch,
                         len(local.out) - 1),
                    reliable=True)
                return
            if self.router.fence_ok(local.rid, name, local._fleet_epoch):
                auth = self._auth[local.rid]
                auth.out.append(tok)
                if auth.first_token_at is None:
                    auth.first_token_at = now
            else:
                self.fenced_discards += 1

        return emit

    def _sync_terminal(self, replica: Replica, locals_,
                       now: float) -> list[Request]:
        """Apply a replica's newly terminal local requests to the
        authoritative records — through the fence, so a zombie's
        terminal claims are refused like its tokens. Returns the
        authoritative requests that became terminal by THIS call (the
        fence-accepted set): the caller counts them toward run
        completion and folds them into the tick's `terminal` entries
        for the streaming SLO layer (ISSUE 8)."""
        synced: list[Request] = []
        if self.registry is not None:
            # Lazy: the sim path stays jax-free (engine imports jax).
            from .engine import _observe_request
        for local in locals_:
            if not self.router.fence_ok(local.rid, replica.name,
                                        local._fleet_epoch):
                self.fenced_discards += 1
                continue
            auth = self._auth[local.rid]
            auth.status = local.status
            auth.fail_reason = local.fail_reason
            auth.finished_at = local.finished_at
            auth.preemptions += local.preemptions
            auth.quota_wait_s += local.quota_wait_s
            if auth.admitted_at is None:
                auth.admitted_at = local.admitted_at
            if self.registry is not None:
                _observe_request(self.registry, auth)
            # A terminal rid holds no replica: dropping the holder entry
            # releases the (Replica, local) pair — with EngineCompute a
            # dead incarnation's whole PagedEngine cache would otherwise
            # stay pinned for the rest of the run via finished rids.
            self._holder.pop(local.rid, None)
            synced.append(auth)
        return synced

    # -- lossy transport (ISSUE 20) ------------------------------------

    @staticmethod
    def _endpoint(rep: Replica) -> str:
        return f"{rep.name}#{rep.gen}"

    def _on_bus_event(self, kind: str, fields: dict) -> None:
        # Partition open/heal markers, drained onto the replica log (+
        # registry) by the run loop once it knows the tick's `now`.
        self._partition_events.append({"kind": kind, **fields})

    def _router_msg(self, msg, tick: int) -> None:
        """The router's bus endpoint: heartbeats, commits, terminal
        claims. Commits and terminals pass the SAME generation fence
        the direct path uses — the lease (sender side) and the fence
        (receiver side) together are the exactly-once proof."""
        kind, p = msg.kind, msg.payload
        if kind == "hb":
            member = self.router.members.get(p["name"])
            if member is None:
                return  # unknown / failed-over sender: no ack, no renewal
            rep = member.replica
            if rep.gen != p["gen"] or not rep.alive:
                return
            # Guard against reordered/delayed heartbeats moving
            # last_beat backwards.
            if p["tick"] > member.last_beat:
                self.router.beat(p["name"], p["tick"])
            self.bus.send("hb_ack", "router", msg.src,
                          {"until": tick + self.lease_ticks}, tick=tick)
            return
        if kind == "commit":
            rid, epoch = p["rid"], p["epoch"]
            if not self.router.fence_ok(rid, p["name"], epoch):
                self.fenced_discards += 1
                return
            auth = self._auth[rid]
            if auth.terminal:
                # Post-terminal straggler (its dedup keys were
                # released): the request already left the system.
                return
            pos = p["pos"]
            if pos > len(auth.out):
                # Reordered ahead of a gap: stash until the gap fills.
                # (pos < len can only happen when dedup is bypassed —
                # the skip-dedup canary — and then the duplicate
                # append below is exactly the double-generation the
                # chaos oracle must catch: dedup is load-bearing.)
                ep0, stash = self._commit_stash.get(rid, (epoch, None))
                if stash is None or ep0 != epoch:
                    stash = {}
                    self._commit_stash[rid] = (epoch, stash)
                stash[pos] = (p["tok"], p["now"])
                return
            self._apply_commit(auth, p["tok"], p["now"])
            ep0, stash = self._commit_stash.get(rid, (epoch, None))
            if stash is not None and ep0 == epoch:
                while True:
                    nxt = stash.pop(len(auth.out), None)
                    if nxt is None:
                        break
                    self._apply_commit(auth, nxt[0], nxt[1])
                if not stash:
                    del self._commit_stash[rid]
            self._try_pending_term(rid, epoch)
            return
        if kind == "terminal":
            rid, epoch = p["rid"], p["epoch"]
            if not self.router.fence_ok(rid, p["name"], epoch):
                self.fenced_discards += 1
                return
            if self._auth[rid].terminal:
                return
            if len(self._auth[rid].out) < p["outlen"]:
                # Trailing commits still in flight: exactly-once means
                # the terminal waits for them (retransmission
                # guarantees they arrive while the fence holds).
                self._pending_terms[rid] = (epoch, p)
                return
            self._apply_terminal_msg(p)

    @staticmethod
    def _apply_commit(auth: Request, tok: int, now: float) -> None:
        auth.out.append(tok)
        if auth.first_token_at is None:
            auth.first_token_at = now

    def _try_pending_term(self, rid: int, epoch: int) -> None:
        held = self._pending_terms.get(rid)
        if held is None or held[0] != epoch:
            return
        p = held[1]
        if len(self._auth[rid].out) < p["outlen"]:
            return
        del self._pending_terms[rid]
        # The fence can have moved while the terminal waited (a
        # failover re-dispatched the rid): re-check before applying.
        if not self.router.fence_ok(rid, p["name"], epoch):
            self.fenced_discards += 1
            return
        self._apply_terminal_msg(p)

    def _apply_terminal_msg(self, p: dict) -> None:
        """The bus twin of one _sync_terminal iteration (fence already
        checked): fold the replica-local terminal outcome into the
        authoritative record, exactly once."""
        auth = self._auth[p["rid"]]
        auth.status = p["status"]
        auth.fail_reason = p["fail_reason"]
        auth.finished_at = p["finished_at"]
        auth.preemptions += p["preemptions"]
        auth.quota_wait_s += p["quota_wait_s"]
        if auth.admitted_at is None:
            auth.admitted_at = p["admitted_at"]
        if self.registry is not None:
            from .engine import _observe_request
            _observe_request(self.registry, auth)
        self._holder.pop(p["rid"], None)
        self._commit_stash.pop(p["rid"], None)
        # Terminal rid: its dedup keys are dead weight (the
        # auth.terminal guard above catches post-release stragglers).
        self.bus.release_keys(p["rid"])
        self._synced_now.append(auth)

    def _drain_synced(self) -> list[Request]:
        synced, self._synced_now = self._synced_now, []
        return synced

    def _make_replica_msg(self, rep: Replica):
        def handle(msg, tick: int) -> None:
            if msg.kind == "hb_ack":
                rep.lease_until = max(rep.lease_until,
                                      msg.payload["until"])
                return
            if msg.kind == "dispatch":
                local = msg.payload
                rep.core.submit(local)
                if local.cancel_requested:
                    # A cancel that landed while the dispatch was in
                    # flight re-arms the sweep at delivery (the
                    # send-time flag was consumed by earlier steps).
                    rep.core.flag_cancel()
                member = self.router.members.get(rep.name)
                if member is not None and member.replica is rep:
                    # Delivery marker for the replay mirror — CURRENT
                    # incarnations only: a delivery to an isolated
                    # stale incarnation is off-trail (its records
                    # never sink), like a post-failover zombie's work.
                    self._t_delivered.append([local.rid, rep.name])
        return handle

    def _send_terminals(self, rep: Replica, locals_, tick: int) -> None:
        """Bus twin of the _sync_terminal CALL: each newly terminal
        local becomes a reliable terminal claim — unless the sender's
        lease lapsed, in which case it refuses to claim at all (the
        failover will re-dispatch the rid; lease refusal is what makes
        the false-positive path double-generation-free)."""
        for local in locals_:
            if tick >= rep.lease_until:
                self.lease_refusals += 1
                self._lease_refused_tick.append([local.rid, rep.name])
                continue
            self.bus.send(
                "terminal", self._endpoint(rep), "router",
                {"rid": local.rid, "epoch": local._fleet_epoch,
                 "name": rep.name, "outlen": len(local.out),
                 "status": local.status, "fail_reason": local.fail_reason,
                 "finished_at": local.finished_at,
                 "preemptions": local.preemptions,
                 "quota_wait_s": local.quota_wait_s,
                 "admitted_at": local.admitted_at},
                tick=tick, key=(local.rid, "t", local._fleet_epoch),
                reliable=True)

    # -- prefill->decode KV handoff (ISSUE 13) -------------------------

    def _log_handoff(self, ho: Handoff, state: str, tick: int, now: float,
                     **extra) -> None:
        if self.log_handoffs:
            self.handoff_log.append({
                "rid": ho.rid, "hid": ho.hid, "state": state,
                "src": ho.src, "dst": ho.dst, "pages": len(ho.pages),
                "tick": tick, "now": round(now, 4), **extra,
            })
        if self.registry is not None:
            self.registry.inc(f"fleet.handoff_{state}")

    def _note_degraded(self, pool: str, tick: int, now: float) -> None:
        """Latch + log a pool-collapse degradation exactly once per
        episode: the fleet serves affected requests unified instead of
        stalling; `_check_restored` clears the latch when the pool
        repopulates (restart / join)."""
        if not self._degraded[pool]:
            self._degraded[pool] = True
            self._log_replica(pool, "degraded", tick, now, pool=pool)
            if self.registry is not None:
                self.registry.inc("fleet.degraded")

    def _check_restored(self, tick: int, now: float) -> None:
        if self.pools is None:
            return
        for pool in ("prefill", "decode"):
            if self._degraded[pool] and self.router.dispatchable(pool):
                self._degraded[pool] = False
                self._log_replica(pool, "restored", tick, now, pool=pool)

    def _make_prefill_done(self, replica: Replica):
        def on_done(core: ReplicaCore, slot, now: float) -> bool:
            return self._begin_handoff(replica, core, slot, now)
        return on_done

    def _begin_handoff(self, replica: Replica, core: ReplicaCore, slot,
                       now: float) -> bool:
        """A prefill-pool slot just completed its prefill with decode
        work remaining: seal its page set and open a handoff, or — with
        the decode pool EMPTY — degrade this request to unified serving
        on the prefill replica (return False: the slot keeps decoding
        locally instead of stalling behind a pool that may never come
        back)."""
        if self.pools is None or replica.phase != "prefill":
            return False
        member = self.router.members.get(replica.name)
        if (member is None or member.replica is not replica
                or not replica.alive):
            # A ZOMBIE (or already-failed-over) incarnation completing
            # a prefill must not open a handoff: the failover already
            # re-dispatched its requests, and a zombie-initiated
            # transfer would double-dispatch the rid the moment it
            # aborted (sender_dead) — the exactly-once violation the
            # blame-conservation acceptance caught. The zombie decodes
            # locally instead; every commit it attempts is fenced off.
            return False
        rid0 = slot.req.rid
        if rid0 in self._handoffs or self._auth[rid0].terminal:
            # Defensive: one in-flight transfer per rid, never one for
            # a request that already left the system.
            return False
        tick = self._tick
        if not self.router.dispatchable("decode"):
            self._note_degraded("decode", tick, now)
            self._degraded_rids.add(slot.req.rid)
            return False
        local = slot.req
        rid = local.rid
        hid = self._handoff_seq
        self._handoff_seq += 1
        cached = slot.cached
        owner = handoff_owner(rid, hid)
        # The seal-time integrity stamps, from the SENDER's view of the
        # context (rows 0..cached-1; the just-emitted token is not yet
        # a cache row).
        crcs = page_crcs(context_tokens(local.prompt, local.out), cached,
                         self.geometry["page_size"])
        drop = False
        if self.faults is not None:
            for f in self.faults.poll("fleet.handoff", hid):
                if f.kind == "handoff_drop":
                    drop = True
                elif f.kind == "kv_corrupt":
                    page = min(int(f.arg("page", 0)), len(crcs) - 1)
                    crcs[page] ^= 0x5A5A5A5A
                else:
                    raise ValueError(
                        f"fault kind {f.kind!r} is inert at fleet.handoff"
                    )
        pages, private, nodes = core.sched.detach_for_handoff(slot, owner)
        # Nobody may commit for this rid while its KV is in flight: the
        # per-handoff fence. The receiver gets a fresh epoch at
        # completion; an abort re-grants via the re-dispatch path.
        self.router.revoke(rid)
        auth = self._auth[rid]
        auth.preemptions += local.preemptions
        auth.quota_wait_s += local.quota_wait_s
        if auth.admitted_at is None:
            auth.admitted_at = local.admitted_at
        self._holder.pop(rid, None)
        ho = Handoff(hid=hid, rid=rid, src=replica.name, src_rep=replica,
                     pages=pages, private=private, nodes=nodes,
                     cached=cached, crcs=crcs, owner=owner, drop=drop)
        self._handoffs[rid] = ho
        self._handoff_started_tick.append((rid, replica.name))
        self._log_handoff(ho, "started", tick, now)
        return True

    @staticmethod
    def _can_take(member, ho: Handoff, req: Request) -> bool:
        """THE receiver-capability predicate, shared by handoff
        placement and the bind-time re-target so the two can never
        disagree: a receiver must be able to TAKE the transfer — page
        capacity for the whole set, a free slot, and its own pool's
        admission quota — not merely hold its pages."""
        sched = member.replica.core.sched
        return (member.replica.alive
                and sched.pool.free_pages >= len(ho.pages)
                and any(s.free for s in sched.slots)
                and sched.transfer_quota_ok(req))

    def _src_live(self, ho: Handoff) -> bool:
        m = self.router.members.get(ho.src)
        return (m is not None and m.replica is ho.src_rep
                and m.replica.alive)

    def _dst_live(self, ho: Handoff) -> bool:
        m = self.router.members.get(ho.dst)
        return (m is not None and m.replica is ho.dst_rep
                and m.replica.alive)

    def _abort_handoff(self, ho: Handoff, reason: str, tick: int,
                       now: float, redispatch_q: deque) -> None:
        """Resolve a failed transfer to exactly-once: release whichever
        ends still live (a dead incarnation's pool died with it — the
        receiver's partial adoption is revoked, the sender's sealed
        pages freed), then re-enter the fleet's re-dispatch queue —
        the request re-prefills elsewhere under a fresh fence epoch.
        A corrupted or dropped page set is never decoded."""
        if self._src_live(ho):
            ho.src_rep.core.sched.release_handoff(ho.private, ho.nodes,
                                                  ho.owner)
        if ho.dst_pages and self._dst_live(ho):
            ho.dst_rep.core.sched.pool.free(list(ho.dst_pages), ho.owner)
        ho.state = "aborted"
        self.handoffs_aborted += 1
        auth = self._auth[ho.rid]
        # Resume-path integrity stamp (the handoff abort IS a failover
        # for this rid): the committed context is verified before the
        # re-dispatch re-prefills it.
        auth._ctx_crc = context_crc(auth.prompt, auth.out)
        redispatch_q.append(auth)
        del self._handoffs[ho.rid]
        self._handoff_aborted_tick.append((ho.rid, reason))
        self._log_handoff(ho, "aborted", tick, now, reason=reason)

    def _process_handoffs(self, tick: int, now: float,
                          redispatch_q: deque) -> None:
        """Advance every in-flight handoff one fleet tick (rid order —
        deterministic). Runs BEFORE dispatch, so an abort's re-dispatch
        and a completion's first decode can land this same tick, and
        the tick's fleet record (emitted after) carries the markers
        ordered ahead of any replica emission."""
        for rid in sorted(self._handoffs):
            ho = self._handoffs[rid]
            if not self._src_live(ho):
                # Sender died mid-handoff: the receiver's partial
                # adoption is revoked and the request re-prefills
                # elsewhere (the PR-7 fence + re-dispatch path,
                # extended to the handoff site).
                self._abort_handoff(ho, "sender_dead", tick, now,
                                    redispatch_q)
                continue
            if ho.cancelled:
                self._abort_handoff(ho, "cancelled", tick, now,
                                    redispatch_q)
                continue
            if ho.state == "pending":
                auth = self._auth[rid]
                pool_members = self.router.dispatchable("decode")
                cands = [m for m in pool_members
                         if self._can_take(m, ho, auth)]
                if not pool_members:
                    # Decode pool collapsed while the transfer waited:
                    # degrade — re-prefill lands unified via dispatch.
                    self._note_degraded("decode", tick, now)
                    self._degraded_rids.add(rid)
                    self._abort_handoff(ho, "decode_pool_empty", tick,
                                        now, redispatch_q)
                    continue
                if not cands:
                    continue  # capacity in flight — retry next tick
                member = min(cands,
                             key=lambda m: (m.replica.load(), m.name))
                dst_pages = member.replica.core.sched.pool.try_alloc(
                    len(ho.pages), ho.owner)
                assert dst_pages is not None
                # Counts toward same-tick load like a dispatch: several
                # placements in one tick spread instead of dog-piling
                # the stalest gauge.
                member.replica.pending_dispatches += 1
                ho.dst = member.name
                ho.dst_rep = member.replica
                ho.dst_pages = dst_pages
                ho.state = "copying"
                ho.ticks_left = self.handoff_ticks
                self._handoff_placed_tick.append((rid, member.name))
                continue
            # state == "copying": the transfer is in flight.
            if not self._dst_live(ho):
                # Receiver died mid-handoff: the sender's sealed pages
                # are released and the router re-targets via the
                # re-dispatch path.
                ho.dst_pages = []  # died with the incarnation's pool
                self._abort_handoff(ho, "receiver_dead", tick, now,
                                    redispatch_q)
                continue
            if ho.ticks_left > 0:
                ho.ticks_left -= 1
            if ho.ticks_left > 0:
                continue
            if ho.drop:
                self._abort_handoff(ho, "dropped", tick, now,
                                    redispatch_q)
                continue
            auth = self._auth[rid]
            if not ho.copied:
                # Adoption check FIRST: a page set whose stamps do not
                # match the authoritative context is refused — the
                # request re-prefills, garbage is never decoded.
                if not verify_page_crcs(
                        ho.crcs, context_tokens(auth.prompt, auth.out),
                        ho.cached, self.geometry["page_size"]):
                    self.kv_refusals += 1
                    self._abort_handoff(ho, "kv_corrupt", tick, now,
                                        redispatch_q)
                    continue
                ho.dst_rep.core.compute.adopt_pages(
                    ho.src_rep.core.compute, ho.pages, ho.dst_pages)
                ho.copied = True
            local = Request(rid=rid, prompt=auth.prompt,
                            max_new_tokens=auth.max_new_tokens,
                            arrival=auth.arrival, deadline=auth.deadline,
                            session=auth.session, tenant=auth.tenant)
            local.out = list(auth.out)
            local.admitted_at = auth.admitted_at
            slot = ho.dst_rep.core.sched.bind_transfer(
                local, ho.dst_pages, ho.cached, ho.owner, now)
            if slot is None:
                # The receiver filled up (slots or quota) between
                # placement and completion. If ANOTHER decode replica
                # could take the transfer right now, re-target instead
                # of pinning pages on the stalled one: release the
                # destination pages and return to pending (the content
                # re-copies — correctness over the wasted copy).
                others = [
                    m for m in self.router.dispatchable("decode")
                    if m.replica is not ho.dst_rep
                    and self._can_take(m, ho, local)
                ]
                if others:
                    ho.dst_rep.core.sched.pool.free(list(ho.dst_pages),
                                                    ho.owner)
                    self._handoff_unplaced_tick.append((rid, ho.dst))
                    ho.dst = None
                    ho.dst_rep = None
                    ho.dst_pages = []
                    ho.copied = False
                    ho.state = "pending"
                continue
            epoch = self.router.grant(rid, ho.dst)
            local._fleet_epoch = epoch
            self._holder[rid] = (ho.dst_rep, local)
            if auth.cancel_requested:
                local.cancel()
                ho.dst_rep.core.flag_cancel()
            ho.state = "done"
            self.handoffs += 1
            self.handoff_pages += len(ho.pages)
            if self._src_live(ho):
                ho.src_rep.core.sched.release_handoff(
                    ho.private, ho.nodes, ho.owner)
            del self._handoffs[rid]
            self._handoff_done_tick.append((rid, ho.dst))
            self._log_handoff(ho, "done", tick, now)

    # -- dispatch ------------------------------------------------------

    def _dispatch(self, req: Request, *, tick: int,
                  redispatch: bool) -> str | None:
        """Place `req` on a replica; returns the member NAME (the
        flight-recorder record needs the routing decision's target, not
        just that one was made) or None when nothing can take work."""
        phase = "prefill" if self.pools is not None else None
        member = self.router.pick(req, phase)
        if member is None and phase is not None:
            # Prefill pool empty (crashes / circuit breaks / leaves):
            # degrade this request to unified serving on whatever can
            # take work instead of stalling behind the dead pool.
            member = self.router.pick(req)
            if member is not None:
                now = self.clock() - self._t0
                self._note_degraded("prefill", tick, now)
                self._degraded_rids.add(req.rid)
        if member is None:
            return None
        if self.router.policy == "cache_aware":
            # Route accounting (ISSUE 18): last_route_overlap is the
            # matched prefix tokens of the pick above (0 on fallback);
            # a degraded unified re-pick overwrote it, so the read here
            # always describes the decision that actually placed `req`.
            matched = self.router.last_route_overlap
            st = self._route_by.setdefault(member.name, [0, 0])
            st[1] += 1
            if matched > 0:
                st[0] += 1
                self.route_hits += 1
                self.route_hit_tokens += matched
                self._route_hits_tick.append([req.rid, member.name,
                                              matched])
            else:
                self.route_misses += 1
            if self.registry is not None:
                self.registry.inc("fleet.route_hits" if matched > 0
                                  else "fleet.route_misses")
        if redispatch and self.redispatch == "resume" and req.out:
            # KV transfer integrity, failover leg (ISSUE 13): the
            # committed context a resume re-dispatch re-prefills is
            # verified against the stamp taken when the request was
            # stranded — it used to be re-adopted unchecked. A
            # mismatch (or an injected kv_corrupt@fleet.resume) falls
            # back to discard semantics: the tokens are regenerated
            # from the prompt, never decoded as-is.
            stamp = getattr(req, "_ctx_crc", None)
            if self.faults is not None:
                for f in self.faults.poll("fleet.resume",
                                          self._resume_seq):
                    if f.kind != "kv_corrupt":
                        raise ValueError(
                            f"fault kind {f.kind!r} is inert at "
                            "fleet.resume"
                        )
                    stamp = (stamp ^ 0x5A5A5A5A) if stamp is not None \
                        else 1
            self._resume_seq += 1
            if stamp is None or stamp != context_crc(req.prompt, req.out):
                self.kv_refusals += 1
                self.events.append({
                    "kind": "resume_refused", "id": req.rid,
                    "tokens_discarded": len(req.out),
                })
                req.out.clear()
                req.first_token_at = None
        epoch = self.router.grant(req.rid, member.name)
        if redispatch and self.redispatch == "discard":
            req.out.clear()
            req.first_token_at = None
        local = Request(rid=req.rid, prompt=req.prompt,
                        max_new_tokens=req.max_new_tokens,
                        arrival=req.arrival, deadline=req.deadline,
                        session=req.session, tenant=req.tenant)
        local.out = list(req.out)
        # A request that was ever admitted keeps that mark across
        # failover (even under discard, which regenerates the tokens):
        # enforce_queue_bound exempts admitted_at-bearing requests, and
        # a re-dispatch must never be backpressure-rejected as a fresh
        # arrival when the fleet already served tokens for it.
        local.admitted_at = req.admitted_at
        local._fleet_epoch = epoch
        if self.bus is not None:
            # Bus-routed dispatch (ISSUE 20): a reliable keyed message
            # to the target's CURRENT incarnation endpoint. Inline
            # delivery at zero faults is the direct submit(); under
            # faults the message can be dropped (retransmitted),
            # delayed, or duplicated (deduped at the endpoint).
            self.bus.send("dispatch", "router",
                          self._endpoint(member.replica), local,
                          tick=tick, key=(req.rid, "d", epoch),
                          reliable=True)
        else:
            member.replica.core.submit(local)
        member.replica.pending_dispatches += 1
        self._holder[req.rid] = (member.replica, local)
        if req.cancel_requested:
            # A cancel that landed while the rid awaited (re-)dispatch
            # carries over to the new incarnation.
            local.cancel()
            member.replica.core.flag_cancel()
        kind = "redispatch" if redispatch else "dispatch"
        self.dispatch_trace.append((tick, req.rid, member.name, epoch, kind))
        self.dispatches += not redispatch
        self.redispatches += redispatch
        if self.registry is not None:
            self.registry.inc(f"fleet.{kind}es")
        return member.name

    def cancel(self, rid: int) -> None:
        """Client-side abort of `rid`, fleet-wide: marks the
        authoritative request AND the replica-local copy currently in
        flight (they are distinct objects), and forces that replica's
        sweep on its next step. Callable mid-run from a sink callback
        (the loop invokes sinks every tick); a terminal or unknown rid
        is a no-op, a rid awaiting re-dispatch picks the cancel up at
        dispatch time."""
        auth = self._auth.get(rid)
        if auth is None or auth.terminal:
            return
        auth.cancel()
        ho = self._handoffs.get(rid)
        if ho is not None:
            # Mid-handoff cancel: the transfer aborts at its next
            # processing step and the cancel rides the re-dispatch
            # (the new incarnation sweeps it terminally).
            ho.cancelled = True
            return
        held = self._holder.get(rid)
        if held is not None:
            replica, local = held
            local.cancel()
            replica.core.flag_cancel()

    # -- failure handling ----------------------------------------------

    def _harvest(self, replica: Replica) -> list[Request]:
        """Authoritative requests stranded on a dead/removed replica
        (fence revoked here — a zombie loses commit rights the moment
        failover begins, before the re-dispatch is even placed)."""
        sched = replica.core.sched
        if self.bus is not None:
            # Holder-based harvest (ISSUE 20): under the lossy bus a
            # dispatch can still be IN FLIGHT to the dead/isolated
            # incarnation (delayed, or dropped and awaiting
            # retransmit) — it exists in no slot or queue, but its rid
            # is stranded all the same. The holder map is the
            # authoritative "who serves rid" record, written at send
            # time; at zero faults it names exactly the slot+queue set
            # the direct path harvests. Undelivered-terminal rids (the
            # local finished but the claim never landed) are stranded
            # too: their holder entry survives because only a
            # fence-accepted terminal apply pops it.
            locals_ = [local for _rid, (rep2, local)
                       in sorted(self._holder.items())
                       if rep2 is replica]
        else:
            locals_ = [s.req for s in sched.slots if s.req is not None]
            locals_ += list(sched.queue)
        stranded = []
        for local in locals_:
            auth = self._auth[local.rid]
            if auth.terminal:
                continue
            auth.preemptions += local.preemptions
            auth.quota_wait_s += local.quota_wait_s
            if auth.admitted_at is None:
                auth.admitted_at = local.admitted_at
            # Resume-path integrity stamp (ISSUE 13): taken the moment
            # the failover strands the request; verified before the
            # re-dispatch re-prefills the committed context.
            auth._ctx_crc = context_crc(auth.prompt, auth.out)
            stranded.append(auth)
        stranded.sort(key=lambda r: r.rid)
        # Revoke in SORTED order — the order the dead-replica record's
        # `stranded` list carries, so the replay reconstruction chains
        # the identical fence ops (ISSUE 15; epoch counters are
        # order-independent, only the fence_crc chain cares).
        revoked = (stranded[:-1] if CHAOS_PLANT == "skip-revoke"
                   else stranded)
        for auth in revoked:
            self.router.revoke(auth.rid)
        if self.bus is not None:
            for auth in stranded:
                # Reordered commits / deferred terminals stashed under
                # the just-revoked epoch can never apply — drop them
                # (a live epoch's stash is rebuilt by retransmission).
                self._commit_stash.pop(auth.rid, None)
                self._pending_terms.pop(auth.rid, None)
        return stranded

    def _fail_over(self, member, *, tick: int, now: float,
                   redispatch_q: deque) -> None:
        name = member.name
        self.router.deregister(name)
        self._retire_counts(member.replica)
        stranded = self._harvest(member.replica)
        redispatch_q.extend(stranded)
        # Causal marker (ISSUE 11): this tick's fleet record names the
        # rids the failover stranded, so `mctpu explain` can end their
        # active segments at the failover and bill the re-dispatch wait
        # + re-prefill to redispatch_replay instead of self-compute.
        self._failed_over_tick.extend((r.rid, name) for r in stranded)
        self._log_replica(name, "dead", tick, now,
                          stranded=[r.rid for r in stranded],
                          **({"draining": True} if member.draining else {}))
        if self.bus is not None:
            rep = member.replica
            if rep.alive:
                # Failure detection is fallible under a lossy transport
                # (late != dead): this member's heartbeats stopped
                # arriving but the replica itself is fine — a
                # FALSE-POSITIVE death declaration. It keeps stepping
                # off-trail until its lease lapses; the lease (sender
                # side) + the revoked fence (receiver side) guarantee
                # none of its commits ever land again.
                self._isolated.append((rep, name))
                self._log_replica(name, "isolated", tick, now,
                                  lease_until=rep.lease_until)
            elif rep not in self._zombies:
                # Truly dead and done stepping: tear down the
                # incarnation's endpoint (pending retransmits TO it are
                # purged — nobody is listening, ever again).
                self.bus.unregister(self._endpoint(rep))
        if member.draining:
            # The operator already asked this replica to leave; its
            # crash completes the departure (in-flight work was just
            # harvested for re-dispatch). Restarting it would override
            # the drain intent with a fresh dispatch-taking member.
            return
        try:
            delay = self.router.record_crash(name)
            self._pending_restarts.append(((self.clock() - self._t0) + delay,
                                           name))
            self._pending_restarts.sort()
            self._log_replica(name, "restart_scheduled", tick, now,
                              delay_s=round(delay, 4))
        except CircuitOpen as e:
            self.circuit_opens += 1
            self._log_replica(name, "circuit_open", tick, now, reason=str(e))

    def _retire_counts(self, replica: Replica) -> None:
        core = replica.core
        self._retired[0] += core.decode_ticks
        self._retired[1] += core.prefill_chunks
        self._retired[2] += core.sched.preemptions
        for k, v in core.prefix_stats().items():
            self._retired_prefix[k] += v
        for k, v in core.spec_stats.items():
            self._retired_spec[k] += v
        # A later zombie step must not re-bank these.
        core.decode_ticks = core.prefill_chunks = 0
        core.sched.preemptions = 0
        core.reset_prefix_stats()
        core.reset_spec_stats()

    def _resolve_fault_target(self, f) -> str:
        """The rN name a crash/leave fault targets. A name that no
        replica has EVER carried is a config error and raises — the
        plan-validation contract (ISSUE 7 satellite) is that a fault
        must never silently not fire. A name that existed but is
        currently dead/absent is a legitimate plan/timing race and is
        the caller's no-op."""
        name = f.arg("replica", "r0")
        name = name if isinstance(name, str) else f"r{name}"
        ever = {f"r{i}" for i in range(self._next_idx)}
        if name not in ever:
            raise ValueError(
                f"fault {f.kind}@{f.site}: replica {name!r} has never "
                f"joined this fleet (members ever: r0..r{self._next_idx - 1})"
                " — the fault would silently never fire"
            )
        return name

    def _crash_member(self, member, *, tick: int, now: float,
                      zombie: int = 0) -> None:
        member.replica.alive = False
        self.crashes += 1
        if zombie > 0:
            member.replica.zombie_until = tick + zombie
            self._zombies.append(member.replica)
        self._log_replica(member.name, "crash", tick, now,
                          zombie_ticks=zombie)

    def _apply_fault(self, f, *, tick: int, now: float,
                     redispatch_q: deque) -> None:
        if f.kind == "replica_crash":
            name = self._resolve_fault_target(f)
            member = self.router.members.get(name)
            if member is None or not member.replica.alive:
                return
            self._crash_member(member, tick=tick, now=now,
                               zombie=int(f.arg("zombie_ticks", 0)))
        elif f.kind == "pool_crash":
            # Pool-collapse driver (ISSUE 13): kill every live member
            # of one phase pool — the degradation path's test vehicle.
            pool = f.arg("pool")
            if self.pools is None or pool not in ("prefill", "decode"):
                raise ValueError(
                    f"fault {f.kind}@{f.site}: pool={pool!r} needs a "
                    "disaggregated fleet with pool 'prefill' or 'decode'"
                )
            for member in list(self.router.members.values()):
                if (member.replica.phase == pool
                        and member.replica.alive):
                    self._crash_member(member, tick=tick, now=now,
                                       zombie=int(f.arg("zombie_ticks",
                                                        0)))
        elif f.kind == "replica_join":
            phase = f.arg("pool")
            if phase is None:
                # A disaggregated fleet's unlabeled join lands in the
                # decode pool (capacity there unblocks handoffs); a
                # unified fleet's join stays phaseless.
                phase = "decode" if self.pools is not None else None
            elif phase not in ("prefill", "decode"):
                raise ValueError(
                    f"fault {f.kind}@{f.site}: pool={phase!r} must be "
                    "'prefill' or 'decode'"
                )
            elif self.pools is None:
                raise ValueError(
                    f"fault {f.kind}@{f.site}: pool={phase!r} on a "
                    "unified fleet — there are no pools to join"
                )
            for _ in range(int(f.arg("replicas", 1))):
                self._join(tick=tick, now=now, phase=phase)
        elif f.kind == "replica_leave":
            name = self._resolve_fault_target(f)
            member = self.router.members.get(name)
            if member is not None and not member.draining:
                member.draining = True
                self.leaves += 1
                self._log_replica(name, "leave", tick, now)

    # -- online autoscaling (ISSUE 18) ---------------------------------

    def _autoscale_step(self, tick: int, now: float,
                        redispatch_q: deque) -> None:
        """One autoscaler consult: fold the live pressure gauges into
        the policy and apply its decision through the SAME membership
        machinery the fault plan drives — a scale-out is a _join (the
        mirrored "join" record), a scale-in drains the least-loaded
        member (the mirrored "leave" record; drain completion
        deregisters it like an operator leave). The scale_up/scale_down
        marker records carry no digested state — obs surfaces read
        them, the replay mirror ignores them."""
        phase = "decode" if self.pools is not None else None
        cands = [m for m in self.router.dispatchable(phase)
                 if m.replica.alive]
        live = len(cands)
        load = sum(m.replica.load() for m in cands) + len(redispatch_q)
        decision = self.autoscaler.step(now=now, live=live, load=load,
                                        dispatched=self.dispatches)
        if decision == "up":
            rep = self._join(tick=tick, now=now, phase=phase)
            self.scale_ups += 1
            self._log_replica(rep.name, "scale_up", tick, now,
                              replicas=live + 1)
            self.scale_crc = zlib.crc32(
                repr((tick, "up", rep.name)).encode(), self.scale_crc)
        elif decision == "down" and cands:
            victim = min(cands, key=lambda m: (m.replica.load(), m.name))
            victim.draining = True
            self.leaves += 1
            self._log_replica(victim.name, "leave", tick, now)
            self.scale_downs += 1
            self._log_replica(victim.name, "scale_down", tick, now,
                              replicas=live - 1)
            self.scale_crc = zlib.crc32(
                repr((tick, "down", victim.name)).encode(), self.scale_crc)

    # -- the loop ------------------------------------------------------

    def _validate(self, requests) -> None:
        """Fail a structurally impossible workload at run() entry,
        before any replica sees it — the same shared check a replica's
        submit() would apply, evaluated against the common geometry
        (every replica owns an identical pool)."""
        g = self.geometry
        usable = PagePool(g["num_pages"]).usable
        for r in requests:
            validate_request(r, max_len=g["max_len"],
                             page_size=g["page_size"], usable=usable)

    def run(self, requests: list[Request]) -> FleetResult:
        reqs = sorted(requests, key=lambda r: (r.arrival, r.rid))
        self._validate(reqs)
        self._auth = {r.rid: r for r in reqs}
        if len(self._auth) != len(reqs):
            raise ValueError("duplicate request ids in the workload")
        pending = deque(reqs)
        redispatch_q: deque[Request] = deque()
        # Arrival announcements (ISSUE 11): each fleet record names the
        # rids whose arrival fell due since the last one — the tick
        # anchor `mctpu explain` starts every blame span at.
        announce = deque((r.arrival, r.rid) for r in reqs)
        clock, tick_s = self.clock, self.tick_s
        self._t0 = t0 = clock()
        n_done = 0
        n_total = len(reqs)
        tick = self._tick
        while n_done < n_total:
            self._tick = tick
            now = clock() - t0
            if self.faults is not None:
                for f in self.faults.fire("fleet.tick", tick):
                    self._apply_fault(f, tick=tick, now=now,
                                      redispatch_q=redispatch_q)
                self.events.extend(self.faults.drain_events())
            pump_synced: list[Request] = []
            if self.bus is not None:
                # Transport tick (ISSUE 20): poll fleet.transport
                # (partitions open/heal, message effects arm), then
                # pump — due retransmits go back on the wire and due
                # delayed copies deliver. A delivery can complete a
                # request (a deferred terminal whose trailing commits
                # just landed): those count toward run completion here,
                # and ride the fleet record's t_terminal marker.
                self.bus.apply_tick_faults(tick)
                if self.faults is not None:
                    self.events.extend(self.faults.drain_events())
                for ev in self._partition_events:
                    self._log_replica(ev["name"], ev["kind"], tick, now,
                                      **({"heal": ev["heal"]}
                                         if "heal" in ev else {}))
                    self.transport_log.append(
                        {"kind": ev["kind"], "name": ev["name"],
                         "tick": tick, "now": round(now, 6),
                         **({"heal": ev["heal"]} if "heal" in ev else {})})
                self._partition_events.clear()
                self.bus.pump(tick)
                pump_synced = self._drain_synced()
                n_done += len(pump_synced)
                if self.autoscaler is not None:
                    for r in pump_synced:
                        self.autoscaler.observe_terminal(
                            terminal_fields(r), now)
            # Restarts whose backoff elapsed rejoin with fresh state.
            while self._pending_restarts and self._pending_restarts[0][0] <= now:
                _, name = self._pending_restarts.pop(0)
                rep = self._new_replica(name)
                self.router.register(rep, tick=tick)
                # Counted HERE, not at scheduling: a run that ends
                # before the backoff elapses had no restart, and the
                # summary must agree with the replica_log's events.
                self.restarts += 1
                self._log_replica(name, "restart", tick, now)
            # Failure detection: heartbeat staleness, then failover.
            for member in self.router.stale(tick):
                self._fail_over(member, tick=tick, now=now,
                                redispatch_q=redispatch_q)
            # Graceful leave completes when the drain empties — under
            # the bus, only once every terminal CLAIM also landed (an
            # unacked terminal still retransmitting would be lost with
            # the endpoint).
            for member in list(self.router.members.values()):
                if member.draining and member.replica.core.unfinished == 0 \
                        and (self.bus is None
                             or not any(rep2 is member.replica
                                        for rep2, _l in
                                        self._holder.values())):
                    self.router.deregister(member.name)
                    self._retire_counts(member.replica)
                    if self.bus is not None:
                        self.bus.unregister(self._endpoint(member.replica))
                    self._log_replica(member.name, "drain_complete", tick,
                                      now)
            # Online autoscaling (ISSUE 18): AFTER drain completions
            # and failure handling (the membership it reads is this
            # tick's), BEFORE the fleet record (the digest at emission
            # time already reflects the decision — the replay mirror
            # applies the tick's join/leave events before checking it).
            if self.autoscaler is not None:
                self._autoscale_step(tick, now, redispatch_q)
            # Disaggregation (ISSUE 13): clear degradation latches for
            # pools that repopulated, then advance every in-flight KV
            # handoff (aborts feed redispatch_q ahead of the dispatch
            # pass below; completions bind decode-ready this tick).
            self._check_restored(tick, now)
            if self._handoffs:
                self._process_handoffs(tick, now, redispatch_q)
            # Dispatch: failovers first (they already waited), then due
            # arrivals, FCFS. A re-dispatch happens EXACTLY once per
            # failover — the queue is drained head-first and a request
            # enters it only via _harvest.
            dispatched, redispatched = [], []
            dispatched_to, redispatched_to = [], []
            while redispatch_q:
                req = redispatch_q[0]
                name = self._dispatch(req, tick=tick, redispatch=True)
                if name is None:
                    break
                redispatch_q.popleft()
                redispatched.append(req.rid)
                # Target + carried context length (post discard/refusal
                # — the replica-local out the new incarnation starts
                # with): what the replay reconstruction re-submits.
                redispatched_to.append([req.rid, name, len(req.out)])
            while pending and pending[0].arrival <= now:
                req = pending[0]
                name = self._dispatch(req, tick=tick, redispatch=False)
                if name is None:
                    break
                pending.popleft()
                dispatched.append(req.rid)
                dispatched_to.append([req.rid, name])
            # The fleet record goes out BEFORE the replicas step: the
            # tick's routing decisions precede, in the JSONL, any token
            # the target replica emits this same tick — which is what
            # lets `mctpu trace` anchor a discard re-dispatch's token
            # reset ahead of the new replica's first emission.
            failed_over, self._failed_over_tick = self._failed_over_tick, []
            ho_started, self._handoff_started_tick = \
                self._handoff_started_tick, []
            ho_done, self._handoff_done_tick = self._handoff_done_tick, []
            ho_aborted, self._handoff_aborted_tick = \
                self._handoff_aborted_tick, []
            ho_placed, self._handoff_placed_tick = \
                self._handoff_placed_tick, []
            ho_unplaced, self._handoff_unplaced_tick = \
                self._handoff_unplaced_tick, []
            route_hits_tick, self._route_hits_tick = \
                self._route_hits_tick, []
            # Transport markers (ISSUE 20): this tick's bus state and
            # delivery/retransmit events, drained for the fleet record
            # (pump + dispatch-phase deliveries both happened above).
            transport_fields = None
            t_delivered: list[list] = []
            t_retransmits: list[list] = []
            lease_refused, self._lease_refused_tick = \
                self._lease_refused_tick, []
            if self.bus is not None:
                transport_fields = self.bus.record_fields()
                t_delivered, self._t_delivered = self._t_delivered, []
                t_retransmits = self.bus.drain_retransmits()
            # Flight recorder (ISSUE 15): the router/fleet state digest
            # at record-emission time — membership, in-flight handoff
            # states, dispatch backlog, and the running fence chain —
            # computed on every run (the chain is gate-pinned on
            # summary-only storms) and stamped on the fleet record.
            members = self.router.members
            mparts = []
            for name in sorted(members):
                m = members[name]
                mparts.append((name, m.replica.phase or "", m.draining,
                               m.replica.alive))
            hparts = []
            if self._handoffs:
                hparts = [(rid, ho.state, ho.src, ho.dst or "")
                          for rid, ho in sorted(self._handoffs.items())]
            fleet_crc = fleet_state_digest(
                mparts, hparts, len(pending),
                [r.rid for r in redispatch_q] if redispatch_q else (),
                self.router.fence_crc,
                transport=(transport_digest_tuple(transport_fields)
                           if transport_fields is not None else None),
            )
            self.state_chain = zlib.crc32(fleet_crc.to_bytes(4, "little"),
                                          self.state_chain)
            if self.fleet_sink is not None:
                arrived_now = []
                while announce and announce[0][0] <= now:
                    arrived_now.append(announce.popleft()[1])
                self.fleet_sink({
                    "tick": tick, "now": round(now, 4),
                    "state_crc": fleet_crc,
                    "replicas": len(self.router.members),
                    "pending": len(pending) + len(redispatch_q),
                    "arrived": arrived_now,
                    "dispatched": dispatched, "redispatched": redispatched,
                    # Routing targets (ISSUE 15): which replica each
                    # decision placed the rid on — the event the replay
                    # reconstruction sources queue membership from (the
                    # bare rid lists above keep the pre-ISSUE-15 shape
                    # for trace/explain/top).
                    "dispatched_to": dispatched_to,
                    "redispatched_to": redispatched_to,
                    "failed_over": [[rid, name]
                                    for rid, name in failed_over],
                    # Handoff markers (ISSUE 13), ordered in the JSONL
                    # BEFORE any replica record of this tick: a done
                    # marker always precedes the decode pool's first
                    # emission for the rid, which is what lets `mctpu
                    # trace`/`explain` anchor the phase transition.
                    "handoff_started": [[rid, src]
                                        for rid, src in ho_started],
                    "handoff_done": [[rid, dst] for rid, dst in ho_done],
                    "handoff_aborted": [[rid, why]
                                        for rid, why in ho_aborted],
                    "handoff_placed": [[rid, dst]
                                       for rid, dst in ho_placed],
                    "handoff_unplaced": [[rid, dst]
                                         for rid, dst in ho_unplaced],
                    "handoffs_inflight": len(self._handoffs),
                    "redispatch": self.redispatch,
                    # Cache-aware routing fields (ISSUE 18), only under
                    # the policy that produces them: the tick's scoring
                    # wins [rid, replica, matched_tokens] and the
                    # cumulative per-replica [hits, dispatches] split
                    # the ROUTER top panel / report tables read. Extra
                    # fleet-record fields — replay/blame ignore them.
                    **({"route_hits": route_hits_tick,
                        "route": {n: list(st) for n, st in
                                  sorted(self._route_by.items())}}
                       if self.router.policy == "cache_aware" else {}),
                    # Lossy-transport fields (ISSUE 20), bus runs only:
                    # the digested bus state block, dispatch deliveries
                    # to current incarnations (the mirror's queue-
                    # membership source), pump-applied terminals (the
                    # blame/oracle fold reads them next to the replica
                    # records' fence-accepted sets), and the tick's
                    # retransmit / lease-refusal display markers.
                    **({"transport": transport_fields,
                        "t_delivered": t_delivered,
                        "t_terminal": [terminal_fields(r)
                                       for r in pump_synced],
                        "t_retransmits": t_retransmits,
                        "lease_refused": lease_refused}
                       if self.bus is not None else {}),
                    "load": {m.name: [len(m.replica.core.sched.queue),
                                      sum(1 for s in
                                          m.replica.core.sched.slots
                                          if not s.free),
                                      m.replica.core.sched.pool.free_pages]
                             for m in sorted(self.router.members.values(),
                                             key=lambda m: m.name)},
                })
            # Step every live member (and any zombies — partitioned
            # replicas the router no longer trusts); only live members
            # heartbeat.
            any_work = False
            for member in sorted(self.router.members.values(),
                                 key=lambda m: m.name):
                rep = member.replica
                if not rep.alive:
                    continue
                rec, new_fin, new_drop = rep.step(now)
                # Cumulative live-member step count (ISSUE 18): the
                # capacity actually spent — what the static-vs-
                # autoscaled acceptance compares. Zombies excluded
                # (their steps serve nobody the fence accepts).
                self.replica_ticks += 1
                if self.bus is None:
                    self.router.beat(member.name, tick)
                    synced = self._sync_terminal(rep, new_fin + new_drop,
                                                 now)
                else:
                    # Heartbeat as a MESSAGE (ISSUE 20): liveness is
                    # now whatever the router can observe over the
                    # lossy channel — a partition starves last_beat
                    # and staleness declares this member dead even
                    # though it is fine (the false-positive path). The
                    # hb_ack carries the lease renewal back.
                    self.bus.send("hb", self._endpoint(rep), "router",
                                  {"name": member.name, "gen": rep.gen,
                                   "tick": tick}, tick=tick)
                    self._send_terminals(rep, new_fin + new_drop, tick)
                    synced = self._drain_synced()
                n_done += len(synced)
                if self.autoscaler is not None and synced:
                    # Burn-rate pressure feed (ISSUE 18): the SAME
                    # fence-accepted terminal set the streaming SLO
                    # layer folds — a zombie's refused claims never
                    # push the autoscaler.
                    for r in synced:
                        self.autoscaler.observe_terminal(
                            terminal_fields(r), now)
                any_work = any_work or rec["progressed"] or rep.core.unfinished
                self.state_chain = zlib.crc32(
                    rec["state_crc"].to_bytes(4, "little"), self.state_chain)
                if self.replica_tick_sink is not None:
                    # `terminal` carries the FENCE-ACCEPTED set (the
                    # authoritative requests), not the replica-local
                    # claims: a zombie's post-failover "finished" must
                    # not count as a good SLO event when the commit was
                    # refused (ISSUE 8).
                    self.replica_tick_sink({
                        "tick": tick, "now": round(now, 4),
                        "mode": f"fleet/{member.name}",
                        **{k: rec[k] for k in
                           ("queue", "running", "free_pages", "admitted",
                            "prefill", "decoded", "preempted",
                            "blocked", "preempted_for", "finished",
                            "aborted", "state_crc")},
                        **({"prefix_hits": rec["prefix_hits"],
                            "prefix": rec["prefix"]}
                           if "prefix_hits" in rec else {}),
                        **({"prefix_readmits": rec["prefix_readmits"]}
                           if "prefix_readmits" in rec else {}),
                        **({"spec": rec["spec"]}
                           if "spec" in rec else {}),
                        "terminal": [terminal_fields(r) for r in synced],
                    })
            for rep in list(self._zombies):
                if tick >= rep.zombie_until:
                    self._zombies.remove(rep)
                    if self.bus is not None:
                        member = self.router.members.get(rep.name)
                        if member is None or member.replica is not rep:
                            # Already failed over: the incarnation is
                            # done stepping — tear down its endpoint.
                            # (Pre-failover expiry keeps it: the
                            # failover's unregister handles it.)
                            self.bus.unregister(self._endpoint(rep))
                    continue
                rec, new_fin, new_drop = rep.step(now)
                # Terminal claims from a zombie are fenced like tokens:
                # before failover revokes its fences the zombie's
                # completions are authoritative commits and must count
                # toward n_done; after revocation they are discarded.
                if self.bus is None:
                    synced = self._sync_terminal(rep, new_fin + new_drop,
                                                 now)
                else:
                    # A zombie never heartbeats (alive=False), so its
                    # lease starves and its late claims are first
                    # lease-refused, then fence-refused — both counted.
                    self._send_terminals(rep, new_fin + new_drop, tick)
                    synced = self._drain_synced()
                n_done += len(synced)
                if self.autoscaler is not None and synced:
                    # Fence-accepted only — same feed as live members.
                    for r in synced:
                        self.autoscaler.observe_terminal(
                            terminal_fields(r), now)
                # Pre-failover the zombie is still a member and its
                # commits still land — its tick telemetry is part of
                # the same in-flight drain, and `mctpu trace` needs it
                # to account the committed tokens. Post-failover its
                # commits are fence-refused, so the trail rightly
                # excludes its records.
                member = self.router.members.get(rep.name)
                if member is not None and member.replica is rep:
                    # Pre-failover zombie telemetry is part of the same
                    # in-flight drain: its state digest chains exactly
                    # while its records still flow (post-failover both
                    # stop together — the trail and the chain agree).
                    self.state_chain = zlib.crc32(
                        rec["state_crc"].to_bytes(4, "little"),
                        self.state_chain)
                if (member is not None and member.replica is rep
                        and self.replica_tick_sink is not None):
                    self.replica_tick_sink({
                        "tick": tick, "now": round(now, 4),
                        "mode": f"fleet/{rep.name}",
                        **{k: rec[k] for k in
                           ("queue", "running", "free_pages", "admitted",
                            "prefill", "decoded", "preempted",
                            "blocked", "preempted_for", "finished",
                            "aborted", "state_crc")},
                        **({"prefix_hits": rec["prefix_hits"],
                            "prefix": rec["prefix"]}
                           if "prefix_hits" in rec else {}),
                        **({"prefix_readmits": rec["prefix_readmits"]}
                           if "prefix_readmits" in rec else {}),
                        **({"spec": rec["spec"]}
                           if "spec" in rec else {}),
                        "terminal": [terminal_fields(r) for r in synced],
                    })
            # False-positive failovers (ISSUE 20): an isolated replica
            # does not know it was declared dead — it keeps stepping,
            # heartbeating into the partition, and trying to commit.
            # Off-trail like a post-failover zombie (no records, no
            # state chain: the fleet's trail covers what the router
            # TRUSTS). Every commit it sends is fence-refused; once
            # its lease lapses it refuses its own sends
            # (lease_refusals), and after a grace window it is torn
            # down.
            for rep, name in list(self._isolated):
                if (rep.core.unfinished == 0
                        or tick >= rep.lease_until + self.lease_ticks):
                    self._isolated.remove((rep, name))
                    self.bus.unregister(self._endpoint(rep))
                    self._log_replica(name, "isolated_end", tick, now)
                    continue
                _rec, new_fin, new_drop = rep.step(now)
                self.bus.send("hb", self._endpoint(rep), "router",
                              {"name": name, "gen": rep.gen,
                               "tick": tick}, tick=tick)
                self._send_terminals(rep, new_fin + new_drop, tick)
                synced = self._drain_synced()
                n_done += len(synced)
                if self.autoscaler is not None and synced:
                    for r in synced:
                        self.autoscaler.observe_terminal(
                            terminal_fields(r), now)
            if self.registry is not None:
                self.registry.set("fleet.replicas",
                                  len(self.router.members))
                self.registry.set("fleet.pending",
                                  len(pending) + len(redispatch_q))
            tick += 1
            clock.advance(tick_s)
            if n_done >= n_total:
                break
            if not any_work and not self._zombies and not self._handoffs:
                # Fleet idle: nothing in flight on any LIVE replica. A
                # dead-but-undetected member may still hold work — keep
                # ticking until heartbeat staleness surfaces it. Else
                # jump the clock to the next event, or — with no
                # replicas and none restarting — fail what remains
                # terminally (requests must always leave).
                if self.bus is not None and (self.bus.busy()
                                             or self._isolated):
                    # The WIRE still holds work (a delayed dispatch, an
                    # unacked retransmitting send) or an isolated
                    # replica is still lapsing — neither shows up as
                    # replica work, but jumping the clock past it would
                    # strand the run.
                    continue
                if any(not m.replica.alive
                       for m in self.router.members.values()):
                    continue
                now = clock() - t0
                if (not self.router.members and not self._pending_restarts
                        and self.faults is not None
                        and self.faults.pending("fleet.tick",
                                                "replica_join")):
                    # Empty fleet, but the plan still schedules a join:
                    # capacity is in flight exactly like a pending
                    # restart — keep ticking until its tick arrives.
                    continue
                if not self.router.members and not self._pending_restarts:
                    # Nothing can ever serve again — future arrivals
                    # included (waiting for one would spin forever: it
                    # arrives, no member can take it, repeat).
                    failed_now = []
                    for req in list(pending) + list(redispatch_q):
                        if req.terminal:
                            continue
                        req.status = "failed"
                        req.fail_reason = "fleet has no replicas"
                        # A future arrival fails AT its arrival moment,
                        # never before it — finished_at < arrival would
                        # put negative latencies in the obs records.
                        req.finished_at = max(now, req.arrival)
                        self._holder.pop(req.rid, None)
                        n_done += 1
                        failed_now.append(req)
                    pending.clear()
                    redispatch_q.clear()
                    if failed_now and self.registry is not None:
                        # A total outage is the SLO event that matters
                        # most: these terminals must reach the same
                        # registry twins every fenced completion does.
                        from .engine import _observe_request
                        for req in failed_now:
                            _observe_request(self.registry, req)
                    if failed_now:
                        # The mass failure empties both dispatch queues:
                        # chain the post-clear router digest so the
                        # flight-recorder chain reflects the transition
                        # (the synthetic record below carries it too).
                        router_crc = fleet_state_digest(
                            (), (), 0, (), self.router.fence_crc,
                            transport=(self.bus.digest_tuple()
                                       if self.bus is not None
                                       else None))
                        self.state_chain = zlib.crc32(
                            router_crc.to_bytes(4, "little"),
                            self.state_chain)
                    if failed_now and self.replica_tick_sink is not None:
                        # One router-attributed tick record carries the
                        # mass failure into the trail: the burn-rate
                        # rules fold its `terminal` entries (a fleet
                        # that died with work outstanding must page),
                        # and `mctpu trace` sees the aborted rids so
                        # the lifecycles stay consistent with the
                        # request records.
                        self.replica_tick_sink({
                            "tick": tick, "now": round(now, 4),
                            "mode": "fleet/router",
                            "state_crc": router_crc,
                            "queue": 0, "running": 0, "free_pages": 0,
                            "admitted": [], "prefill": None,
                            "decoded": [], "preempted": [],
                            "blocked": [], "preempted_for": [],
                            "finished": [],
                            "aborted": [[r.rid, r.status]
                                        for r in failed_now],
                            "terminal": [terminal_fields(r)
                                         for r in failed_now],
                        })
                    continue
                targets = [pending[0].arrival] if pending else []
                if self._pending_restarts:
                    targets.append(self._pending_restarts[0][0])
                # Only a FUTURE event can be jumped to; a target <= now
                # (work already here, capacity arriving via a restart
                # that pops next iteration) just keeps ticking.
                future = [t for t in targets if t > now]
                if future:
                    clock.advance(min(future) - now)
                elif not targets and not (pending or redispatch_q):
                    raise RuntimeError(
                        "fleet stalled: replicas idle but "
                        f"{n_total - n_done} request(s) unaccounted for"
                    )
        self._tick = tick
        # Pool invariant at exit on every surviving replica: zero
        # leaked, zero double-booked pages, fleet-wide.
        for member in self.router.members.values():
            member.replica.core.sched.check()
        decode_ticks = self._retired[0] + sum(
            m.replica.core.decode_ticks for m in self.router.members.values())
        prefills = self._retired[1] + sum(
            m.replica.core.prefill_chunks
            for m in self.router.members.values())
        preempts = self._retired[2] + sum(
            m.replica.core.sched.preemptions
            for m in self.router.members.values())
        prefix_totals = dict(self._retired_prefix)
        for m in self.router.members.values():
            for k, v in m.replica.core.prefix_stats().items():
                prefix_totals[k] += v
        spec_totals = dict(self._retired_spec)
        for m in self.router.members.values():
            for k, v in m.replica.core.spec_stats.items():
                spec_totals[k] += v
        return FleetResult(
            requests=reqs, ticks=tick, duration_s=clock() - t0,
            dispatches=self.dispatches, redispatches=self.redispatches,
            fenced_discards=self.fenced_discards, crashes=self.crashes,
            joins=self.joins, leaves=self.leaves, restarts=self.restarts,
            circuit_opens=self.circuit_opens, decode_ticks=decode_ticks,
            prefill_chunks=prefills, preemptions=preempts,
            replicas_final=len(self.router.members),
            handoffs=self.handoffs, handoff_pages=self.handoff_pages,
            handoffs_aborted=self.handoffs_aborted,
            kv_refusals=self.kv_refusals,
            degraded_unified=len(self._degraded_rids), pools=self.pools,
            handoff_log=self.handoff_log,
            dispatch_trace=self.dispatch_trace, events=self.events,
            replica_log=self.replica_log,
            transport_log=self.transport_log, prefix=prefix_totals,
            spec=spec_totals, state_crc=self.state_chain,
            route_hits=self.route_hits, route_misses=self.route_misses,
            route_hit_tokens=self.route_hit_tokens,
            scale_ups=self.scale_ups, scale_downs=self.scale_downs,
            scale_crc=self.scale_crc, replica_ticks=self.replica_ticks,
            lease_refusals=self.lease_refusals,
            lease_ticks=self.lease_ticks,
            **({"msgs_sent": self.bus.counters["sent"],
                "msgs_delivered": self.bus.counters["delivered"],
                "msgs_dropped": self.bus.counters["dropped"],
                "msgs_duped": self.bus.counters["duped"],
                "msgs_delayed": self.bus.counters["delayed"],
                "msgs_deduped": self.bus.counters["deduped"],
                "retransmits": self.bus.counters["retransmits"],
                "partitions": self.bus.counters["partitions"]}
               if self.bus is not None else {}),
        )


def make_fleet_workload(*, n: int, vocab: int, prompt_min: int,
                        prompt_max: int, out_min: int, out_max: int,
                        rate: float, seed: int, sessions: int = 0,
                        deadline_s: float = 0.0, tenants: int = 0,
                        prefix_mix: float = 0.0,
                        len_dist: str = "uniform",
                        templates: int = 0,
                        turns_dist: str | None = None,
                        turn_gap_s: float = 0.0,
                        diurnal_amp: float = 0.0,
                        diurnal_period_s: float = 10.0) -> list[Request]:
    """The serve-bench workload generator plus session keys: request i
    belongs to session i % sessions (0 = sessionless), so the
    session-affinity policy has stable keys to rendezvous-hash.
    `tenants`/`prefix_mix`/`len_dist`/`templates` pass through to
    make_workload's seeded tenant mix, shared-template-prefix mix
    (ISSUE 9), heavy-tail length mix (ISSUE 16), and sized template
    pool (ISSUE 17).

    ISSUE 18's two workload shapes compose on top, both leaving the
    base stream bitwise-unchanged when off: `diurnal_amp` > 0 time-warps
    the arrivals into a day cycle (bench.diurnal_warp — no new draws),
    then `turns_dist` grows each session's first request into a
    multi-turn conversation whose turns re-arrive carrying the previous
    turn's context (bench.add_session_turns — (seed, 5) spawn). Turns
    chain off WARPED arrivals: think-time gaps trail the conversation's
    actual start, which is what puts follow-up traffic inside the same
    diurnal peak that anchored it."""
    from .bench import add_session_turns, diurnal_warp, make_workload

    reqs = make_workload(n=n, vocab=vocab, prompt_min=prompt_min,
                         prompt_max=prompt_max, out_min=out_min,
                         out_max=out_max, rate=rate, seed=seed,
                         deadline_s=deadline_s, tenants=tenants,
                         prefix_mix=prefix_mix, len_dist=len_dist,
                         templates=templates)
    if sessions > 0:
        for r in reqs:
            r.session = r.rid % sessions
    if diurnal_amp > 0:
        reqs = diurnal_warp(reqs, amp=diurnal_amp,
                            period_s=diurnal_period_s)
    if turns_dist:
        if sessions <= 0:
            raise ValueError("turns_dist needs sessions > 0 (turns are "
                             "per-session conversations; a sessionless "
                             "workload has no chains to grow)")
        reqs = add_session_turns(reqs, turns_dist=turns_dist,
                                 turn_gap_s=turn_gap_s, vocab=vocab,
                                 out_min=out_min, out_max=out_max,
                                 max_len=prompt_max + out_max, seed=seed)
    return reqs
