"""Failure-aware multi-replica serving fleet (ISSUE 7, ROADMAP item 4).

One PagedEngine is one chip. This module puts N single-engine replicas
behind serve/router.py's deterministic policy layer and makes replica
DEATH a scheduled, tested event rather than an outage:

- Each `Replica` wraps its own scheduler + PagePool (the PR-3 policy
  machinery, unchanged) and a pluggable `compute`: `EngineCompute`
  drives a real PagedEngine's jitted prefill/decode programs (each
  replica its own page pools — the one-chip-per-replica model), while
  `SimCompute` replaces the device math with a pure token function of
  (request, position) so a 10^5-request storm runs on CPU in seconds
  with the SCHEDULING — dispatch, paging, preemption, re-dispatch —
  exercised for real. Both computes produce per-request outputs that
  are a pure function of (prompt, params|salt), which is what makes
  the crash-vs-crash-free output-equality proof meaningful.

- `ReplicaCore.step` is the PagedEngine.run loop body restructured as
  one scheduler iteration (sweep -> admit -> one prefill chunk -> one
  decode tick) so the fleet can interleave N replicas on one clock.
  The deadline sweep is skipped on ticks where no submitted request
  carries a deadline and no cancel is pending — the O(queue) scan is
  what would otherwise dominate a storm.

- The `Fleet` loop advances a FakeClock by `tick_s` per tick; every
  decision (router policy, failure detection, backoff, fencing) is
  host-side and deterministic, so two identical-seed runs produce
  bitwise-equal dispatch traces and per-status totals — the property
  CI gates by running the seeded storm twice and `mctpu compare`-ing
  the structural counts at exact equality.

Failure semantics (the exactly-once contract):

- A `replica_crash@fleet.tick:T?replica=K` fault stops replica K. The
  router notices via heartbeat staleness (`heartbeat_miss` ticks), then
  FAILS OVER: the dead replica's non-terminal requests have their
  generation fence revoked, are harvested with their COMMITTED tokens,
  and are re-dispatched exactly once each to surviving replicas —
  `redispatch="resume"` re-prefills prompt + committed output (the
  recompute-preemption path, now across replicas), `"discard"` drops
  the partial output and restarts from the prompt.
- Every token and terminal claim a replica makes passes the router's
  generation-token fence. A crashed-but-partitioned replica
  (``zombie_ticks=N``) keeps stepping after failover; every commit it
  attempts is refused — zero double-generated tokens, pinned by test.
- The crashed replica restarts after utils/retry.backoff_delay and
  rejoins with empty pools; a replica that keeps flapping is
  circuit-opened (permanently removed). `replica_join` scales the
  fleet out elastically; `replica_leave` drains one gracefully.
"""

from __future__ import annotations

import dataclasses
import functools
import json
import zlib
from collections import deque

from ..faults import FakeClock
from ..obs.metrics import MetricsRegistry
from .pool import PagePool
from .prefix_cache import PrefixCache, empty_prefix_fields
from .router import CircuitOpen, Router
from .scheduler import (
    ContinuousScheduler,
    Request,
    SLOScheduler,
    tenant_block,
    terminal_fields,
    validate_request,
)

__all__ = [
    "EngineCompute", "Fleet", "FleetResult", "Replica", "ReplicaCore",
    "SimCompute",
]


class SimCompute:
    """Device-free compute: the next token is a pure 32-bit mix of
    (rid, output position, salt) mod vocab. Identical on every replica,
    so a re-dispatched request regenerates exactly the tokens the dead
    replica would have — the sim twin of greedy decode under shared
    weights — while costing nothing, which is what lets the 10^5 storm
    run on this box."""

    def __init__(self, vocab: int = 512, chunk: int = 32, salt: int = 0):
        self.vocab = vocab
        self.chunk = chunk
        self.salt = salt

    def _tok(self, req: Request) -> int:
        j = len(req.out)
        h = (req.rid * 1000003 + j * 2654435761 + self.salt * 97
             + int(req.prompt.size) * 8191) & 0xFFFFFFFF
        return h % self.vocab

    def prefill_chunk(self, slot) -> tuple[int, int]:
        n = min(self.chunk, slot.target - slot.cached)
        return n, self._tok(slot.req)

    def decode(self, dslots) -> dict[int, int]:
        return {s.idx: self._tok(s.req) for s in dslots}

    def copy_page(self, src: int, dst: int) -> None:
        """Sim COW is pure bookkeeping: tokens are a function of
        (rid, position), not of cache contents — the page accounting
        is exercised for real, the device copy has nothing to copy."""


class EngineCompute:
    """Model-backed compute: one PagedEngine (its own page pools) per
    replica; prefill/decode go through the engine's two jitted
    programs via the same run_prefill_chunk/run_decode_tick path
    engine.run uses — one implementation, two drivers."""

    def __init__(self, engine):
        self.engine = engine

    def prefill_chunk(self, slot) -> tuple[int, int]:
        return self.engine.run_prefill_chunk(slot)

    def decode(self, dslots):
        return self.engine.run_decode_tick(dslots)

    def copy_page(self, src: int, dst: int) -> None:
        self.engine.copy_page(src, dst)


class ReplicaCore:
    """One replica's steppable engine loop over the PR-3 scheduler.

    `on_emit(req, tok, now)` is the fleet's fenced commit hook, called
    AFTER the token lands in the replica-local request (the local copy
    always advances — a zombie replica keeps generating; only the
    fence decides whether the authoritative output accepts it)."""

    def __init__(self, compute, *, slots: int, num_pages: int,
                 page_size: int, max_len: int, max_queue: int | None = None,
                 on_emit=None, check_every: int = 1, prefix: bool = False,
                 policy=None):
        pool = PagePool(num_pages)
        self.prefix = PrefixCache(pool, page_size) if prefix else None
        sched_kw = dict(slots=slots, pool=pool, page_size=page_size,
                        max_len=max_len, max_queue=max_queue,
                        prefix=self.prefix)
        if policy is not None:
            self.sched = SLOScheduler(policy=policy, **sched_kw)
        else:
            self.sched = ContinuousScheduler(**sched_kw)
        self.compute = compute
        self.on_emit = on_emit
        self.check_every = check_every
        self.steps = 0
        self.decode_ticks = 0
        self.prefill_chunks = 0
        self._cancel_pending = False
        self._n_fin = 0
        self._n_drop = 0

    def submit(self, req: Request) -> None:
        self.sched.submit([req])

    def flag_cancel(self) -> None:
        """A cancel() landed on one of this core's requests: force the
        sweep on the next step even with no deadlines in play."""
        self._cancel_pending = True

    @property
    def unfinished(self) -> int:
        return self.sched.unfinished

    def _emit(self, req: Request, tok: int, now: float) -> None:
        req.out.append(tok)
        if req.first_token_at is None:
            req.first_token_at = now
        if self.on_emit is not None:
            self.on_emit(req, tok, now)

    def step(self, now: float):
        """One scheduler iteration (the engine.run body, minus the
        idle/fault/watchdog handling the fleet owns). Returns
        (tick-record fields, newly finished locals, newly dropped
        locals) — the fleet syncs terminal statuses from the tails."""
        sched = self.sched
        self.steps += 1
        progressed = False
        if sched.has_deadlines or self._cancel_pending:
            progressed = bool(sched.sweep(now))
            self._cancel_pending = False
        admitted = [[s.idx, s.req.rid] for s in sched.admit(now)]
        if sched.max_queue is not None:
            progressed |= bool(sched.enforce_queue_bound(now))
        prefill_rec = None
        slot = sched.prefill_slot()
        if slot is not None:
            if slot.cow is not None:
                # COW (ISSUE 9): duplicate the partially matched shared
                # page before the slot's first write (engine.run's rule;
                # SimCompute's copy is accounting-only).
                self.compute.copy_page(*slot.cow)
                sched.cow_complete(slot)
            n, nxt = self.compute.prefill_chunk(slot)
            slot.cached += n
            self.prefill_chunks += 1
            prefill_rec = [slot.idx, slot.req.rid, n]
            progressed = True
            if slot.cached >= slot.target:
                # Prefill complete: adopt the prompt's pages into the
                # prefix tree (ISSUE 9); the first generated token is
                # due now (TTFT at prefill completion — engine.run's
                # rule).
                sched.note_prefill_complete(slot)
                # Sanctioned sync (engine.run's rule): int() only on
                # the completing chunk, where the token is emitted.
                # mctpu: disable=MCT007
                self._emit(slot.req, int(nxt), now)
                prefill_rec.append("emit")
                if slot.req.done:
                    sched.finish(slot, now)
        dslots = sched.grow_for_decode(now)
        decoded = [[s.idx, s.req.rid] for s in dslots]
        if dslots:
            toks = self.compute.decode(dslots)
            self.decode_ticks += 1
            progressed = True
            for s in dslots:
                s.cached += 1
                self._emit(s.req, int(toks[s.idx]), now)
                if s.req.done:
                    sched.finish(s, now)
        preempted_pairs = sched.drain_preempted()
        blocked = sched.drain_blocked()
        prefix_tick = (self.prefix.drain_tick()
                       if self.prefix is not None else None)
        new_fin = sched.finished[self._n_fin:]
        new_drop = sched.dropped[self._n_drop:]
        self._n_fin, self._n_drop = len(sched.finished), len(sched.dropped)
        if self.check_every and self.steps % self.check_every == 0:
            sched.check()
        rec = {
            "queue": len(sched.queue),
            "running": sum(1 for s in sched.slots if not s.free),
            "free_pages": sched.pool.free_pages,
            "admitted": admitted, "prefill": prefill_rec,
            "decoded": decoded,
            "preempted": [v for v, _ in preempted_pairs],
            # Causal edges (ISSUE 11): blocked admission attempts and
            # preemption beneficiaries, same shape as engine.run's tick
            # record so `mctpu explain` folds both trails identically.
            "blocked": [[rid, reason, holders]
                        for rid, reason, holders in blocked],
            "preempted_for": [[v, b] for v, b in preempted_pairs
                              if b is not None],
            "finished": [r.rid for r in new_fin],
            "aborted": [[r.rid, r.status] for r in new_drop],
            "progressed": progressed or bool(admitted or new_fin or new_drop),
        }
        if prefix_tick is not None:
            rec["prefix_hits"] = prefix_tick["hits"]
        return rec, new_fin, new_drop

    def prefix_stats(self) -> dict:
        """Cumulative prefix counters in the flat fleet-summary shape
        (zeros with sharing off — gated metrics exist in every run)."""
        if self.prefix is None:
            return empty_prefix_fields()
        return self.prefix.summary_fields()

    def reset_prefix_stats(self) -> None:
        """Zero the counters after they were banked (retirement at
        failover: a zombie's later activity must not re-bank)."""
        if self.prefix is not None:
            for k in self.prefix.stats:
                self.prefix.stats[k] = 0


class Replica:
    """One fleet member: a named ReplicaCore plus the PR-6 registry its
    step loop keeps current — `load()` (what least-loaded dispatch
    reads) is queue depth + running slots FROM THE GAUGES, plus the
    dispatches routed here since the last step (so a burst arriving
    within one tick spreads instead of dog-piling the stalest gauge)."""

    def __init__(self, name: str, compute, *, slots: int, num_pages: int,
                 page_size: int, max_len: int, max_queue: int | None = None,
                 check_every: int = 1, on_emit=None, clock=None,
                 prefix: bool = False, policy=None):
        self.name = name
        self.registry = MetricsRegistry(clock=clock)
        self.core = ReplicaCore(
            compute, slots=slots, num_pages=num_pages, page_size=page_size,
            max_len=max_len, max_queue=max_queue, check_every=check_every,
            on_emit=on_emit, prefix=prefix, policy=policy,
        )
        self.alive = True
        self.zombie_until = -1   # fleet tick a partitioned zombie stops at
        self.pending_dispatches = 0

    def _gauge(self, name: str) -> float:
        g = self.registry.gauges.get(name)
        return g.value if g is not None and g.value is not None else 0.0

    def load(self) -> float:
        return (self._gauge("serve.queue_depth")
                + self._gauge("serve.running_slots")
                + self.pending_dispatches)

    def step(self, now: float):
        rec, new_fin, new_drop = self.core.step(now)
        r = self.registry
        r.set("serve.queue_depth", rec["queue"])
        r.set("serve.running_slots", rec["running"])
        r.set("serve.free_pages", rec["free_pages"])
        if rec["decoded"]:
            r.inc("serve.decode_ticks")
        if rec["prefill"] is not None:
            r.inc("serve.prefill_chunks")
        if rec["preempted"]:
            r.inc("serve.preemptions", len(rec["preempted"]))
        if rec.get("prefix_hits"):
            r.inc("serve.prefix.hits", len(rec["prefix_hits"]))
            r.inc("serve.prefix.hit_tokens",
                  sum(m for _, m in rec["prefix_hits"]))
        self.pending_dispatches = 0
        return rec, new_fin, new_drop


@dataclasses.dataclass
class FleetResult:
    """One fleet run: every submitted request terminal, plus the
    structural counts the determinism gate compares at exact equality
    and the dispatch trace that IS the schedule (crc32-hashable)."""

    requests: list[Request]
    ticks: int
    duration_s: float
    dispatches: int
    redispatches: int
    fenced_discards: int
    crashes: int
    joins: int
    leaves: int
    restarts: int
    circuit_opens: int
    decode_ticks: int
    prefill_chunks: int
    preemptions: int
    replicas_final: int
    # (tick, rid, replica name, epoch, "dispatch" | "redispatch") —
    # every routing decision in order; bitwise-equal across
    # identical-seed runs (the determinism acceptance).
    dispatch_trace: list[tuple] = dataclasses.field(default_factory=list)
    events: list[dict] = dataclasses.field(default_factory=list)
    replica_log: list[dict] = dataclasses.field(default_factory=list)
    # Fleet-wide prefix-cache structural counters (ISSUE 9): summed
    # across every replica incarnation; zeros with sharing off so the
    # gated metrics exist in every fleet-bench run.
    prefix: dict = dataclasses.field(default_factory=empty_prefix_fields)

    @property
    def output_tokens(self) -> int:
        return sum(len(r.out) for r in self.requests)

    @property
    def tokens_per_s(self) -> float:
        return self.output_tokens / max(self.duration_s, 1e-9)

    @functools.cached_property
    def trace_crc(self) -> int:
        """crc32 of the dispatch trace — one number `mctpu compare`
        can gate at exact equality to pin the whole schedule. Cached:
        the CI storm's trace holds ~10^5 tuples and the bench reads
        this twice (the trace is complete once the result exists)."""
        return zlib.crc32(json.dumps(self.dispatch_trace).encode())

    def status_counts(self) -> dict[str, int]:
        counts: dict[str, int] = {}
        for r in self.requests:
            counts[r.status] = counts.get(r.status, 0) + 1
        return counts

    def outputs(self) -> dict[int, list[int]]:
        """rid -> committed tokens (the authoritative, fenced output)."""
        return {r.rid: list(r.out) for r in self.requests}

    def finished_requests(self) -> list[Request]:
        return [r for r in self.requests if r.status == "finished"]

    def request_records(self) -> list[dict]:
        """Per-request obs `request` field dicts, mode="fleet" — built
        by engine.request_record, the ONE record shape report/trace
        consume for engine and fleet runs alike."""
        from .engine import request_record

        return [request_record(r, "fleet")
                for r in sorted(self.requests, key=lambda r: r.rid)]

    def summary(self) -> dict:
        from ..obs.metrics import pct_nearest

        fin = self.finished_requests()
        ttft = [1e3 * (r.first_token_at - r.arrival) for r in fin]
        tpot = [1e3 * (r.finished_at - r.first_token_at)
                / max(len(r.out) - 1, 1) for r in fin]
        return {
            "mode": "fleet",
            "requests": len(self.requests),
            "statuses": self.status_counts(),
            "output_tokens": self.output_tokens,
            "decode_ticks": self.decode_ticks,
            "prefill_chunks": self.prefill_chunks,
            "preemptions": self.preemptions,
            "duration_s": round(self.duration_s, 4),
            "tokens_per_s": round(self.tokens_per_s, 2),
            "ttft_p50_ms": pct_nearest(ttft, 50),
            "ttft_p99_ms": pct_nearest(ttft, 99),
            "tpot_p50_ms": pct_nearest(tpot, 50),
            "tpot_p99_ms": pct_nearest(tpot, 99),
            "replicas": self.replicas_final,
            "fleet_ticks": self.ticks,
            "dispatches": self.dispatches,
            "redispatches": self.redispatches,
            "fenced_discards": self.fenced_discards,
            "crashes": self.crashes,
            "joins": self.joins,
            "leaves": self.leaves,
            "restarts": self.restarts,
            "circuit_opens": self.circuit_opens,
            "trace_crc": self.trace_crc,
            # Prefix-sharing counters (ISSUE 9): flat keys the fleet
            # determinism gate pins at exact equality.
            **self.prefix,
            # Per-tenant status/latency counts (ISSUE 8) — same shape
            # and flattening as ServeResult.summary's block.
            "tenants": tenant_block(self.requests),
        }


class Fleet:
    """The router + N replicas on one deterministic clock (module doc).

    `compute_factory(name)` builds each replica's compute (fresh state
    per incarnation — a restarted replica comes back with empty pools).
    `faults` injects replica_crash / replica_join / replica_leave at
    the "fleet.tick" site. Telemetry is opt-in: `registry` aggregates
    fleet-level counters/latency histograms, `fleet_sink` receives one
    router record per tick, `replica_tick_sink` the per-replica tick
    records (mode "fleet/<name>") `mctpu trace` reconstructs from.
    """

    def __init__(self, compute_factory, *, replicas: int = 2,
                 slots: int = 4, num_pages: int = 64, page_size: int = 16,
                 max_len: int = 256, max_queue: int | None = None,
                 policy: str = "least_loaded", heartbeat_miss: int = 3,
                 backoff_base: float = 0.0, max_flaps: int = 3,
                 redispatch: str = "resume", tick_s: float = 1e-3,
                 check_every: int = 1, faults=None, clock: FakeClock | None = None,
                 registry: MetricsRegistry | None = None, fleet_sink=None,
                 replica_tick_sink=None, jitter=None, prefix: bool = False,
                 sched_policy=None):
        if replicas < 1:
            raise ValueError(f"need at least one replica, got {replicas}")
        if redispatch not in ("resume", "discard"):
            raise ValueError(
                f"redispatch {redispatch!r}: want 'resume' or 'discard'")
        self.compute_factory = compute_factory
        # prefix/sched_policy (ISSUE 9): each replica gets its own
        # PrefixCache over its own pool (a restarted incarnation comes
        # back cold) and, with sched_policy, an SLOScheduler instead of
        # FCFS — the same upgrade engine.run applies single-engine.
        self.geometry = dict(slots=slots, num_pages=num_pages,
                             page_size=page_size, max_len=max_len,
                             max_queue=max_queue, check_every=check_every,
                             prefix=prefix, policy=sched_policy)
        self.redispatch = redispatch
        self.tick_s = tick_s
        self.faults = faults
        self.clock = clock if clock is not None else FakeClock()
        self.registry = registry
        self.fleet_sink = fleet_sink
        self.replica_tick_sink = replica_tick_sink
        self.router = Router(policy, heartbeat_miss=heartbeat_miss,
                             backoff_base=backoff_base, max_flaps=max_flaps,
                             jitter=jitter)
        self.events: list[dict] = []       # obs `fault` field dicts
        self.replica_log: list[dict] = []  # obs `replica` field dicts
        self.dispatch_trace: list[tuple] = []
        self.dispatches = 0
        self.redispatches = 0
        self.fenced_discards = 0
        self.crashes = self.joins = self.leaves = 0
        self.restarts = self.circuit_opens = 0
        self._retired = [0, 0, 0]  # decode_ticks, prefill_chunks, preempts
        self._retired_prefix = empty_prefix_fields()
        self._failed_over_tick: list[tuple[int, str]] = []
        self._auth: dict[int, Request] = {}
        # rid -> (holding replica, live local copy): where a cancel()
        # must land (the authoritative object the caller holds is a
        # different Request than the replica-local one in flight).
        self._holder: dict[int, tuple[Replica, Request]] = {}
        self._zombies: list[Replica] = []
        self._pending_restarts: list[tuple[float, str]] = []
        self._next_idx = 0
        self._tick = 0
        for _ in range(replicas):
            self._join(tick=0, now=0.0, log=False)

    # -- membership ----------------------------------------------------

    def _new_replica(self, name: str) -> Replica:
        rep = Replica(name, self.compute_factory(name),
                      clock=self.clock, **self.geometry)
        rep.core.on_emit = self._make_emit(rep)
        return rep

    def _join(self, *, tick: int, now: float, log: bool = True) -> Replica:
        name = f"r{self._next_idx}"
        self._next_idx += 1
        rep = self._new_replica(name)
        self.router.register(rep, tick=tick)
        self.joins += log
        if log:
            self._log_replica(name, "join", tick, now)
        return rep

    def _log_replica(self, name: str, kind: str, tick: int, now: float,
                     **extra) -> None:
        self.replica_log.append({
            "name": name, "kind": kind, "tick": tick,
            "now": round(now, 4), **extra,
        })
        if self.registry is not None:
            self.registry.inc(f"fleet.replica_{kind}")

    # -- fenced commits ------------------------------------------------

    def _make_emit(self, replica: Replica):
        name = replica.name

        def emit(local: Request, tok: int, now: float) -> None:
            if self.router.fence_ok(local.rid, name, local._fleet_epoch):
                auth = self._auth[local.rid]
                auth.out.append(tok)
                if auth.first_token_at is None:
                    auth.first_token_at = now
            else:
                self.fenced_discards += 1

        return emit

    def _sync_terminal(self, replica: Replica, locals_,
                       now: float) -> list[Request]:
        """Apply a replica's newly terminal local requests to the
        authoritative records — through the fence, so a zombie's
        terminal claims are refused like its tokens. Returns the
        authoritative requests that became terminal by THIS call (the
        fence-accepted set): the caller counts them toward run
        completion and folds them into the tick's `terminal` entries
        for the streaming SLO layer (ISSUE 8)."""
        synced: list[Request] = []
        if self.registry is not None:
            # Lazy: the sim path stays jax-free (engine imports jax).
            from .engine import _observe_request
        for local in locals_:
            if not self.router.fence_ok(local.rid, replica.name,
                                        local._fleet_epoch):
                self.fenced_discards += 1
                continue
            auth = self._auth[local.rid]
            auth.status = local.status
            auth.fail_reason = local.fail_reason
            auth.finished_at = local.finished_at
            auth.preemptions += local.preemptions
            auth.quota_wait_s += local.quota_wait_s
            if auth.admitted_at is None:
                auth.admitted_at = local.admitted_at
            if self.registry is not None:
                _observe_request(self.registry, auth)
            # A terminal rid holds no replica: dropping the holder entry
            # releases the (Replica, local) pair — with EngineCompute a
            # dead incarnation's whole PagedEngine cache would otherwise
            # stay pinned for the rest of the run via finished rids.
            self._holder.pop(local.rid, None)
            synced.append(auth)
        return synced

    # -- dispatch ------------------------------------------------------

    def _dispatch(self, req: Request, *, tick: int, redispatch: bool) -> bool:
        member = self.router.pick(req)
        if member is None:
            return False
        epoch = self.router.grant(req.rid, member.name)
        if redispatch and self.redispatch == "discard":
            req.out.clear()
            req.first_token_at = None
        local = Request(rid=req.rid, prompt=req.prompt,
                        max_new_tokens=req.max_new_tokens,
                        arrival=req.arrival, deadline=req.deadline,
                        session=req.session, tenant=req.tenant)
        local.out = list(req.out)
        # A request that was ever admitted keeps that mark across
        # failover (even under discard, which regenerates the tokens):
        # enforce_queue_bound exempts admitted_at-bearing requests, and
        # a re-dispatch must never be backpressure-rejected as a fresh
        # arrival when the fleet already served tokens for it.
        local.admitted_at = req.admitted_at
        local._fleet_epoch = epoch
        member.replica.core.submit(local)
        member.replica.pending_dispatches += 1
        self._holder[req.rid] = (member.replica, local)
        if req.cancel_requested:
            # A cancel that landed while the rid awaited (re-)dispatch
            # carries over to the new incarnation.
            local.cancel()
            member.replica.core.flag_cancel()
        kind = "redispatch" if redispatch else "dispatch"
        self.dispatch_trace.append((tick, req.rid, member.name, epoch, kind))
        self.dispatches += not redispatch
        self.redispatches += redispatch
        if self.registry is not None:
            self.registry.inc(f"fleet.{kind}es")
        return True

    def cancel(self, rid: int) -> None:
        """Client-side abort of `rid`, fleet-wide: marks the
        authoritative request AND the replica-local copy currently in
        flight (they are distinct objects), and forces that replica's
        sweep on its next step. Callable mid-run from a sink callback
        (the loop invokes sinks every tick); a terminal or unknown rid
        is a no-op, a rid awaiting re-dispatch picks the cancel up at
        dispatch time."""
        auth = self._auth.get(rid)
        if auth is None or auth.terminal:
            return
        auth.cancel()
        held = self._holder.get(rid)
        if held is not None:
            replica, local = held
            local.cancel()
            replica.core.flag_cancel()

    # -- failure handling ----------------------------------------------

    def _harvest(self, replica: Replica) -> list[Request]:
        """Authoritative requests stranded on a dead/removed replica
        (fence revoked here — a zombie loses commit rights the moment
        failover begins, before the re-dispatch is even placed)."""
        sched = replica.core.sched
        locals_ = [s.req for s in sched.slots if s.req is not None]
        locals_ += list(sched.queue)
        stranded = []
        for local in locals_:
            auth = self._auth[local.rid]
            if auth.terminal:
                continue
            self.router.revoke(local.rid)
            auth.preemptions += local.preemptions
            auth.quota_wait_s += local.quota_wait_s
            if auth.admitted_at is None:
                auth.admitted_at = local.admitted_at
            stranded.append(auth)
        return sorted(stranded, key=lambda r: r.rid)

    def _fail_over(self, member, *, tick: int, now: float,
                   redispatch_q: deque) -> None:
        name = member.name
        self.router.deregister(name)
        self._retire_counts(member.replica)
        stranded = self._harvest(member.replica)
        redispatch_q.extend(stranded)
        # Causal marker (ISSUE 11): this tick's fleet record names the
        # rids the failover stranded, so `mctpu explain` can end their
        # active segments at the failover and bill the re-dispatch wait
        # + re-prefill to redispatch_replay instead of self-compute.
        self._failed_over_tick.extend((r.rid, name) for r in stranded)
        self._log_replica(name, "dead", tick, now,
                          stranded=[r.rid for r in stranded],
                          **({"draining": True} if member.draining else {}))
        if member.draining:
            # The operator already asked this replica to leave; its
            # crash completes the departure (in-flight work was just
            # harvested for re-dispatch). Restarting it would override
            # the drain intent with a fresh dispatch-taking member.
            return
        try:
            delay = self.router.record_crash(name)
            self._pending_restarts.append(((self.clock() - self._t0) + delay,
                                           name))
            self._pending_restarts.sort()
            self._log_replica(name, "restart_scheduled", tick, now,
                              delay_s=round(delay, 4))
        except CircuitOpen as e:
            self.circuit_opens += 1
            self._log_replica(name, "circuit_open", tick, now, reason=str(e))

    def _retire_counts(self, replica: Replica) -> None:
        core = replica.core
        self._retired[0] += core.decode_ticks
        self._retired[1] += core.prefill_chunks
        self._retired[2] += core.sched.preemptions
        for k, v in core.prefix_stats().items():
            self._retired_prefix[k] += v
        # A later zombie step must not re-bank these.
        core.decode_ticks = core.prefill_chunks = 0
        core.sched.preemptions = 0
        core.reset_prefix_stats()

    def _resolve_fault_target(self, f) -> str:
        """The rN name a crash/leave fault targets. A name that no
        replica has EVER carried is a config error and raises — the
        plan-validation contract (ISSUE 7 satellite) is that a fault
        must never silently not fire. A name that existed but is
        currently dead/absent is a legitimate plan/timing race and is
        the caller's no-op."""
        name = f.arg("replica", "r0")
        name = name if isinstance(name, str) else f"r{name}"
        ever = {f"r{i}" for i in range(self._next_idx)}
        if name not in ever:
            raise ValueError(
                f"fault {f.kind}@{f.site}: replica {name!r} has never "
                f"joined this fleet (members ever: r0..r{self._next_idx - 1})"
                " — the fault would silently never fire"
            )
        return name

    def _apply_fault(self, f, *, tick: int, now: float,
                     redispatch_q: deque) -> None:
        if f.kind == "replica_crash":
            name = self._resolve_fault_target(f)
            member = self.router.members.get(name)
            if member is None or not member.replica.alive:
                return
            member.replica.alive = False
            self.crashes += 1
            zombie = int(f.arg("zombie_ticks", 0))
            if zombie > 0:
                member.replica.zombie_until = tick + zombie
                self._zombies.append(member.replica)
            self._log_replica(name, "crash", tick, now, zombie_ticks=zombie)
        elif f.kind == "replica_join":
            for _ in range(int(f.arg("replicas", 1))):
                self._join(tick=tick, now=now)
        elif f.kind == "replica_leave":
            name = self._resolve_fault_target(f)
            member = self.router.members.get(name)
            if member is not None and not member.draining:
                member.draining = True
                self.leaves += 1
                self._log_replica(name, "leave", tick, now)

    # -- the loop ------------------------------------------------------

    def _validate(self, requests) -> None:
        """Fail a structurally impossible workload at run() entry,
        before any replica sees it — the same shared check a replica's
        submit() would apply, evaluated against the common geometry
        (every replica owns an identical pool)."""
        g = self.geometry
        usable = PagePool(g["num_pages"]).usable
        for r in requests:
            validate_request(r, max_len=g["max_len"],
                             page_size=g["page_size"], usable=usable)

    def run(self, requests: list[Request]) -> FleetResult:
        reqs = sorted(requests, key=lambda r: (r.arrival, r.rid))
        self._validate(reqs)
        self._auth = {r.rid: r for r in reqs}
        if len(self._auth) != len(reqs):
            raise ValueError("duplicate request ids in the workload")
        pending = deque(reqs)
        redispatch_q: deque[Request] = deque()
        # Arrival announcements (ISSUE 11): each fleet record names the
        # rids whose arrival fell due since the last one — the tick
        # anchor `mctpu explain` starts every blame span at.
        announce = deque((r.arrival, r.rid) for r in reqs)
        clock, tick_s = self.clock, self.tick_s
        self._t0 = t0 = clock()
        n_done = 0
        n_total = len(reqs)
        tick = self._tick
        while n_done < n_total:
            now = clock() - t0
            if self.faults is not None:
                for f in self.faults.fire("fleet.tick", tick):
                    self._apply_fault(f, tick=tick, now=now,
                                      redispatch_q=redispatch_q)
                self.events.extend(self.faults.drain_events())
            # Restarts whose backoff elapsed rejoin with fresh state.
            while self._pending_restarts and self._pending_restarts[0][0] <= now:
                _, name = self._pending_restarts.pop(0)
                rep = self._new_replica(name)
                self.router.register(rep, tick=tick)
                # Counted HERE, not at scheduling: a run that ends
                # before the backoff elapses had no restart, and the
                # summary must agree with the replica_log's events.
                self.restarts += 1
                self._log_replica(name, "restart", tick, now)
            # Failure detection: heartbeat staleness, then failover.
            for member in self.router.stale(tick):
                self._fail_over(member, tick=tick, now=now,
                                redispatch_q=redispatch_q)
            # Graceful leave completes when the drain empties.
            for member in list(self.router.members.values()):
                if member.draining and member.replica.core.unfinished == 0:
                    self.router.deregister(member.name)
                    self._retire_counts(member.replica)
                    self._log_replica(member.name, "drain_complete", tick,
                                      now)
            # Dispatch: failovers first (they already waited), then due
            # arrivals, FCFS. A re-dispatch happens EXACTLY once per
            # failover — the queue is drained head-first and a request
            # enters it only via _harvest.
            dispatched, redispatched = [], []
            while redispatch_q:
                req = redispatch_q[0]
                if not self._dispatch(req, tick=tick, redispatch=True):
                    break
                redispatch_q.popleft()
                redispatched.append(req.rid)
            while pending and pending[0].arrival <= now:
                req = pending[0]
                if not self._dispatch(req, tick=tick, redispatch=False):
                    break
                pending.popleft()
                dispatched.append(req.rid)
            # The fleet record goes out BEFORE the replicas step: the
            # tick's routing decisions precede, in the JSONL, any token
            # the target replica emits this same tick — which is what
            # lets `mctpu trace` anchor a discard re-dispatch's token
            # reset ahead of the new replica's first emission.
            failed_over, self._failed_over_tick = self._failed_over_tick, []
            if self.fleet_sink is not None:
                arrived_now = []
                while announce and announce[0][0] <= now:
                    arrived_now.append(announce.popleft()[1])
                self.fleet_sink({
                    "tick": tick, "now": round(now, 4),
                    "replicas": len(self.router.members),
                    "pending": len(pending) + len(redispatch_q),
                    "arrived": arrived_now,
                    "dispatched": dispatched, "redispatched": redispatched,
                    "failed_over": [[rid, name]
                                    for rid, name in failed_over],
                    "redispatch": self.redispatch,
                    "load": {m.name: [len(m.replica.core.sched.queue),
                                      sum(1 for s in
                                          m.replica.core.sched.slots
                                          if not s.free),
                                      m.replica.core.sched.pool.free_pages]
                             for m in sorted(self.router.members.values(),
                                             key=lambda m: m.name)},
                })
            # Step every live member (and any zombies — partitioned
            # replicas the router no longer trusts); only live members
            # heartbeat.
            any_work = False
            for member in sorted(self.router.members.values(),
                                 key=lambda m: m.name):
                rep = member.replica
                if not rep.alive:
                    continue
                rec, new_fin, new_drop = rep.step(now)
                self.router.beat(member.name, tick)
                synced = self._sync_terminal(rep, new_fin + new_drop, now)
                n_done += len(synced)
                any_work = any_work or rec["progressed"] or rep.core.unfinished
                if self.replica_tick_sink is not None:
                    # `terminal` carries the FENCE-ACCEPTED set (the
                    # authoritative requests), not the replica-local
                    # claims: a zombie's post-failover "finished" must
                    # not count as a good SLO event when the commit was
                    # refused (ISSUE 8).
                    self.replica_tick_sink({
                        "tick": tick, "now": round(now, 4),
                        "mode": f"fleet/{member.name}",
                        **{k: rec[k] for k in
                           ("queue", "running", "free_pages", "admitted",
                            "prefill", "decoded", "preempted",
                            "blocked", "preempted_for", "finished",
                            "aborted")},
                        **({"prefix_hits": rec["prefix_hits"]}
                           if "prefix_hits" in rec else {}),
                        "terminal": [terminal_fields(r) for r in synced],
                    })
            for rep in list(self._zombies):
                if tick >= rep.zombie_until:
                    self._zombies.remove(rep)
                    continue
                rec, new_fin, new_drop = rep.step(now)
                # Terminal claims from a zombie are fenced like tokens:
                # before failover revokes its fences the zombie's
                # completions are authoritative commits and must count
                # toward n_done; after revocation they are discarded.
                synced = self._sync_terminal(rep, new_fin + new_drop, now)
                n_done += len(synced)
                # Pre-failover the zombie is still a member and its
                # commits still land — its tick telemetry is part of
                # the same in-flight drain, and `mctpu trace` needs it
                # to account the committed tokens. Post-failover its
                # commits are fence-refused, so the trail rightly
                # excludes its records.
                member = self.router.members.get(rep.name)
                if (member is not None and member.replica is rep
                        and self.replica_tick_sink is not None):
                    self.replica_tick_sink({
                        "tick": tick, "now": round(now, 4),
                        "mode": f"fleet/{rep.name}",
                        **{k: rec[k] for k in
                           ("queue", "running", "free_pages", "admitted",
                            "prefill", "decoded", "preempted",
                            "blocked", "preempted_for", "finished",
                            "aborted")},
                        **({"prefix_hits": rec["prefix_hits"]}
                           if "prefix_hits" in rec else {}),
                        "terminal": [terminal_fields(r) for r in synced],
                    })
            if self.registry is not None:
                self.registry.set("fleet.replicas",
                                  len(self.router.members))
                self.registry.set("fleet.pending",
                                  len(pending) + len(redispatch_q))
            tick += 1
            clock.advance(tick_s)
            if n_done >= n_total:
                break
            if not any_work and not self._zombies:
                # Fleet idle: nothing in flight on any LIVE replica. A
                # dead-but-undetected member may still hold work — keep
                # ticking until heartbeat staleness surfaces it. Else
                # jump the clock to the next event, or — with no
                # replicas and none restarting — fail what remains
                # terminally (requests must always leave).
                if any(not m.replica.alive
                       for m in self.router.members.values()):
                    continue
                now = clock() - t0
                if (not self.router.members and not self._pending_restarts
                        and self.faults is not None
                        and self.faults.pending("fleet.tick",
                                                "replica_join")):
                    # Empty fleet, but the plan still schedules a join:
                    # capacity is in flight exactly like a pending
                    # restart — keep ticking until its tick arrives.
                    continue
                if not self.router.members and not self._pending_restarts:
                    # Nothing can ever serve again — future arrivals
                    # included (waiting for one would spin forever: it
                    # arrives, no member can take it, repeat).
                    failed_now = []
                    for req in list(pending) + list(redispatch_q):
                        if req.terminal:
                            continue
                        req.status = "failed"
                        req.fail_reason = "fleet has no replicas"
                        # A future arrival fails AT its arrival moment,
                        # never before it — finished_at < arrival would
                        # put negative latencies in the obs records.
                        req.finished_at = max(now, req.arrival)
                        self._holder.pop(req.rid, None)
                        n_done += 1
                        failed_now.append(req)
                    pending.clear()
                    redispatch_q.clear()
                    if failed_now and self.registry is not None:
                        # A total outage is the SLO event that matters
                        # most: these terminals must reach the same
                        # registry twins every fenced completion does.
                        from .engine import _observe_request
                        for req in failed_now:
                            _observe_request(self.registry, req)
                    if failed_now and self.replica_tick_sink is not None:
                        # One router-attributed tick record carries the
                        # mass failure into the trail: the burn-rate
                        # rules fold its `terminal` entries (a fleet
                        # that died with work outstanding must page),
                        # and `mctpu trace` sees the aborted rids so
                        # the lifecycles stay consistent with the
                        # request records.
                        self.replica_tick_sink({
                            "tick": tick, "now": round(now, 4),
                            "mode": "fleet/router",
                            "queue": 0, "running": 0, "free_pages": 0,
                            "admitted": [], "prefill": None,
                            "decoded": [], "preempted": [],
                            "blocked": [], "preempted_for": [],
                            "finished": [],
                            "aborted": [[r.rid, r.status]
                                        for r in failed_now],
                            "terminal": [terminal_fields(r)
                                         for r in failed_now],
                        })
                    continue
                targets = [pending[0].arrival] if pending else []
                if self._pending_restarts:
                    targets.append(self._pending_restarts[0][0])
                # Only a FUTURE event can be jumped to; a target <= now
                # (work already here, capacity arriving via a restart
                # that pops next iteration) just keeps ticking.
                future = [t for t in targets if t > now]
                if future:
                    clock.advance(min(future) - now)
                elif not targets and not (pending or redispatch_q):
                    raise RuntimeError(
                        "fleet stalled: replicas idle but "
                        f"{n_total - n_done} request(s) unaccounted for"
                    )
        self._tick = tick
        # Pool invariant at exit on every surviving replica: zero
        # leaked, zero double-booked pages, fleet-wide.
        for member in self.router.members.values():
            member.replica.core.sched.check()
        decode_ticks = self._retired[0] + sum(
            m.replica.core.decode_ticks for m in self.router.members.values())
        prefills = self._retired[1] + sum(
            m.replica.core.prefill_chunks
            for m in self.router.members.values())
        preempts = self._retired[2] + sum(
            m.replica.core.sched.preemptions
            for m in self.router.members.values())
        prefix_totals = dict(self._retired_prefix)
        for m in self.router.members.values():
            for k, v in m.replica.core.prefix_stats().items():
                prefix_totals[k] += v
        return FleetResult(
            requests=reqs, ticks=tick, duration_s=clock() - t0,
            dispatches=self.dispatches, redispatches=self.redispatches,
            fenced_discards=self.fenced_discards, crashes=self.crashes,
            joins=self.joins, leaves=self.leaves, restarts=self.restarts,
            circuit_opens=self.circuit_opens, decode_ticks=decode_ticks,
            prefill_chunks=prefills, preemptions=preempts,
            replicas_final=len(self.router.members),
            dispatch_trace=self.dispatch_trace, events=self.events,
            replica_log=self.replica_log, prefix=prefix_totals,
        )


def make_fleet_workload(*, n: int, vocab: int, prompt_min: int,
                        prompt_max: int, out_min: int, out_max: int,
                        rate: float, seed: int, sessions: int = 0,
                        deadline_s: float = 0.0, tenants: int = 0,
                        prefix_mix: float = 0.0) -> list[Request]:
    """The serve-bench workload generator plus session keys: request i
    belongs to session i % sessions (0 = sessionless), so the
    session-affinity policy has stable keys to rendezvous-hash.
    `tenants`/`prefix_mix` pass through to make_workload's seeded
    tenant mix and shared-template-prefix mix (ISSUE 9)."""
    from .bench import make_workload

    reqs = make_workload(n=n, vocab=vocab, prompt_min=prompt_min,
                         prompt_max=prompt_max, out_min=out_min,
                         out_max=out_max, rate=rate, seed=seed,
                         deadline_s=deadline_s, tenants=tenants,
                         prefix_mix=prefix_mix)
    if sessions > 0:
        for r in reqs:
            r.session = r.rid % sessions
    return reqs
