"""Deterministic simulated message bus for the fleet control plane.

Every robustness result before ISSUE 20 assumed the router<->replica
channel is a perfect in-process call: dispatches arrive instantly and
exactly once, heartbeats are ground truth, and failure detection can
never be wrong. Production fleets talk over a lossy network, and
network partitions are a dominant cause of real cloud outages
(Alquraan et al., OSDI'18). This module makes the transport a
first-class, faultable subsystem while keeping the whole fleet
bitwise-deterministic.

Design (all jax-free, importable by the obs tools):

- Typed messages on per-(src, dst) links, each stamped with a
  per-link sequence number. Endpoints are "router" and
  ``"<name>#<gen>"`` — the incarnation counter makes an address of a
  restarted replica distinct from its predecessor's, so a message in
  flight to a dead incarnation can never reach its successor.
- ZERO-FAULT DELIVERY IS INLINE: with no armed fault and no open
  partition, ``send`` invokes the destination handler synchronously,
  which is exactly the direct-call fleet — the bitwise-parity
  acceptance (bus on == bus off at zero faults) falls out of this,
  not out of careful tuning. Messages queue only when a fault delays
  them.
- At-least-once retransmission for reliable kinds: an unacked send is
  retransmitted on a `utils.retry.backoff_delay`-paced schedule
  (jitter pinned to zero, delays ceil'd to whole ticks) with no
  retry cap — the sender keeps trying until acked or torn down,
  which is what makes "the network eventually heals" sufficient for
  delivery. Receivers ack on delivery AND on dedup (a re-ack answers
  the retransmit whose original ack was lost).
- Receiver-side dedup: reliable messages carry a key
  ``(rid, kind0, epoch[, pos])``; a receiver remembers delivered keys
  per rid and drops repeats. The key store for a rid is released by
  the fleet once the rid is terminal (the fleet's own
  ``req.terminal`` guard makes post-release stragglers harmless).
- Fault kinds at site ``fleet.transport`` (all TICK-triggered — the
  fleet polls the site once per tick): ``partition`` opens a window
  that drops everything to/from one replica name (every incarnation;
  both directions, at send AND at delayed delivery); ``msg_drop`` /
  ``msg_dup`` / ``msg_delay`` arm one-shot effects that hit the next
  matching send (optionally filtered by message kind and/or replica).
- Conservation invariant, audited by the replay mirror every tick:
  ``sent == delivered + deduped + dropped + inflight``. A dup
  increments sent AND duped (two wire copies, one logical send); a
  retransmit increments sent AND retransmits.

``record_fields()`` is the bus's whole observable state; the producer
folds ``transport_digest_tuple`` of it into ``fleet_state_digest`` so
`mctpu replay`/`diverge` cover the transport with zero drift.
"""

from __future__ import annotations

from ..utils.retry import backoff_delay

TRANSPORT_SITE = "fleet.transport"

#: Message kinds carried on the bus. "ack" is bus-internal (clears the
#: sender's retransmit entry); the rest are fleet control-plane traffic.
MSG_KINDS = ("dispatch", "commit", "terminal", "hb", "hb_ack", "ack")

#: Counters every TransportBus maintains (record_fields order).
COUNTER_KEYS = ("sent", "delivered", "dropped", "duped", "delayed",
                "deduped", "retransmits", "partitions")

#: backoff_delay attempt values are capped here so the retransmit
#: interval plateaus (~32 ticks with the default base) instead of
#: growing without bound across a long partition.
_RTO_ATTEMPT_CAP = 5
_RTO_TICK_CAP = 32


def _no_jitter() -> float:
    return 0.0


def transport_digest_tuple(fields: dict) -> tuple:
    """Canonical hashable form of a transport record block — the ONE
    spelling shared by the producer (`fleet_state_digest`'s transport
    component) and the replay mirror, so the two can never drift on
    how bus state folds into the per-tick state_crc."""
    return (
        tuple(int(fields[k]) for k in COUNTER_KEYS),
        int(fields["inflight"]),
        int(fields["unacked"]),
        tuple((str(s), str(d), int(n)) for s, d, n in fields["links"]),
        tuple((str(n), int(u)) for n, u in fields["partitioned"]),
    )


class _Message:
    """One wire message. Payloads are in-memory python objects (the bus
    is simulated); a delayed or retransmitted copy re-delivers the SAME
    payload object, which is what a real network's byte copy would
    decode to."""

    __slots__ = ("seq", "kind", "src", "dst", "payload", "key",
                 "reliable", "sent_tick")

    def __init__(self, seq, kind, src, dst, payload, key, reliable,
                 sent_tick):
        self.seq = seq
        self.kind = kind
        self.src = src
        self.dst = dst
        self.payload = payload
        self.key = key
        self.reliable = reliable
        self.sent_tick = sent_tick


def _endpoint_replica(endpoint: str) -> str:
    """The replica NAME behind an endpoint ("r1#2" -> "r1"); the router
    endpoint maps to itself (never partitioned)."""
    return endpoint.partition("#")[0]


class TransportBus:
    """Seeded-deterministic message bus (see module docstring).

    `faults` is the fleet's FaultInjector (or None); the fleet calls
    `apply_tick_faults(tick)` once per tick to poll ``fleet.transport``
    and arm effects, then `pump(tick)` to retransmit due unacked sends
    and deliver due delayed copies. `plant` is a zero-arg callable
    returning the active chaos plant tag (the "skip-dedup" canary
    bypasses commit dedup so the oracle can prove dedup is
    load-bearing). `on_event` receives (kind, fields) for partition
    open/heal so the fleet can log them on the obs trail.
    """

    def __init__(self, *, faults=None, site: str = TRANSPORT_SITE,
                 rto_base: float = 2.0, plant=None, on_event=None):
        if rto_base < 1:
            raise ValueError(f"rto_base must be >= 1, got {rto_base}")
        self.faults = faults
        self.site = site
        self.rto_base = float(rto_base)
        self.plant = plant
        self.on_event = on_event
        self._endpoints: dict[str, object] = {}
        self._next_seq: dict[tuple[str, str], int] = {}
        # dst -> rid -> set of delivered reliable keys (released per
        # rid by the fleet at terminal apply).
        self._seen: dict[str, dict] = {}
        # key -> [attempt, due_tick, message]; insertion order is the
        # deterministic retransmit scan order.
        self._unacked: dict[tuple, list] = {}
        self._delayed: list[list] = []  # [due_tick, order, message]
        self._order = 0
        self._armed: list[dict] = []
        self.partitions: dict[str, int] = {}  # name -> heal tick
        self.counters = {k: 0 for k in COUNTER_KEYS}
        self._retx_tick: list[list] = []

    # ------------------------------------------------------------------
    # endpoints

    def register(self, endpoint: str, handler) -> None:
        self._endpoints[endpoint] = handler
        self._seen.setdefault(endpoint, {})

    def unregister(self, endpoint: str) -> None:
        """Tear down an endpoint. Its unacked sends stop retransmitting
        (the sender is gone) and pending retransmits TO it are dropped
        from the schedule; delayed copies already in flight stay in
        flight and count as dropped at delivery time if nobody is
        listening — the network does not know the process died."""
        self._endpoints.pop(endpoint, None)
        self._seen.pop(endpoint, None)
        stale = [k for k, ent in self._unacked.items()
                 if ent[2].src == endpoint or ent[2].dst == endpoint]
        for k in stale:
            del self._unacked[k]

    def release_keys(self, rid: int) -> None:
        """Drop the dedup key store for a terminal rid (bounds memory
        across a 10^5 storm); the fleet's terminal-request guard makes
        a post-release straggler commit harmless."""
        for per_rid in self._seen.values():
            per_rid.pop(rid, None)

    # ------------------------------------------------------------------
    # faults

    def apply_tick_faults(self, tick: int) -> None:
        """Poll ``fleet.transport`` at `tick`: open partitions, arm
        one-shot message effects, heal expired partitions."""
        healed = [n for n, until in self.partitions.items()
                  if tick >= until]
        for name in sorted(healed):
            del self.partitions[name]
            if self.on_event is not None:
                self.on_event("partition_heal", {"name": name,
                                                 "tick": tick})
        if self.faults is None:
            return
        for f in self.faults.poll(self.site, tick):
            if f.kind == "partition":
                rep = f.arg("replica", 0)
                name = rep if isinstance(rep, str) else f"r{int(rep)}"
                ticks = max(1, int(f.arg("ticks", 8)))
                self.partitions[name] = tick + ticks
                self.counters["partitions"] += 1
                if self.on_event is not None:
                    self.on_event("partition_open",
                                  {"name": name, "tick": tick,
                                   "heal": tick + ticks})
            elif f.kind in ("msg_drop", "msg_dup", "msg_delay"):
                rep = f.arg("replica", None)
                self._armed.append({
                    "effect": f.kind[4:],  # drop / dup / delay
                    "kind": f.arg("kind", None),
                    "replica": (None if rep is None
                                else rep if isinstance(rep, str)
                                else f"r{int(rep)}"),
                    "count": max(1, int(f.arg("count", 1))),
                    "ticks": max(1, int(f.arg("ticks", 2))),
                })
            else:  # pragma: no cover - validate_plan_sites blocks this
                raise ValueError(
                    f"fault kind {f.kind!r} is inert at {self.site}")

    def _blocked(self, endpoint: str, tick: int) -> bool:
        until = self.partitions.get(_endpoint_replica(endpoint))
        return until is not None and tick < until

    def _match_armed(self, msg: _Message):
        ep = msg.dst if msg.dst != "router" else msg.src
        rep = _endpoint_replica(ep)
        for i, a in enumerate(self._armed):
            if a["kind"] is not None and a["kind"] != msg.kind:
                continue
            if a["replica"] is not None and a["replica"] != rep:
                continue
            a["count"] -= 1
            if a["count"] <= 0:
                self._armed.pop(i)
            return a
        return None

    # ------------------------------------------------------------------
    # send / deliver

    def send(self, kind: str, src: str, dst: str, payload, *, tick: int,
             key: tuple | None = None, reliable: bool = False) -> None:
        if reliable and key is None:
            raise ValueError("reliable sends need a dedup key")
        link = (src, dst)
        seq = self._next_seq.get(link, 0)
        self._next_seq[link] = seq + 1
        msg = _Message(seq, kind, src, dst, payload, key, reliable, tick)
        if reliable:
            self._unacked[key] = [0, tick + self._rto(0), msg]
        self._transmit(msg, tick)

    def _rto(self, attempt: int) -> int:
        delay = backoff_delay(min(attempt, _RTO_ATTEMPT_CAP),
                              base=float(self.rto_base),
                              jitter=_no_jitter)
        return min(_RTO_TICK_CAP, max(1, -int(-delay // 1)))

    def _transmit(self, msg: _Message, tick: int) -> None:
        """One wire attempt: partition check, armed-effect check, then
        inline delivery."""
        c = self.counters
        c["sent"] += 1
        if self._blocked(msg.src, tick) or self._blocked(msg.dst, tick):
            c["dropped"] += 1
            return
        eff = self._match_armed(msg)
        if eff is not None:
            effect = eff["effect"]
            if effect == "drop":
                c["dropped"] += 1
                return
            if effect == "dup":
                c["duped"] += 1
                c["sent"] += 1  # the duplicate is a second wire copy
                self._deliver(msg, tick)
                self._deliver(msg, tick)
                return
            # delay: park a copy; pump() re-checks partitions at the
            # due tick (a window can open while the copy is in flight).
            c["delayed"] += 1
            self._delayed.append([tick + eff["ticks"], self._order, msg])
            self._order += 1
            return
        self._deliver(msg, tick)

    def _deliver(self, msg: _Message, tick: int) -> None:
        c = self.counters
        handler = self._endpoints.get(msg.dst)
        if handler is None:
            c["dropped"] += 1  # nobody listening at this incarnation
            return
        if msg.kind == "ack":
            c["delivered"] += 1
            self._unacked.pop(msg.payload, None)
            return
        if msg.key is not None:
            per_rid = self._seen[msg.dst].setdefault(msg.key[0], set())
            skip_dedup = (self.plant is not None
                          and self.plant() == "skip-dedup"
                          and msg.key[1] == "c")
            if msg.key in per_rid and not skip_dedup:
                c["deduped"] += 1
                if msg.reliable:
                    # re-ack: the retransmit means our first ack was
                    # lost (or the copy was duped) — answer it anyway.
                    self.send("ack", msg.dst, msg.src, msg.key,
                              tick=tick)
                return
            per_rid.add(msg.key)
        c["delivered"] += 1
        handler(msg, tick)
        if msg.reliable:
            self.send("ack", msg.dst, msg.src, msg.key, tick=tick)

    # ------------------------------------------------------------------
    # per-tick pump

    def pump(self, tick: int) -> None:
        """Retransmit due unacked sends, then deliver due delayed
        copies (oldest due first, FIFO within a tick)."""
        for key in list(self._unacked):
            ent = self._unacked.get(key)
            if ent is None:  # acked by an earlier retransmit this pump
                continue
            if ent[1] > tick:  # not due yet
                continue
            ent[0] += 1
            ent[1] = tick + self._rto(ent[0])
            self.counters["retransmits"] += 1
            msg = ent[2]
            self._retx_tick.append(
                [msg.kind, msg.dst, msg.key[0] if msg.key else -1])
            self._transmit(msg, tick)
        if not self._delayed:
            return
        due = [e for e in self._delayed if e[0] <= tick]
        if not due:
            return
        self._delayed = [e for e in self._delayed if e[0] > tick]
        due.sort(key=lambda e: (e[0], e[1]))
        for _due, _order, msg in due:
            if (self._blocked(msg.src, tick)
                    or self._blocked(msg.dst, tick)):
                self.counters["dropped"] += 1
                continue
            self._deliver(msg, tick)

    def busy(self) -> bool:
        """True while the wire still holds work: a delayed copy in
        flight or an unacked reliable send awaiting retransmission —
        the fleet must keep ticking through either (a clock jump would
        strand them)."""
        return bool(self._delayed or self._unacked)

    def drain_retransmits(self) -> list[list]:
        """This tick's retransmit markers ([kind, dst, rid]) for the
        fleet record — `mctpu trace` renders them as lifecycle
        markers."""
        out, self._retx_tick = self._retx_tick, []
        return out

    # ------------------------------------------------------------------
    # observability

    def record_fields(self) -> dict:
        """The bus's whole observable state, as it rides the per-tick
        fleet record. `transport_digest_tuple` of this dict is the
        transport component of `fleet_state_digest`."""
        fields = {k: self.counters[k] for k in COUNTER_KEYS}
        fields["inflight"] = len(self._delayed)
        fields["unacked"] = len(self._unacked)
        fields["links"] = [[s, d, n] for (s, d), n
                           in sorted(self._next_seq.items())]
        fields["partitioned"] = [[n, u] for n, u
                                 in sorted(self.partitions.items())]
        return fields

    def digest_tuple(self) -> tuple:
        return transport_digest_tuple(self.record_fields())
