"""Serving engine: paged KV cache + iteration-level continuous batching.

The decode-side counterpart of the scanned-epoch training design — see
paged_cache.py (the memory layout), scheduler.py (the admission /
preemption policy), engine.py (the jitted ticks), bench.py (the
`mctpu serve-bench` harness).
"""

from .engine import PagedEngine, ServeResult
from .paged_cache import PagedKVCache, PagePool, init_paged_cache
from .scheduler import (
    ContinuousScheduler,
    Request,
    StaticScheduler,
    pages_for,
)

__all__ = [
    "ContinuousScheduler",
    "PagedEngine",
    "PagedKVCache",
    "PagePool",
    "Request",
    "ServeResult",
    "StaticScheduler",
    "init_paged_cache",
    "pages_for",
]
