"""Serving engine: paged KV cache + iteration-level continuous batching.

The decode-side counterpart of the scanned-epoch training design — see
paged_cache.py (the memory layout), scheduler.py (the admission /
preemption policy), engine.py (the jitted ticks), bench.py (the
`mctpu serve-bench` / `mctpu fleet-bench` harnesses), router.py (the
fleet's dispatch/health/fencing policy), fleet.py (N replicas behind
the router, failure-aware re-dispatch — ISSUE 7), prefix_cache.py (the
prefix-sharing tree: refcounted read-only pages, copy-on-write, LRU
retention — ISSUE 9; scheduler.py's SLOScheduler is the matching
SLO-aware admission/preemption policy), handoff.py (the disaggregated
prefill/decode pools' crash-safe page-granular KV transfer protocol —
ISSUE 13; fleet.py drives it, engine.adopt_pages is the device copy),
spec.py (batched speculative decoding's jax-free policy half —
ISSUE 14: prompt-lookup proposal, the greedy acceptance law, the round
scaffold engine.run and ReplicaCore.step share; the engine compiles
the batched verify block, the scheduler owns the acceptance-aware page
accounting).
"""

from .engine import PagedEngine, ServeResult
from .fleet import (
    EngineCompute,
    Fleet,
    FleetResult,
    Replica,
    SimCompute,
)
from .handoff import Handoff, parse_pools
from .paged_cache import PagedKVCache, PagePool, init_paged_cache
from .prefix_cache import PrefixCache
from .router import Router
from .scheduler import (
    ContinuousScheduler,
    Request,
    SLOPolicy,
    SLOScheduler,
    StaticScheduler,
    pages_for,
)
from .spec import LookupProposer, accept_len, lookup_propose

__all__ = [
    "ContinuousScheduler",
    "EngineCompute",
    "Fleet",
    "FleetResult",
    "Handoff",
    "LookupProposer",
    "PagedEngine",
    "PagedKVCache",
    "PagePool",
    "PrefixCache",
    "Replica",
    "Request",
    "Router",
    "SLOPolicy",
    "SLOScheduler",
    "ServeResult",
    "SimCompute",
    "StaticScheduler",
    "accept_len",
    "init_paged_cache",
    "lookup_propose",
    "pages_for",
    "parse_pools",
]
