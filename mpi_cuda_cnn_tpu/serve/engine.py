"""The serving engine: jitted paged ticks driven by a scheduler.

Exactly TWO compiled programs serve every request mix, so continuous
batching never retraces as the batch composition churns:

- `decode tick` — all engine slots advance one token in one forward
  (B = slots, k = 1, per-slot positions); dead/padded slots ride along
  with valid=False, their writes routed to the scratch page and their
  sampled tokens ignored by the host.
- `prefill chunk` — one slot advances `prefill_chunk` prompt tokens
  (B = 1, k = chunk, padded to the static chunk width). The LAST chunk
  of a prompt also yields the request's first generated token (argmax
  of the final valid position's logits) — TTFT is paid at prefill
  completion, not at the next decode tick.

A speculative engine (ISSUE 14: spec="lookup"/"draft") compiles ONE
additional program, the batched verify block — every slot's k candidate
rows at per-slot positions through the same paged_forward, rows past a
slot's round width riding along valid=False. serve/spec.py owns the
jax-free policy half (proposal, greedy acceptance, the round scaffold
shared with the fleet's ReplicaCore); the scheduler owns the
acceptance-aware page accounting (opportunistic growth toward k,
rejected-draft page rollback at commit).

Both donate the page pools, so the cache updates in place across ticks
(utils/donation discipline; the pool is the engine's dominant buffer).
Sampling is greedy — the serving benches measure schedule/memory
effects, and greedy keeps static-vs-continuous token streams bitwise
comparable per request.

The host loop (`run`) is one scheduler iteration per pass: sweep
deadlines/cancellations -> enforce the queue bound -> admit -> at most
one prefill chunk -> one decode tick over every decoding slot.
Interleaving the single chunk between ticks bounds how long a long
prompt can stall token emission for in-flight sequences (the Orca
iteration-level property); `decode_ticks`/`prefill_chunks` counts are
the deterministic cost model the CPU tests compare schedulers on.

Failure-awareness (ISSUE 4): `run` accepts a faults.FaultInjector whose
"serve.tick" site can squeeze the page pool (steal pages for a window
of ticks) or stall a tick; a tick watchdog counts iterations slower
than `watchdog_s`; every abort, rejection, expiry, injected fault, and
watchdog breach lands in `ServeResult.events` (obs `fault` records —
serve/bench.py writes them to the JSONL sink). Every submitted request
leaves with a terminal status; aborted slots return their pages through
the ownership-checked PagePool.free, and the pool invariant is checked
every iteration.
"""

from __future__ import annotations

import dataclasses
import time
import zlib

import jax.numpy as jnp
import numpy as np

from ..models.transformer import TransformerLM
from ..utils.donation import donate_jit
from .host_tier import TIER_SPILL_SITE, HostTier
from .paged_cache import (
    PagedKVCache,
    PagePool,
    init_paged_cache,
    paged_forward,
    pages_for,
)
from .prefix_cache import PrefixCache, empty_prefix_fields
from .spec import (
    SPEC_MODES,
    LookupProposer,
    empty_spec_fields,
    run_round,
)
from .scheduler import (
    ContinuousScheduler,
    Request,
    SLOPolicy,
    SLOScheduler,
    StaticScheduler,
    scheduler_digest,
    tenant_block,
    terminal_fields,
)


def request_record(r: Request, mode: str) -> dict:
    """One request as an obs `request` field dict — THE record shape
    report/trace consume, shared by ServeResult and FleetResult so the
    two surfaces cannot drift. Aborted requests carry null latencies
    where the moment never happened (no first token -> ttft_ms null);
    queue_wait_ms anchors on FIRST admission, null if never admitted."""
    return {
        "id": r.rid,
        "mode": mode,
        "status": r.status,
        "tenant": r.tenant or "default",
        "prompt_tokens": int(r.prompt.size),
        # The token budget (ISSUE 15): what obs/replay.py needs to
        # reconstruct static reservations and done-checks from the
        # trail alone (output_tokens only equals it for finished
        # requests).
        "max_new_tokens": int(r.max_new_tokens),
        "output_tokens": len(r.out),
        "ttft_ms": (None if r.first_token_at is None
                    else round(1e3 * (r.first_token_at - r.arrival), 3)),
        "latency_ms": (None if r.finished_at is None
                       else round(1e3 * (r.finished_at - r.arrival), 3)),
        # Lifecycle anchors (ISSUE 6): absolute arrival on the run's
        # clock (pairs with tick records' "now").
        "arrival_s": round(r.arrival, 4),
        "queue_wait_ms": (None if r.admitted_at is None
                          else round(1e3 * (r.admitted_at - r.arrival), 3)),
        # Quota skip-over share of the queue wait (ISSUE 11): time an
        # SLOScheduler spent skipping this request over for its own
        # tenant's quota — zero under FCFS/capacity waits.
        "queue_wait_quota_ms": round(1e3 * r.quota_wait_s, 3),
        "preemptions": r.preemptions,
        **({"reason": r.fail_reason} if r.fail_reason else {}),
    }


@dataclasses.dataclass
class ServeResult:
    """One engine run: every submitted request in a terminal status
    (with its timestamps filled in) plus the aggregate counters the
    bench reports. `requests` includes aborted ones — filter by
    `status` or use `finished_requests`."""

    mode: str
    requests: list[Request]
    decode_ticks: int
    prefill_chunks: int
    preemptions: int
    duration_s: float
    events: list[dict] = dataclasses.field(default_factory=list)
    watchdog_slow_ticks: int = 0
    # Prefix-cache structural counters (ISSUE 9): always present (zeros
    # with sharing off) so gated metrics exist in every run.
    prefix: dict = dataclasses.field(default_factory=empty_prefix_fields)
    # Speculative-decoding counters (ISSUE 14): rounds run, draft
    # tokens proposed, draft tokens accepted — always present (zeros
    # with spec off) so the gated metrics exist in every run.
    spec: dict = dataclasses.field(default_factory=empty_spec_fields)
    # Flight-recorder chain (ISSUE 15): crc32 chained over every tick's
    # state digest — ONE number that pins the full per-tick state
    # trajectory, stamped in the summary so the 0%/equal determinism
    # gates cover it even on summary-only runs.
    state_crc: int = 0

    @property
    def finished_requests(self) -> list[Request]:
        return [r for r in self.requests if r.status == "finished"]

    @property
    def output_tokens(self) -> int:
        # Tokens emitted before an abort were still served.
        return sum(len(r.out) for r in self.requests)

    @property
    def tokens_per_s(self) -> float:
        return self.output_tokens / max(self.duration_s, 1e-9)

    def status_counts(self) -> dict[str, int]:
        counts: dict[str, int] = {}
        for r in self.requests:
            counts[r.status] = counts.get(r.status, 0) + 1
        return counts

    def ttft_ms(self) -> list[float]:
        return [1e3 * (r.first_token_at - r.arrival)
                for r in self.finished_requests]

    def tpot_ms(self) -> list[float]:
        """Per-output-token latency (time-per-output-token) after the
        first token, per finished request; requests with one token
        report 0."""
        return [
            1e3 * (r.finished_at - r.first_token_at) / max(len(r.out) - 1, 1)
            for r in self.finished_requests
        ]

    def request_records(self) -> list[dict]:
        """Per-request field dicts in the obs `request` event shape
        (the caller stamps them through MetricsLogger/make_record).
        Aborted requests carry null latencies where the moment never
        happened (no first token -> ttft_ms null)."""
        return [request_record(r, self.mode)
                for r in sorted(self.requests, key=lambda r: r.rid)]

    def summary(self) -> dict:
        # Nearest-rank percentiles (obs.metrics.pct_nearest) — the ONE
        # serving convention, so `mctpu report`'s per-request table and
        # this summary can never disagree on the same run.
        from ..obs.metrics import pct_nearest

        ttft, tpot = self.ttft_ms(), self.tpot_ms()
        return {
            "mode": self.mode,
            "requests": len(self.requests),
            "statuses": self.status_counts(),
            "output_tokens": self.output_tokens,
            "decode_ticks": self.decode_ticks,
            "prefill_chunks": self.prefill_chunks,
            "preemptions": self.preemptions,
            "watchdog_slow_ticks": self.watchdog_slow_ticks,
            "duration_s": round(self.duration_s, 4),
            "tokens_per_s": round(self.tokens_per_s, 2),
            # Per-tick state-digest chain (ISSUE 15): gated at 0%/equal
            # by the determinism gates like trace_crc/blame_crc.
            "state_crc": self.state_crc,
            "ttft_p50_ms": pct_nearest(ttft, 50),
            "ttft_p99_ms": pct_nearest(ttft, 99),
            "tpot_p50_ms": pct_nearest(tpot, 50),
            "tpot_p99_ms": pct_nearest(tpot, 99),
            # Prefix-sharing counters (ISSUE 9), flat so `mctpu
            # compare` gates them as serve.<mode>.prefix_hits etc.
            **self.prefix,
            # Speculative-decoding counters (ISSUE 14), flat so `mctpu
            # compare` gates them as serve.<mode>.spec_rounds etc.
            **self.spec,
            # Per-tenant status/latency counts (ISSUE 8): the summary
            # keys `mctpu compare` flattens as serve.<mode>.tenant.<t>.*
            # and `mctpu health` falls back to on summary-only logs.
            "tenants": tenant_block(self.requests),
        }


def _observe_request(registry, r: Request) -> None:
    """Fold one terminal request into the registry: a per-status
    counter plus the latency histograms (same formulas as
    ServeResult.ttft_ms/tpot_ms, so the registry's percentiles and the
    summary's can never disagree on the same run). Null moments —
    aborted before admission or before the first token — are skipped,
    the serving null convention. A TAGGED tenant (ISSUE 8) additionally
    lands in `serve.tenant.<name>.*` twins of every metric, which is
    what `mctpu health` reads off a summary-only run; untagged requests
    stay global-only (a single-tenant run must not pay double)."""
    prefixes = ["serve."]
    if r.tenant is not None:
        prefixes.append(f"serve.tenant.{r.tenant}.")
    for p in prefixes:
        registry.inc(f"{p}requests_{r.status}")
        if r.admitted_at is not None:
            registry.observe(f"{p}queue_wait_ms",
                             1e3 * (r.admitted_at - r.arrival))
        if r.quota_wait_s > 0:
            # The SLOScheduler skip-over share of the wait (ISSUE 11),
            # split out so a quota-throttled tenant's policy wait can't
            # masquerade as a capacity shortage. Observed only when
            # nonzero: FCFS runs must not bury the histogram in zeros.
            registry.observe(f"{p}queue_wait_quota_ms",
                             1e3 * r.quota_wait_s)
        if r.status != "finished":
            continue
        registry.observe(f"{p}ttft_ms",
                         1e3 * (r.first_token_at - r.arrival))
        registry.observe(
            f"{p}tpot_ms",
            1e3 * (r.finished_at - r.first_token_at) / max(len(r.out) - 1, 1),
        )


class DraftProposer:
    """Model-draft proposal behind the LookupProposer interface
    (ISSUE 14): a cheap draft model proposes each slot's k-1 candidate
    tokens by greedy argmax over a fixed sliding WINDOW of the
    request's committed context — cacheless, so the draft needs no
    paged pools, no COW, and no handoff story of its own (the full
    per-slot draft KV cache is the chip-scale follow-up; T=0 exactness
    never depends on the draft, only the acceptance rate does). The
    draft steps are BATCHED across slots like the verify block: one
    jitted (batch, W) window forward per draft position — k-1 forwards
    and k-1 host syncs per tick, however many slots speculate — with
    static shapes, compiled once."""

    def __init__(self, model: TransformerLM, params, *, window: int = 32,
                 batch: int = 1):
        import jax

        self.model = model
        self.window = min(window, model.max_seq)
        self.batch = batch
        self.params = params

        @jax.jit
        def step(params, toks, n_valid):
            # Full causal forward over the padded windows; each row's
            # proposal is the argmax after its last VALID position
            # (causal masking keeps the pad tail out of that logit).
            logits = model.apply(params, toks, moe_inference=True)
            picks = jnp.argmax(logits, axis=-1)            # (B, W)
            idx = jnp.maximum(n_valid - 1, 0)
            return jnp.take_along_axis(
                picks, idx[:, None], axis=1)[:, 0].astype(jnp.int32)

        self._step = step

    def propose(self, ctx: np.ndarray, n_props: int) -> np.ndarray:
        return self.propose_batch([ctx], [n_props])[0]

    def propose_batch(self, ctxs, n_props):
        """Per-slot proposals for one round, drafted in lockstep: draft
        position i runs ONE (batch, W) forward for every slot at once
        (rows past a slot's own width ride along; their picks are
        dropped host-side)."""
        n_max = max(n_props, default=0)
        if n_max == 0:
            return [np.empty(0, np.int32) for _ in ctxs]
        if len(ctxs) > self.batch:
            raise ValueError(
                f"{len(ctxs)} draft contexts exceed batch {self.batch}")
        w = self.window
        bufs = [[int(t) for t in c[-w:]] for c in ctxs]
        outs = [[] for _ in ctxs]
        for step_i in range(n_max):
            toks = np.zeros((self.batch, w), np.int32)
            n_valid = np.ones((self.batch,), np.int32)
            for i, buf in enumerate(bufs):
                win = buf[-w:]
                toks[i, : len(win)] = win
                n_valid[i] = max(len(win), 1)
            # The sanctioned sync: one host transfer per BATCHED draft
            # step (every slot's pick in one array), not per sequence.
            # mctpu: disable=MCT007
            picks = np.asarray(self._step(
                self.params, jnp.asarray(toks), jnp.asarray(n_valid)))
            for i, buf in enumerate(bufs):
                if step_i < n_props[i]:
                    # Host-side already (the batched fetch above);
                    # int() here is list bookkeeping, not a new sync.
                    # mctpu: disable=MCT007
                    t = int(picks[i])
                    outs[i].append(t)
                    buf.append(t)
        return [np.asarray(o, np.int32) for o in outs]


class PagedDraftProposer:
    """The paged draft-model KV cache (ISSUE 17, the PR-14 remainder):
    the draft becomes just another paged-cache client — its own small
    PagePool + per-slot block tables growing and rolling back in
    lockstep with the target's commit_spec — replacing the cacheless
    sliding-window draft that recomputes ~W x the FLOPs per round.

    Per round and slot the paged draft runs CATCH-UP (the tokens
    committed since its last round — at steady state the previous
    round's accepted count, not the whole window) plus n single-token
    proposal steps, against its own persistent KV pages. At round end
    each slot's draft rows are TRIMMED back to the committed context
    (pages holding only proposal rows return to the draft pool) — the
    rollback twin of the target scheduler's commit_spec page law, so a
    rejected draft token's KV is never live on either cache. Proposal
    rows inside the kept partial page are overwritten before they are
    ever read (paged_update_attend writes first; the causal mask keeps
    unwritten positions out of the softmax).

    Page accounting laws (what `mctpu replay` mirrors, the state_crc
    extension): after a slot's round the draft holds exactly
    pages_for(committed_rows) pages, where committed_rows is the
    slot's pre-commit `cached` (= len(prompt)+len(out)-1 at propose
    time); a slot's state persists LAZILY across release (reset on the
    next rid mismatch or context shrink), and the pool is sized to
    slots x pages_for(max_len) so the deterministic schedule never
    depends on a draft-pool dry path. T=0 exactness never depends on
    the draft (the acceptance scaffold is the same for any proposer) —
    only FLOPs per round do.
    """

    # run_round feeds slot identities (and every slot's real context)
    # to proposers that carry per-slot cache state.
    needs_slots = True

    def __init__(self, model: TransformerLM, params, *, slots: int,
                 page_size: int, max_len: int, cache_dtype=jnp.float32,
                 chunk: int = 32, attn_kernel: str = "gather"):
        self.model = model
        self.params = params
        self.slots = slots
        self.page_size = page_size
        self.max_len = min(max_len, model.max_seq)
        self.table_width = pages_for(self.max_len, page_size)
        self.chunk = chunk
        self.attn_kernel = attn_kernel
        # +1 for the reserved scratch page: full per-slot coverage, so
        # draft paging changes FLOPs, never the serving schedule.
        self.pool = PagePool(slots * self.table_width + 1)
        tmpl = init_paged_cache(model, slots=slots,
                                num_pages=slots * self.table_width + 1,
                                page_size=page_size, dtype=cache_dtype,
                                max_len=self.max_len, kernel=attn_kernel)
        self._pages = tmpl.pages
        # Per-slot draft state, indexed by ENGINE slot idx: the rid the
        # cache rows belong to, committed rows held, physical pages.
        self._rid: list = [None] * slots
        self._cached = [0] * slots
        self._spages: list[list[int]] = [[] for _ in range(slots)]

        ck = self.chunk

        def catchup(cache: PagedKVCache, params, toks, pos0, n_valid):
            positions = pos0[:, None] + jnp.arange(ck)[None, :]
            valid = jnp.arange(ck)[None, :] < n_valid[:, None]
            _, cache = paged_forward(model, params, toks, positions,
                                     valid, cache)
            return cache

        def step(cache: PagedKVCache, params, toks, pos, live):
            logits, cache = paged_forward(
                model, params, toks[:, None], pos[:, None], live[:, None],
                cache,
            )
            return cache, jnp.argmax(
                logits[:, 0, :], axis=-1).astype(jnp.int32)

        self._catchup = donate_jit(catchup)
        self._step = donate_jit(step)

    @property
    def tracked(self) -> int:
        """Slots carrying draft-cache state (the digest's lazy-state
        count — entries persist across slot release until reused)."""
        return sum(1 for r in self._rid if r is not None)

    def _owner(self, idx: int) -> tuple:
        return ("draft", idx)

    def _reset(self, idx: int, rid) -> None:
        if self._spages[idx]:
            self.pool.free(self._spages[idx], self._owner(idx))
        self._rid[idx] = rid
        self._cached[idx] = 0
        self._spages[idx] = []

    def _ensure_pages(self, idx: int, rows: int) -> None:
        need = pages_for(rows, self.page_size) - len(self._spages[idx])
        if need > 0:
            got = self.pool.try_alloc(need, self._owner(idx))
            assert got is not None, "draft pool sized to full coverage"
            self._spages[idx].extend(got)

    def _trim(self, idx: int, rows: int) -> None:
        keep = pages_for(rows, self.page_size)
        extra = self._spages[idx][keep:]
        if extra:
            self.pool.free(extra, self._owner(idx))
            del self._spages[idx][keep:]

    def _cache_view(self, table: np.ndarray) -> PagedKVCache:
        return PagedKVCache(pages=self._pages,
                            block_table=jnp.asarray(table),
                            page_size=self.page_size,
                            kernel=self.attn_kernel)

    def end_run(self) -> None:
        """Release every slot's draft pages and prove the draft pool
        clean — the engine's end-of-run twin of the main pool check."""
        for idx in range(self.slots):
            if self._spages[idx]:
                self.pool.free(self._spages[idx], self._owner(idx))
            self._rid[idx] = None
            self._cached[idx] = 0
            self._spages[idx] = []
        self.pool.check()
        assert self.pool.free_pages == self.pool.usable, \
            "draft pages leaked"

    def propose_batch(self, ctxs, n_props, dslots):
        """One paged draft round over this tick's decoding slots:
        reset stale state (rid change / context shrink — the preempt
        rollback), grow each slot's block table to cover catch-up +
        proposal rows, run batched catch-up chunks then n single-token
        steps, and trim every slot back to its committed rows."""
        outs = [np.empty(0, np.int32) for _ in ctxs]
        work = []       # (idx, ctx, n, committed_rows)
        for s, ctx, n in zip(dslots, ctxs, n_props):
            idx = s.idx
            rows = len(ctx) - 1     # committed KV rows the draft holds
            if self._rid[idx] != s.req.rid or self._cached[idx] > rows:
                self._reset(idx, s.req.rid)
            self._ensure_pages(idx, rows + max(n, 0))
            work.append((idx, ctx, n, rows))
        # Batched catch-up: every behind slot advances `chunk` rows per
        # jitted call until all hold their committed rows.
        table = np.zeros((self.slots, self.table_width), np.int32)
        for idx, _, _, _ in work:
            table[idx, : len(self._spages[idx])] = self._spages[idx]
        while True:
            toks = np.zeros((self.slots, self.chunk), np.int32)
            pos0 = np.zeros((self.slots,), np.int32)
            n_valid = np.zeros((self.slots,), np.int32)
            behind = False
            for idx, ctx, _, rows in work:
                got = self._cached[idx]
                if got >= rows:
                    continue
                n = min(self.chunk, rows - got)
                toks[idx, :n] = ctx[got : got + n]
                pos0[idx] = got
                n_valid[idx] = n
                self._cached[idx] = got + n
                behind = True
            if not behind:
                break
            cache = self._catchup(
                self._cache_view(table), self.params, jnp.asarray(toks),
                jnp.asarray(pos0), jnp.asarray(n_valid),
            )
            self._pages = cache.pages
        # n proposal steps, batched across slots: step t feeds the
        # previous pick (step 1: the slot's last committed token) at
        # position rows + t - 1, writing that row and reading the
        # causal prefix below it.
        n_max = max(n_props, default=0)
        if n_max > 0:
            cur = np.zeros((self.slots,), np.int32)
            pos = np.zeros((self.slots,), np.int32)
            for idx, ctx, n, rows in work:
                cur[idx] = ctx[-1]
                pos[idx] = rows
            for t in range(n_max):
                live = np.zeros((self.slots,), bool)
                for i, (idx, ctx, n, rows) in enumerate(work):
                    live[idx] = t < n
                cache, picks = self._step(
                    self._cache_view(table), self.params,
                    jnp.asarray(cur), jnp.asarray(pos), jnp.asarray(live),
                )
                self._pages = cache.pages
                # The sanctioned sync: one host transfer per BATCHED
                # draft step (every slot's pick in one array).
                # mctpu: disable=MCT007
                picks = np.asarray(picks)
                for i, (idx, ctx, n, rows) in enumerate(work):
                    if t < n:
                        outs[i] = np.append(outs[i], picks[idx])
                        cur[idx] = picks[idx]
                        pos[idx] += 1
        # Roll back to committed rows: pages holding only proposal
        # rows return to the draft pool (commit_spec's rollback twin).
        for idx, ctx, n, rows in work:
            self._trim(idx, rows)
            self._cached[idx] = rows
        return [np.asarray(o, np.int32) for o in outs]


class PagedEngine:
    """Greedy serving engine over a paged KV cache.

    slots bounds the decode batch; num_pages * page_size tokens is the
    TOTAL cache budget shared by all in-flight sequences (page 0 is
    scratch); max_len bounds any one sequence (prompt + new tokens) and
    sizes the block table. cache_dtype composes with the shipped
    --decode-cache-dtype forms (float32 / bfloat16 / int8).

    ISSUE 12 levers, both behind the ONE shared decode implementation:
    `attn_kernel` picks the paged read — "gather" (XLA) or "pallas"
    (the fused ops/pallas_paged_attention kernel; bitwise in f32,
    <= 1e-5 in bf16/int8) — carried as PagedKVCache metadata so both
    jitted programs (run_prefill_chunk / run_decode_tick) compile the
    same choice; `weights_dtype` quantizes the decode GEMV weights ONCE
    at construction (ops/pallas_gemv.quantize_decode_params — int8
    per-channel absmax, bf16 cast, or f32 pass-through; "auto" routes
    via generate.pick_weights_dtype, the pick_cache_dtype twin).
    """

    def __init__(self, model: TransformerLM, params, *, slots: int = 4,
                 num_pages: int = 64, page_size: int = 16,
                 prefill_chunk: int = 32, cache_dtype="float32",
                 max_len: int | None = None, attn_kernel: str = "gather",
                 weights_dtype: str = "float32", spec: str = "off",
                 spec_k: int = 8, spec_ngram: int = 2,
                 draft_model: TransformerLM | None = None,
                 draft_params=None, draft_cache: str = "window"):
        from ..models.generate import pick_cache_dtype, pick_weights_dtype
        from ..ops.pallas_gemv import quantize_decode_params

        if spec not in SPEC_MODES:
            raise ValueError(f"spec {spec!r}: want one of {SPEC_MODES}")
        if draft_cache not in ("window", "paged"):
            raise ValueError(
                f"draft_cache {draft_cache!r}: want 'window' or 'paged'")
        if spec != "off" and spec_k < 2:
            raise ValueError(
                f"spec_k must be >= 2 (k={spec_k} would propose nothing)")
        if spec == "draft":
            if draft_model is None or draft_params is None:
                raise ValueError(
                    "spec='draft' needs draft_model + draft_params")
            if draft_model.vocab != model.vocab:
                raise ValueError(
                    f"target vocab {model.vocab} != draft vocab "
                    f"{draft_model.vocab}")
        self.spec_mode = spec
        self.spec_k = spec_k
        self.spec_ngram = spec_ngram
        self.draft_cache = draft_cache
        self.model = model
        self.slots = slots
        self.page_size = page_size
        self.num_pages = num_pages
        self.prefill_chunk = prefill_chunk
        self.weights_dtype = pick_weights_dtype(
            weights_dtype, heads=model.heads, kv_heads=model.n_kv)
        # One-time conversion: the hot loop only ever reads this form.
        self.params = quantize_decode_params(params, self.weights_dtype)
        self.attn_kernel = attn_kernel
        if isinstance(cache_dtype, str) and cache_dtype == "auto":
            # VERDICT item 7: route the storage dtype from the banked
            # measurements — int8 for GQA/MQA, bfloat16 for MHA.
            cache_dtype = pick_cache_dtype("auto", heads=model.heads,
                                           kv_heads=model.n_kv)
        self.cache_dtype = jnp.dtype(cache_dtype)
        self.max_len = min(max_len or model.max_seq, model.max_seq)
        tmpl = init_paged_cache(model, slots=slots, num_pages=num_pages,
                                page_size=page_size, dtype=self.cache_dtype,
                                max_len=self.max_len, kernel=attn_kernel)
        self._pages = tmpl.pages
        self._table_width = tmpl.block_table.shape[1]

        def tick(cache: PagedKVCache, params, toks, pos, live):
            logits, cache = paged_forward(
                model, params, toks[:, None], pos[:, None], live[:, None],
                cache,
            )
            return cache, jnp.argmax(logits[:, 0, :], axis=-1).astype(jnp.int32)

        chunk = prefill_chunk

        def prefill(cache: PagedKVCache, params, toks, pos0, n_valid):
            positions = pos0 + jnp.arange(chunk)[None, :]
            valid = (jnp.arange(chunk) < n_valid)[None, :]
            logits, cache = paged_forward(
                model, params, toks, positions, valid, cache
            )
            nxt = jnp.argmax(logits[0, jnp.maximum(n_valid - 1, 0)])
            return cache, nxt.astype(jnp.int32)

        def copy(pages, src, dst):
            # Copy-on-write (ISSUE 9): duplicate one physical page's
            # rows across every layer's pools — the divergent request
            # writes into the copy, the shared source stays read-only.
            return [
                {name: c[name].at[dst].set(c[name][src]) for name in c}
                for c in pages
            ]

        def adopt(pages, src_pool, src, dst):
            # Cross-engine KV transfer (ISSUE 13): scatter the sender
            # pool's page rows (keys, values, int8 scales alike) into
            # this engine's pools at the destination indices — the
            # device half of the prefill->decode handoff.
            return [
                {name: c[name].at[dst].set(s[name][src]) for name in c}
                for c, s in zip(pages, src_pool)
            ]

        def restore(pages, host_rows, dst):
            # Host-tier readmission (ISSUE 17): scatter one spilled
            # page's host-resident rows back into every layer's pools
            # at the freshly allocated device page — adopt()'s
            # host->device twin, one page per call (readmissions are
            # per-walk-chunk events, not bulk transfers).
            return [
                {name: c[name].at[dst].set(h[name]) for name in c}
                for c, h in zip(pages, host_rows)
            ]

        # Donate the cache: the page pools update in place tick-to-tick
        # (the engine always adopts the returned cache) instead of
        # allocating a second pool-sized buffer per dispatch. donate_jit
        # is the repo's ONE donation spelling (`mctpu lint` MCT003).
        self._tick = donate_jit(tick)
        self._prefill = donate_jit(prefill)
        self._copy = donate_jit(copy)
        self._adopt = donate_jit(adopt)
        self._restore = donate_jit(restore)
        # Speculative verify (ISSUE 14): ONE batched block forward per
        # round — every slot's k candidate rows at per-slot positions
        # through the same paged_forward the plain tick compiles, with
        # per-row validity (short rounds and dead slots write scratch).
        # Compiled only when speculation is configured: a spec-off
        # engine keeps exactly its two programs.
        self._spec = None
        self._draft_proposer = None
        if spec != "off":
            kk = spec_k

            def spec_tick(cache: PagedKVCache, params, toks, pos, valid):
                positions = pos[:, None] + jnp.arange(kk)[None, :]
                logits, cache = paged_forward(
                    model, params, toks, positions, valid, cache
                )
                return cache, jnp.argmax(logits, axis=-1).astype(jnp.int32)

            self._spec = donate_jit(spec_tick)
            if spec == "draft":
                dparams = quantize_decode_params(draft_params,
                                                 self.weights_dtype)
                if draft_cache == "paged":
                    self._draft_proposer = PagedDraftProposer(
                        draft_model, dparams, slots=slots,
                        page_size=page_size, max_len=self.max_len,
                        cache_dtype=self.cache_dtype,
                        chunk=prefill_chunk, attn_kernel=attn_kernel)
                else:
                    self._draft_proposer = DraftProposer(
                        draft_model, dparams, batch=slots)

    # -- host-side helpers ------------------------------------------------

    def _cache_view(self, table: np.ndarray) -> PagedKVCache:
        return PagedKVCache(pages=self._pages,
                            block_table=jnp.asarray(table),
                            page_size=self.page_size,
                            kernel=self.attn_kernel)

    def _slot_table(self, slot) -> np.ndarray:
        row = np.zeros((1, self._table_width), np.int32)
        row[0, : len(slot.pages)] = slot.pages
        return row

    def _emit(self, slot, tok: int, now: float) -> None:
        req = slot.req
        req.out.append(tok)
        if req.first_token_at is None:
            req.first_token_at = now

    def copy_page(self, src: int, dst: int) -> None:
        """Device-side COW: duplicate page `src` into page `dst` in
        every layer's pools (keys and values, plus int8 scales). The
        caller (engine.run / ReplicaCore.step) releases the shared
        source's reference via scheduler.cow_complete afterwards."""
        self._pages = self._copy(self._pages, jnp.int32(src),
                                 jnp.int32(dst))

    def spill_page(self, page: int):
        """Fetch one device page's KV rows (every layer's keys/values,
        plus int8 scales) to host memory — HostTier.spill_fn. The
        device->host transfer happens HERE, before the pool frees the
        page; the page's content is then owned by the tier entry until
        readmission or host eviction."""
        # Device->host fetch of the evicted page — the spill's one
        # sanctioned sync (an np.asarray per layer pool).
        # mctpu: disable=MCT007
        return [{name: np.asarray(c[name][page]) for name in c}
                for c in self._pages]

    def readmit_page(self, page: int, payload) -> None:
        """Restore a spilled page's host-resident KV rows into device
        page `page` — HostTier.readmit_fn, called only AFTER the CRC
        verify accepted the entry (a refused spill is never restored,
        so garbage rows cannot enter the pools)."""
        self._pages = self._restore(
            self._pages,
            [{name: jnp.asarray(h[name]) for name in h} for h in payload],
            jnp.int32(page),
        )

    def adopt_pages(self, src_engine: "PagedEngine", src_pages,
                    dst_pages) -> None:
        """Adopt KV page content from another engine's pools (the
        disaggregated prefill->decode handoff, ISSUE 13): the sender's
        rows at `src_pages` land at this engine's `dst_pages`, every
        layer's keys/values (and int8 scales) together. Both engines
        must share the cache geometry — the fleet builds every replica
        from one model/config, which is also what makes the handed-off
        decode bitwise-equal to the unified one."""
        if (src_engine.page_size != self.page_size
                or src_engine.cache_dtype != self.cache_dtype
                or len(src_engine._pages) != len(self._pages)):
            raise ValueError(
                "adopt_pages across mismatched cache geometries "
                f"(page_size {src_engine.page_size} vs {self.page_size}, "
                f"dtype {src_engine.cache_dtype} vs {self.cache_dtype})"
            )
        if len(src_pages) != len(dst_pages):
            raise ValueError(
                f"adopt_pages: {len(src_pages)} source pages vs "
                f"{len(dst_pages)} destinations"
            )
        # Pad the index arrays to the next power of two so the jitted
        # scatter compiles O(log num_pages) shapes, not one per handoff
        # page count. Pad entries copy the sender's scratch page onto
        # THIS pool's scratch page (page 0 on both ends) — scratch is
        # the sanctioned garbage sink, never read as live data.
        n = len(src_pages)
        width = 1 << max(n - 1, 0).bit_length()
        src = np.zeros(width, np.int32)
        dst = np.zeros(width, np.int32)
        src[:n] = src_pages
        dst[:n] = dst_pages
        self._pages = self._adopt(
            self._pages, src_engine._pages,
            jnp.asarray(src), jnp.asarray(dst),
        )

    def run_prefill_chunk(self, slot):
        """Advance `slot`'s prefill by one chunk on the device. Returns
        (rows written, next-token argmax of the chunk's last valid row
        — the request's first generated token iff this chunk completes
        the prefill). The token stays a device array so intermediate
        chunks pipeline under async dispatch: the caller converts it
        (int()) only on the COMPLETING chunk, where it is emitted.
        Scheduler bookkeeping (slot.cached, emission) is the caller's:
        run() and the fleet's EngineCompute (ISSUE 7) share this one
        device path."""
        ctx = np.concatenate(
            [slot.req.prompt, np.asarray(slot.req.out, np.int32)]
        )
        n = min(self.prefill_chunk, slot.target - slot.cached)
        toks = np.zeros((1, self.prefill_chunk), np.int32)
        toks[0, :n] = ctx[slot.cached : slot.cached + n]
        cache, nxt = self._prefill(
            self._cache_view(self._slot_table(slot)), self.params,
            jnp.asarray(toks), jnp.int32(slot.cached), jnp.int32(n),
        )
        self._pages = cache.pages
        return n, nxt

    def run_decode_tick(self, dslots) -> np.ndarray:
        """One batched decode tick over `dslots` (every other engine
        row rides along dead). Returns the per-row sampled tokens
        (index by slot.idx); cached/emit bookkeeping is the caller's."""
        toks = np.zeros((self.slots,), np.int32)
        pos = np.zeros((self.slots,), np.int32)
        live = np.zeros((self.slots,), bool)
        table = np.zeros((self.slots, self._table_width), np.int32)
        for s in dslots:
            toks[s.idx] = s.req.out[-1]
            pos[s.idx] = s.cached
            live[s.idx] = True
            table[s.idx, : len(s.pages)] = s.pages
        cache, nxt = self._tick(
            self._cache_view(table), self.params, jnp.asarray(toks),
            jnp.asarray(pos), jnp.asarray(live),
        )
        self._pages = cache.pages
        # THE sanctioned sync: one host transfer per BATCHED tick
        # (every live slot's token in one array), not per sequence.
        # mctpu: disable=MCT007
        return np.asarray(nxt)

    def run_spec_tick(self, rounds):
        """ONE batched speculative verify over this tick's rounds
        (ISSUE 14): rounds is spec.run_round's [(slot, u, width)] —
        each slot's verify inputs land in its own engine row at its own
        positions [cached, cached+width), rows past a slot's width (and
        every dead slot) ride along valid=False with their writes
        routed to the scratch page. Returns each slot's per-row greedy
        picks (the verify_fn contract run_round consumes)."""
        kk = self.spec_k
        toks = np.zeros((self.slots, kk), np.int32)
        pos = np.zeros((self.slots,), np.int32)
        valid = np.zeros((self.slots, kk), bool)
        table = np.zeros((self.slots, self._table_width), np.int32)
        for s, u, w in rounds:
            toks[s.idx, :w] = u
            pos[s.idx] = s.cached
            valid[s.idx, :w] = True
            table[s.idx, : len(s.pages)] = s.pages
        cache, picks = self._spec(
            self._cache_view(table), self.params, jnp.asarray(toks),
            jnp.asarray(pos), jnp.asarray(valid),
        )
        self._pages = cache.pages
        # The sanctioned sync: one host transfer per BATCHED verify
        # round (every slot's picks in one array), not per sequence.
        # mctpu: disable=MCT007
        picks = np.asarray(picks)
        return [picks[s.idx, :w] for s, _, w in rounds]

    def run(self, requests: list[Request], *, mode: str = "continuous",
            time_fn=time.perf_counter, faults=None, max_queue: int | None = None,
            watchdog_s: float = 0.0, sleep_fn=time.sleep,
            registry=None, tick_sink=None, prefix: bool = False,
            policy: SLOPolicy | None = None,
            spec: bool = False, host_pages: int = 0) -> ServeResult:
        """Serve `requests` to a terminal status each; return ServeResult.

        Requests are mutated in place (out/timestamps/status); arrivals
        and deadlines are seconds relative to run start on `time_fn`'s
        clock — the loop idles (sleep_fn) until the next arrival when
        there is nothing admitted to work on. `faults` injects
        squeeze/slow faults at the "serve.tick" site (tick value = the
        iteration index); watchdog_s > 0 counts iterations slower than
        that budget. Deterministic tests drive time_fn/sleep_fn with a
        faults.FakeClock.

        Observability (ISSUE 6): `registry` is an obs.MetricsRegistry
        the engine updates in place — per-tick gauges (queue depth,
        running/prefilling slots, free pages, chunked-prefill backlog)
        and per-request histograms (ttft_ms/tpot_ms/queue_wait_ms) —
        and `tick_sink` receives each per-iteration tick field dict as
        it happens (serve/bench.py points it at the metrics JSONL, which
        is what makes `mctpu top` live-tailable mid-run). Both default
        to off: the hot loop pays nothing unless asked.

        Prefix sharing + SLO policy (ISSUE 9): `prefix=True` puts a
        PrefixCache over the run's pool — a request whose prompt shares
        cached prefix pages prefills only its suffix (TTFT drops
        accordingly; outputs stay bitwise-identical in f32). `policy`
        upgrades continuous batching to the SLOScheduler (priority
        classes, per-tenant quotas, burn-driven preemption). Both apply
        to iteration-level scheduling only — static batching is the
        reservation baseline the comparison measures.

        Speculative decoding (ISSUE 14): `spec=True` (on an engine
        constructed with spec="lookup"/"draft") replaces the one-token
        decode tick with a speculative ROUND — per-slot k-token
        proposal, ONE batched verify forward, greedy acceptance
        committing 1..k tokens per slot per tick (serve/spec.py).
        Iteration-level only, like prefix sharing: static stays the
        one-token baseline. At T=0 (the engine's only sampling) the
        emitted streams are the target's own greedy continuations —
        bitwise-equal to a spec-off run per request, while the tick
        count drops with the acceptance rate.

        Host-tier spill (ISSUE 17): `host_pages > 0` (requires
        prefix=True) puts a bounded HostTier under the prefix cache —
        LRU-reclaimed refcount-0 prefix pages spill device->host
        instead of being discarded, and a later prefix hit readmits
        them host->device (serve/host_tier.py). CRC-sealed at the tier
        crossing: a torn/corrupt spill is refused and degrades to
        re-prefill. Outputs stay bitwise-identical to a spill-off run
        in f32; only the prefill-chunk count (and TTFT) change.
        """
        if spec and self.spec_mode == "off":
            raise ValueError(
                "run(spec=True) on an engine constructed with "
                "spec='off' — pass spec='lookup' or 'draft' at "
                "construction (the verify program compiles there)"
            )
        if spec and mode != "continuous":
            raise ValueError(
                "speculative decoding is iteration-level — continuous "
                "batching only (static is the one-token-per-tick "
                "reservation baseline)"
            )
        if host_pages > 0 and not prefix:
            raise ValueError(
                "host_pages > 0 without prefix=True — the host tier "
                "spills prefix-cache pages; there is nothing to spill"
            )
        if host_pages == 0 and faults is not None:
            # Inert-fault contract, tier leg (mirrors Fleet.__init__):
            # without a host tier no spill ever happens, so a tier.spill
            # fault would silently never fire.
            inert = [f"{f.kind}@{f.site}"
                     for f in faults.pending(TIER_SPILL_SITE)]
            if inert:
                raise ValueError(
                    f"fault(s) {', '.join(sorted(set(inert)))} need a "
                    "host tier (--spill / host_pages > 0) — without one "
                    "they would silently never fire"
                )
        pool = PagePool(self.num_pages)
        tier = None
        if host_pages > 0:
            tier = HostTier(
                host_pages, spill_fn=self.spill_page,
                readmit_fn=self.readmit_page,
                fault_poll=((lambda seq: faults.poll(TIER_SPILL_SITE, seq))
                            if faults is not None else None),
            )
        pcache = PrefixCache(pool, self.page_size, tier) if prefix else None
        proposer = None
        if spec:
            proposer = (self._draft_proposer if self.spec_mode == "draft"
                        else LookupProposer(self.spec_ngram))
        draft_paged = isinstance(proposer, PagedDraftProposer)
        spec_rounds = spec_proposed = spec_accepted = 0
        sched_kw = dict(slots=self.slots, pool=pool,
                        page_size=self.page_size, max_len=self.max_len,
                        max_queue=max_queue, prefix=pcache)
        if mode == "continuous":
            if policy is not None:
                sched = SLOScheduler(policy=policy, **sched_kw)
            else:
                sched = ContinuousScheduler(**sched_kw)
        elif mode == "static":
            if prefix or policy is not None:
                raise ValueError(
                    "prefix sharing / SLO policy apply to continuous "
                    "batching only — static is the reservation baseline"
                )
            sched = StaticScheduler(**{**sched_kw, "prefix": None})
        else:
            raise ValueError(f"mode {mode!r}: want 'continuous' or 'static'")
        sched.submit(requests)
        n_reqs = sched.unfinished
        decode_ticks = prefill_chunks = 0
        state_chain = 0
        # Digest framing: spec-off (0, 0), window-draft/lookup spec
        # (1, k) — both the ISSUE-14/15 spellings, bit-for-bit. A PAGED
        # draft (ISSUE 17) extends the tuple with its pool state per
        # tick below; the longer frame can never alias the shorter one
        # (state_digest length-frames the extra block).
        spec_extra = (1, self.spec_k) if spec else (0, 0)
        events: list[dict] = []
        failed_logged: set[int] = set()  # rids with a request_failed event
        watchdog_slow = 0
        squeezes: list[dict] = []  # {"pages": [...], "until": tick}
        tick_idx = 0
        want_ticks = registry is not None or tick_sink is not None
        # Arrival announcements (ISSUE 11): each tick record names the
        # rids whose arrival fell due since the last one, so `mctpu
        # explain` can anchor every request's blame span on the tick
        # axis without needing the end-of-run request records.
        arrivals = sorted((r.arrival, r.rid) for r in requests)
        arr_cursor = 0
        # Terminal-request watermarks: sched.finished / sched.dropped
        # are append-only, so the new tail since last iteration IS this
        # tick's terminal set — no instrumentation at the call sites.
        n_fin_seen = n_drop_seen = 0
        t0 = time_fn()
        while sched.unfinished:
            iter_t0 = time_fn()
            if faults is not None:
                for f in faults.fire("serve.tick", tick_idx):
                    if f.kind == "squeeze":
                        # Steal up to `pages` pages for `ticks` ticks —
                        # ownership-checked like any sequence's, so the
                        # end-of-run pool invariant still proves zero
                        # leaks with faults active.
                        want = int(f.arg("pages", 1))
                        got = sched.pool.try_alloc(
                            min(want, sched.pool.free_pages),
                            f"_fault_squeeze_{tick_idx}",
                        ) or []
                        squeezes.append({
                            "pages": got,
                            "owner": f"_fault_squeeze_{tick_idx}",
                            "until": tick_idx + int(f.arg("ticks", 1)),
                        })
                    elif f.kind == "slow":
                        faults.sleep(float(f.arg("s", 0.05)))
                events.extend(faults.drain_events())
            for sq in [s for s in squeezes if s["until"] <= tick_idx]:
                if sq["pages"]:
                    sched.pool.free(sq["pages"], sq["owner"])
                squeezes.remove(sq)
            now = time_fn() - t0
            for r in sched.sweep(now):
                events.append({"kind": f"request_{r.status}", "id": r.rid,
                               "mode": mode, "t_rel": round(now, 4)})
            admitted = [[s.idx, s.req.rid] for s in sched.admit(now)]
            # Backpressure AFTER admission: the bound applies to what
            # remains waiting once free slots have been filled.
            for r in sched.enforce_queue_bound(now):
                events.append({"kind": "request_rejected", "id": r.rid,
                               "mode": mode, "t_rel": round(now, 4)})
            progressed = False
            prefill_rec = None

            # At most ONE prefill chunk per iteration: long prompts
            # advance without starving in-flight decodes.
            slot = sched.prefill_slot()
            if slot is not None:
                if slot.cow is not None:
                    # Copy-on-write (ISSUE 9): duplicate the partially
                    # matched shared page into the slot's private page
                    # BEFORE its first write lands there.
                    self.copy_page(*slot.cow)
                    sched.cow_complete(slot)
                n, nxt = self.run_prefill_chunk(slot)
                slot.cached += n
                prefill_chunks += 1
                prefill_rec = [slot.idx, slot.req.rid, n]
                progressed = True
                if slot.cached >= slot.target:
                    # Prefill complete: the full prompt's pages are now
                    # adoptable into the prefix tree (ISSUE 9), and the
                    # chunk's last valid logits give the first generated
                    # token right now. A request done at its first token
                    # releases its slot only under continuous batching —
                    # static holds every reservation until the batch
                    # drains (the occupancy discipline the comparison
                    # measures).
                    sched.note_prefill_complete(slot)
                    # Sanctioned sync: int() ONLY on the completing
                    # chunk, where the token is emitted — mid-prompt
                    # chunks pipeline the device array untouched.
                    # mctpu: disable=MCT007
                    self._emit(slot, int(nxt), time_fn() - t0)
                    prefill_rec.append("emit")  # first token at completion
                    if slot.req.done and isinstance(sched,
                                                    ContinuousScheduler):
                        sched.finish(slot, time_fn() - t0)

            dslots = sched.grow_for_decode(
                time_fn() - t0, spec_k=self.spec_k if spec else 1)
            decoded = [[s.idx, s.req.rid] for s in dslots]
            for r in sched.dropped:
                # admit/grow_for_decode may have failed a livelocked
                # request; log each rid once.
                if r.status == "failed" and r.rid not in failed_logged:
                    failed_logged.add(r.rid)
                    events.append({"kind": "request_failed", "id": r.rid,
                                   "mode": mode, "reason": r.fail_reason})
            spec_rec = None
            emitted_decode = 0
            if dslots and spec:
                # Speculative round (ISSUE 14): propose per slot, ONE
                # batched verify block, greedy acceptance — each slot
                # commits 1..k tokens; commit_spec rolls rejected-draft
                # pages back into the pool.
                widths = [sched.spec_width(s, self.spec_k) for s in dslots]
                results = run_round(dslots, widths, proposer,
                                    self.run_spec_tick)
                decode_ticks += 1
                now = time_fn() - t0
                spec_rec = []
                for s, w, j, toks_out in results:
                    sched.commit_spec(s, j)
                    for t in toks_out:
                        self._emit(s, t, now)
                    emitted_decode += j
                    spec_rec.append([s.req.rid, w - 1, j - 1])
                    spec_rounds += 1
                    spec_proposed += w - 1
                    spec_accepted += j - 1
                    if registry is not None:
                        registry.observe("serve.spec.accepted", j - 1)
                    if s.req.done and isinstance(sched, ContinuousScheduler):
                        sched.finish(s, now)
                progressed = True
            elif dslots:
                nxt = self.run_decode_tick(dslots)
                decode_ticks += 1
                now = time_fn() - t0
                for s in dslots:
                    s.cached += 1
                    self._emit(s, int(nxt[s.idx]), now)
                    if s.req.done and isinstance(sched, ContinuousScheduler):
                        sched.finish(s, now)
                emitted_decode = len(dslots)
                progressed = True

            if isinstance(sched, StaticScheduler) and sched.batch_done():
                sched.drain(time_fn() - t0)
                progressed = True

            # Watchdog window closes HERE: the idle branch below sleeps
            # on purpose (waiting for the next arrival / a squeeze to
            # lift), and counting that wait would turn every sparse
            # workload into a stream of false slow-tick alarms.
            busy_s = time_fn() - iter_t0

            if not progressed and sched.unfinished:
                nxt_arrival = sched.next_arrival()
                now = time_fn() - t0
                if squeezes:
                    # An injected squeeze holds the pages the next step
                    # needs (admission or decode growth): idle one tick
                    # until the squeeze lifts.
                    sleep_fn(0.001)
                elif nxt_arrival is None:
                    raise RuntimeError("scheduler stalled with no queue")
                elif nxt_arrival <= now:
                    raise RuntimeError(
                        f"request {sched.queue[0].rid} cannot be "
                        f"admitted into an idle engine — page pool "
                        f"({self.num_pages} pages of {self.page_size})"
                        " too small"
                    )
                else:
                    sleep_fn(min(nxt_arrival - now, 0.05))
            if watchdog_s > 0 and busy_s > watchdog_s:
                watchdog_slow += 1
                if registry is not None:
                    registry.inc("serve.watchdog_slow_ticks")
                events.append({
                    "kind": "watchdog_slow_tick", "tick": tick_idx,
                    "mode": mode, "seconds": round(busy_s, 4),
                })
            # The tick record (obs `tick` event shape): this iteration's
            # scheduling moments + end-of-iteration gauges. Terminal
            # requests are the new tails of the append-only finished/
            # dropped lists since last iteration. Built only when a
            # telemetry consumer asked for it — the slot/queue scans are
            # the cost the docstring promises a bare run never pays; the
            # record itself is streamed, never retained (the JSONL sink
            # is the tick store — an in-memory list would grow without
            # bound on a long-lived serve).
            # (victim, beneficiary) pairs: the rid list keeps the
            # pre-ISSUE-11 tick shape, the pairs are the causal edges.
            preempted_pairs = sched.drain_preempted()
            preempted = [v for v, _ in preempted_pairs]
            blocked = sched.drain_blocked()
            prefix_tick = pcache.drain_tick() if pcache is not None else None
            # Flight recorder (ISSUE 15): the end-of-iteration state
            # digest, stamped on the tick record and chained into the
            # summary's state_crc — computed on EVERY run (bare runs
            # included: the chain is what the determinism gates pin on
            # summary-only storms). O(slots) per tick.
            if draft_paged:
                # Paged-draft pool state rides the digest (ISSUE 17):
                # free draft pages + slots carrying lazy draft state —
                # `mctpu replay` re-derives both from the spec round
                # records (the pages_for page law).
                spec_extra = (1, self.spec_k, 1,
                              proposer.pool.free_pages, proposer.tracked)
            state_crc = scheduler_digest(sched, extra=spec_extra)
            state_chain = zlib.crc32(state_crc.to_bytes(4, "little"),
                                     state_chain)
            if not want_ticks:
                sched.check()
                tick_idx += 1
                continue
            new_fin = sched.finished[n_fin_seen:]
            new_drop = sched.dropped[n_drop_seen:]
            n_fin_seen, n_drop_seen = len(sched.finished), len(sched.dropped)
            now = time_fn() - t0
            arrived_now = []
            while arr_cursor < len(arrivals) and \
                    arrivals[arr_cursor][0] <= now:
                arrived_now.append(arrivals[arr_cursor][1])
                arr_cursor += 1
            arrived_waiting = sum(1 for r in sched.queue if r.arrival <= now)
            running = sum(1 for s in sched.slots if not s.free)
            prefilling = sum(1 for s in sched.slots
                             if s.prefilling and not s.req.terminal)
            backlog = sched.prefill_backlog()
            tick_rec = {
                "tick": tick_idx, "now": round(now, 4), "mode": mode,
                "queue": arrived_waiting, "running": running,
                "prefilling": prefilling,
                "free_pages": sched.pool.free_pages, "backlog": backlog,
                "arrived": arrived_now,
                "admitted": admitted, "prefill": prefill_rec,
                "decoded": decoded,
                "finished": [r.rid for r in new_fin],
                "aborted": [[r.rid, r.status] for r in new_drop],
                "preempted": preempted,
                # Causality (ISSUE 11): blocked admission attempts
                # ([rid, reason, holders]) and preemption beneficiaries
                # ([victim, for_rid]) — the blocker edges of the blame
                # DAG `mctpu explain` reconstructs.
                "blocked": [[rid, reason, holders]
                            for rid, reason, holders in blocked],
                "preempted_for": [[v, b] for v, b in preempted_pairs
                                  if b is not None],
                # Terminal detail (ISSUE 8): tenant + latency per request
                # reaching a terminal status THIS tick — the streaming
                # good/bad events the SLO burn-rate rules fold, emitted
                # when they happen instead of at end of run.
                "terminal": [terminal_fields(r) for r in new_fin + new_drop],
                # Flight recorder (ISSUE 15): crc32 of the canonical
                # host-side state after this iteration — `mctpu replay`
                # recomputes it from the events above at every tick.
                "state_crc": state_crc,
            }
            if squeezes:
                # Pages an injected squeeze currently holds: the replay
                # reconstruction needs it to account the pool's free
                # count (squeeze allocations have no scheduling event).
                tick_rec["squeezed"] = sum(len(sq["pages"])
                                           for sq in squeezes)
            if spec_rec is not None:
                # Speculative round detail (ISSUE 14): [rid, proposed,
                # accepted] per slot — `mctpu trace` derives the round's
                # emitted count (1 + accepted) from it, so the token
                # cross-check survives variable-length commits.
                tick_rec["spec"] = spec_rec
            if prefix_tick is not None:
                # Prefix-cache panel fields (ISSUE 9): this tick's hit
                # markers ([rid, matched_tokens] — the lifecycle event
                # `mctpu trace` renders) + cumulative stats and
                # residency gauges for the `mctpu top` cache panel.
                tick_rec["prefix_hits"] = prefix_tick["hits"]
                tick_rec["prefix"] = {
                    "shared_pages": pcache.shared_pages,
                    "retained_pages": pcache.retained_pages(),
                    **pcache.stats,
                }
                if tier is not None:
                    # Host-tier panel fields (ISSUE 17): cumulative
                    # spill/readmit/refusal/host-eviction counters +
                    # occupancy in the prefix block (the `mctpu top`
                    # cache panel / replay mirror source), plus this
                    # tick's readmit lifecycle markers ([rid, tokens] —
                    # the `mctpu trace` event).
                    tick_rec["prefix"].update(tier.stats)
                    tick_rec["prefix"]["host_used"] = tier.host_used
                    tick_rec["prefix_readmits"] = prefix_tick["readmits"]
            if tick_sink is not None:
                tick_sink(tick_rec)
            if registry is not None:
                registry.set("serve.queue_depth", arrived_waiting)
                registry.set("serve.running_slots", running)
                registry.set("serve.prefilling_slots", prefilling)
                registry.set("serve.free_pages", sched.pool.free_pages)
                registry.set("serve.prefill_backlog", backlog)
                if decoded:
                    registry.inc("serve.decode_ticks")
                if prefill_rec is not None:
                    registry.inc("serve.prefill_chunks")
                emitted = emitted_decode + (1 if prefill_rec is not None
                                            and prefill_rec[-1] == "emit"
                                            else 0)
                if emitted:
                    registry.inc("serve.tokens_emitted", emitted)
                if spec_rec:
                    registry.inc("serve.spec.rounds", len(spec_rec))
                    registry.inc("serve.spec.proposed",
                                 sum(p for _, p, _ in spec_rec))
                    registry.inc("serve.spec.accepted_total",
                                 sum(a for _, _, a in spec_rec))
                if preempted:
                    registry.inc("serve.preemptions", len(preempted))
                if prefix_tick is not None:
                    if prefix_tick["hits"]:
                        registry.inc("serve.prefix.hits",
                                     len(prefix_tick["hits"]))
                        registry.inc("serve.prefix.hit_tokens",
                                     sum(m for _, m in prefix_tick["hits"]))
                    for key in ("cow", "evictions", "inserts"):
                        if prefix_tick[key]:
                            registry.inc(f"serve.prefix.{key}",
                                         prefix_tick[key])
                    registry.set("serve.prefix.shared_pages",
                                 pcache.shared_pages)
                    registry.set("serve.prefix.retained_pages",
                                 pcache.retained_pages())
                    if tier is not None:
                        # Cumulative counters are SET, not inc'd: the
                        # tier already accumulates; gauges mirror it.
                        for key, val in tier.stats.items():
                            registry.set(f"serve.tier.{key}", val)
                        registry.set("serve.tier.host_used",
                                     tier.host_used)
                for r in new_fin + new_drop:
                    _observe_request(registry, r)
            sched.check()
            tick_idx += 1

        # Release any squeeze that outlived the workload, evict every
        # retained prefix page (no slot holds a reference once all
        # requests are terminal), then prove the pool clean: zero
        # leaked, zero double-booked pages — with or without faults.
        for sq in squeezes:
            if sq["pages"]:
                sched.pool.free(sq["pages"], sq["owner"])
        prefix_fields = empty_prefix_fields()
        if pcache is not None:
            prefix_fields = pcache.summary_fields()
            pcache.clear()
            # clear() evicts; freeze the counters at pre-flush values
            # (end-of-run teardown is not cache pressure — and it never
            # SPILLS: a run-end spill burst would land after the last
            # tick's digest, leaving tier counters no record covers).
        if draft_paged:
            # Release the draft pool and prove it clean — the draft's
            # twin of the main-pool leak check below.
            proposer.end_run()
        sched.check()
        terminal = sched.finished + sched.dropped
        if len(terminal) != n_reqs:
            raise RuntimeError(
                f"run lost requests: {len(terminal)} of {n_reqs} reached "
                "a terminal status"
            )
        assert sched.pool.free_pages == sched.pool.usable, "pages leaked"
        return ServeResult(
            mode=mode, requests=terminal, decode_ticks=decode_ticks,
            prefill_chunks=prefill_chunks, preemptions=sched.preemptions,
            duration_s=time_fn() - t0, events=events,
            watchdog_slow_ticks=watchdog_slow, prefix=prefix_fields,
            spec={"spec_rounds": spec_rounds, "spec_proposed": spec_proposed,
                  "spec_accepted": spec_accepted},
            state_crc=state_chain,
        )
