"""Per-request trace timelines: `mctpu trace RUN [--request ID]`.

The serving engine's tick records (obs `tick` events — one per
scheduler iteration, carrying that iteration's admissions, prefill
chunk, decode set, preemptions, and terminal requests) plus the
per-request `request` records are a complete account of a run. This
module reconstructs each request's lifecycle from them:

    submit -> queued -> admit -> prefill chunks -> first token ->
    decode ticks -> (preempt -> requeue -> readmit -> re-prefill)* ->
    terminal status

and renders two views:

- a per-slot tick Gantt (which slot did what on every engine
  iteration: P = prefill chunk, D = decode, . = idle) — the schedule
  itself, visible;
- a per-request latency breakdown (queued vs prefilling vs decoding vs
  preempted-waiting milliseconds), the answer to "why was THIS request
  slow".

Reconstruction is also a cross-check: the lifecycle derived purely
from tick events must land every request in the same terminal status
its `request` record claims, and its emitted-token account (one per
completed prefill + one per decode tick) must match `output_tokens`.
`trace_main` exits nonzero when any lifecycle is inconsistent — drift
between the engine and its telemetry fails loudly, in CI.

Times are approximate to one tick (a tick record's "now" is stamped at
iteration end); the breakdown sums segment durations between those
stamps.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys

from .schema import fmt_cell as _fmt
from .schema import iter_runs


@dataclasses.dataclass
class Lifecycle:
    """One request's reconstructed history within one mode's run."""

    rid: int
    mode: str
    record: dict | None = None      # its `request` record, when present
    # (tick index, now, kind, detail) in tick order; kinds: admitted,
    # prefill, first_token, decode, preempted, finished, aborted.
    events: list[tuple] = dataclasses.field(default_factory=list)
    admissions: int = 0
    prefill_chunks: int = 0
    decode_ticks: int = 0
    preemptions: int = 0
    handoffs: int = 0
    prefix_hits: int = 0
    prefix_hit_tokens: int = 0
    # Speculative decoding (ISSUE 14): rounds this request ran and
    # draft tokens its target accepted — a spec round's decode event
    # carries [slot, emitted] detail instead of the bare slot, which is
    # what keeps tokens_accounted exact under variable-length commits.
    spec_rounds: int = 0
    spec_accepted: int = 0
    # Host-tier readmissions (ISSUE 17): admissions whose device-tree
    # miss was served from the spilled host tier — the prefix_hit's
    # sibling marker (a readmitted chunk counts as a hit at bind, so
    # the hit marker still fires; this one says WHERE the pages came
    # from).
    tier_readmits: int = 0
    tier_readmit_tokens: int = 0
    derived_status: str | None = None
    terminal_now: float | None = None
    # Milliseconds spent per state, summed across segments.
    breakdown: dict = dataclasses.field(default_factory=dict)

    @property
    def tokens_accounted(self) -> int:
        """Tokens the tick trail accounts for: one at each completed
        prefill (the engine emits the first token at prefill
        completion, per readmission) + one per decode tick — except a
        SPECULATIVE decode round (ISSUE 14), whose [slot, emitted]
        detail carries the round's variable-length commit (1..k
        tokens). A fleet re-dispatch under the "discard" policy throws
        the dead replica's partial output away — the trail records the
        fact (a `redispatched` event with detail "discard", ordered
        BEFORE the new replica's first emission), so the account
        resets with it. Under "resume" the committed tokens carry over
        and the count just keeps accumulating across replicas."""
        n = 0
        for e in self.events:
            if e[2] == "first_token":
                n += 1
            elif e[2] == "decode":
                n += e[3][1] if isinstance(e[3], list) else 1
            elif e[2] == "redispatched":
                d = e[3]
                if isinstance(d, list):
                    # [policy, outlen] (ISSUE 20 trail): reset to the
                    # authoritative committed count — discard throws
                    # everything away; resume replays from outlen, and
                    # any tokens the trail emitted past it were lost
                    # undelivered commits the new replica re-emits.
                    n = 0 if d[0] == "discard" else d[1]
                elif d == "discard":
                    n = 0
        return n

    @property
    def consistent(self) -> bool:
        """The reconstruction agrees with the request record: same
        terminal status, and (for requests that produced tokens) the
        tick-derived token count matches output_tokens."""
        if self.record is None:
            return False
        if self.derived_status != self.record.get("status", "finished"):
            return False
        return self.tokens_accounted == self.record.get("output_tokens", 0)

    def arrival_s(self) -> float | None:
        return self.record.get("arrival_s") if self.record else None


def reconstruct(records: list[dict]) -> dict[str, dict[int, Lifecycle]]:
    """Lifecycles per mode per rid from one run's records.

    Reads `tick` events (the per-iteration trail) and `request` events
    (the terminal claims being cross-checked). A file with request
    records but no tick records (pre-ISSUE-6) yields lifecycles with
    record-only data and consistent=False — trace needs the trail.
    """
    out: dict[str, dict[int, Lifecycle]] = {}

    def life(mode: str, rid: int) -> Lifecycle:
        per = out.setdefault(mode, {})
        lc = per.get(rid)
        if lc is None:
            lc = per[rid] = Lifecycle(rid=rid, mode=mode)
        return lc

    for rec in records:
        ev = rec.get("event")
        if ev == "request":
            life(rec.get("mode", "?"), rec["id"]).record = rec
        elif ev == "fleet":
            # Router tick (ISSUE 7): a re-dispatch moves the request to
            # another replica. The marker lands between the old
            # replica's last record and the new one's first (the fleet
            # emits it before stepping replicas), so the lifecycle
            # stays ordered across the failover.
            tick, now = rec.get("tick"), rec.get("now")
            # redispatched_to (ISSUE 15) carries the authoritative
            # committed-token count at failover — under the lossy bus
            # (ISSUE 20) that can be SMALLER than the tokens the dead
            # replica's trail emitted (undelivered commits are lost and
            # re-emitted), so the token account resets to it.
            outls = {rid: outl
                     for rid, _n, outl in rec.get("redispatched_to") or []}
            for rid in rec.get("redispatched") or []:
                lc = life("fleet", rid)
                policy = rec.get("redispatch", "resume")
                lc.events.append((tick, now, "redispatched",
                                  [policy, outls.get(rid, 0)]
                                  if rid in outls else policy))
            # Lossy-transport lifecycle markers (ISSUE 20): a
            # retransmitted dispatch/commit/terminal for the rid, and a
            # commit the replica refused past its lease — display rows
            # that explain a wire gap in the surrounding segments.
            for kind, _dst, rid in rec.get("t_retransmits") or []:
                if rid >= 0:
                    life("fleet", rid).events.append(
                        (tick, now, "retransmit", kind))
            for rid, name in rec.get("lease_refused") or []:
                life("fleet", rid).events.append(
                    (tick, now, "lease_refused", name))
            # Cache-aware routing marker (ISSUE 18): the router placed
            # rid on `name` expecting `matched` hot prefix tokens —
            # ordered before the replica's first emission for the rid
            # (the fleet emits its record before stepping replicas),
            # so the marker explains the prefix_hit that follows.
            for rid, name, matched in rec.get("route_hits") or []:
                life("fleet", rid).events.append(
                    (tick, now, "routed", [name, matched]))
            # Disaggregated handoff markers (ISSUE 13): the fleet emits
            # its record before stepping replicas, so the phase
            # transition (handoff/handoff_done) is ordered BEFORE the
            # decode pool's first emission for the rid.
            for rid, src in rec.get("handoff_started") or []:
                lc = life("fleet", rid)
                lc.handoffs += 1
                lc.events.append((tick, now, "handoff", src))
            for rid, dst in rec.get("handoff_done") or []:
                life("fleet", rid).events.append(
                    (tick, now, "handoff_done", dst))
            for rid, why in rec.get("handoff_aborted") or []:
                life("fleet", rid).events.append(
                    (tick, now, "handoff_aborted", why))
        elif ev == "tick":
            mode = rec.get("mode", "?")
            if mode.startswith("fleet/"):
                # Per-replica trail of one fleet: all replicas fold
                # into the ONE logical mode "fleet" — a request's
                # lifecycle spans every replica that ever held it.
                mode = "fleet"
            tick, now = rec.get("tick"), rec.get("now")
            for slot, rid in rec.get("admitted") or []:
                lc = life(mode, rid)
                lc.admissions += 1
                lc.events.append((tick, now, "admitted", slot))
            for rid, matched in rec.get("prefix_hits") or []:
                # Prefix-cache hit (ISSUE 9): this admission shared
                # `matched` prompt tokens' pages and prefilled only the
                # suffix — the marker that explains a short prefill
                # segment in the breakdown.
                lc = life(mode, rid)
                lc.prefix_hits += 1
                lc.prefix_hit_tokens += matched
                lc.events.append((tick, now, "prefix_hit", matched))
            for rid, depth in rec.get("prefix_readmits") or []:
                # Host-tier readmission (ISSUE 17): the chunk ending at
                # `depth` prompt tokens came back from the spilled host
                # tier instead of re-prefilling — the marker that
                # explains a device-tree miss that still prefilled only
                # the suffix.
                lc = life(mode, rid)
                lc.tier_readmits += 1
                lc.tier_readmit_tokens = max(lc.tier_readmit_tokens,
                                             depth)
                lc.events.append((tick, now, "tier_readmit", depth))
            pf = rec.get("prefill")
            if pf:
                lc = life(mode, pf[1])
                lc.prefill_chunks += 1
                lc.events.append((tick, now, "prefill", pf[2]))
                if pf[-1] == "emit":
                    lc.events.append((tick, now, "first_token", None))
            # Speculative rounds (ISSUE 14): [rid, proposed, accepted]
            # per slot — the decode event's detail becomes
            # [slot, emitted] (= 1 + accepted) so the token account
            # stays exact, and the round itself is the trace's
            # spec-round marker.
            spec_acc = {e[0]: e[2] for e in rec.get("spec") or []}
            for slot, rid in rec.get("decoded") or []:
                lc = life(mode, rid)
                lc.decode_ticks += 1
                if rid in spec_acc:
                    lc.spec_rounds += 1
                    lc.spec_accepted += spec_acc[rid]
                    lc.events.append((tick, now, "decode",
                                      [slot, 1 + spec_acc[rid]]))
                else:
                    lc.events.append((tick, now, "decode", slot))
            for rid in rec.get("preempted") or []:
                lc = life(mode, rid)
                lc.preemptions += 1
                lc.events.append((tick, now, "preempted", None))
            for rid in rec.get("finished") or []:
                lc = life(mode, rid)
                lc.derived_status = "finished"
                lc.terminal_now = now
                lc.events.append((tick, now, "finished", None))
            for rid, status in rec.get("aborted") or []:
                lc = life(mode, rid)
                lc.derived_status = status
                lc.terminal_now = now
                lc.events.append((tick, now, "aborted", status))

    for per in out.values():
        for lc in per.values():
            _compute_breakdown(lc)
    return out


def _compute_breakdown(lc: Lifecycle) -> None:
    """Attribute the request's wall-clock to states by walking its
    events: queued (arrival -> first admit), prefilling (admit ->
    first token / last chunk), decoding, preempted-waiting (preempt ->
    readmit). Milliseconds, rounded; None arrival -> empty breakdown."""
    arrival = lc.arrival_s()
    if arrival is None or lc.terminal_now is None:
        return
    acc = {"queued_ms": 0.0, "prefill_ms": 0.0, "decode_ms": 0.0,
           "preempted_ms": 0.0, "handoff_ms": 0.0}
    state, since = "queued", arrival
    state_key = {"queued": "queued_ms", "prefill": "prefill_ms",
                 "decode": "decode_ms", "preempted": "preempted_ms",
                 "handoff": "handoff_ms"}
    for _tick, now, kind, _detail in lc.events:
        if kind == "admitted":
            acc[state_key[state]] += now - since
            state, since = "prefill", now
        elif kind == "first_token":
            acc[state_key[state]] += now - since
            state, since = "decode", now
        elif kind in ("preempted", "redispatched", "handoff_aborted"):
            # Crash failover is accounted like a preemption wait: the
            # request holds no slot between losing a replica and
            # readmission elsewhere. An aborted handoff enters the
            # same wait (its re-dispatch re-prefills).
            acc[state_key[state]] += now - since
            state, since = "preempted", now
        elif kind == "handoff":
            # Disaggregated phase transition (ISSUE 13): sealed in
            # flight between the pools.
            acc[state_key[state]] += now - since
            state, since = "handoff", now
        elif kind == "handoff_done":
            acc[state_key[state]] += now - since
            state, since = "decode", now
        elif kind in ("finished", "aborted"):
            acc[state_key[state]] += now - since
            since = now
    lc.breakdown = {k: round(1e3 * v, 3) for k, v in acc.items()}


# -- rendering ---------------------------------------------------------


def render_gantt(records: list[dict], mode: str, *, width: int = 96,
                 rid: int | None = None) -> str:
    """Per-slot tick Gantt for one mode: one row per engine slot, one
    column per tick (bucketed down to `width` columns for long runs).
    P = prefill chunk, D = decode, both = '#', idle = '.'. With `rid`,
    only that request's activity is drawn (its queue time shows as
    'q', preempted-waiting as 'x', on the row of the slot it next
    occupies). Mode "fleet" draws every replica's trail (tick modes
    "fleet/<name>") as replica-qualified rows ("r0:2" = replica r0,
    slot 2) — a re-dispatched request's activity visibly jumps rows at
    the failover."""
    ticks = [r for r in records if r.get("event") == "tick"
             and (r.get("mode", "?") == mode
                  or r.get("mode", "?").startswith(mode + "/"))]
    if not ticks:
        return "(no tick records)"
    n_ticks = max(t["tick"] for t in ticks) + 1

    def rkey(t: dict, slot: int) -> tuple[str, int]:
        # ("", slot) for the exact mode; ("r0", slot) for "fleet/r0".
        return (t.get("mode", "?")[len(mode) + 1:], slot)

    keys: set[tuple[str, int]] = set()
    for t in ticks:
        for s, _ in (t.get("admitted") or []):
            keys.add(rkey(t, s))
        for s, _ in (t.get("decoded") or []):
            keys.add(rkey(t, s))
        if t.get("prefill"):
            keys.add(rkey(t, t["prefill"][0]))
    if not keys:
        keys = {("", 0)}
    rows = sorted(keys)
    row_of = {k: i for i, k in enumerate(rows)}
    per_col = max(1, -(-n_ticks // width))  # ceil: ticks per column
    cols = -(-n_ticks // per_col)
    # grid[row][col] accumulates flags: 1 = prefill, 2 = decode.
    grid = [[0] * cols for _ in rows]
    for t in ticks:
        col = t["tick"] // per_col
        pf = t.get("prefill")
        if pf and (rid is None or pf[1] == rid):
            grid[row_of[rkey(t, pf[0])]][col] |= 1
        for s, r in (t.get("decoded") or []):
            if rid is None or r == rid:
                grid[row_of[rkey(t, s)]][col] |= 2
    if rid is not None:
        # Waiting intervals for the focused request, drawn on the row of
        # the slot it lands on NEXT: arrival -> first admission is queue
        # time (flag 4, 'q'), preemption -> readmission is preempted-
        # waiting (flag 8, 'x'). Activity flags win inside a bucketed
        # column; 'x' outranks 'q' (a requeue is the rarer signal).
        admits = [(t["tick"], row_of[rkey(t, s)]) for t in ticks
                  for s, r in (t.get("admitted") or []) if r == rid]
        req = next((r for r in records if r.get("event") == "request"
                    and r.get("id") == rid
                    and r.get("mode", "?") == mode), None)
        waits = []  # (start_tick, end_tick_exclusive, flag)
        if admits and req and req.get("arrival_s") is not None:
            arrive = next((t["tick"] for t in ticks
                           if t["now"] >= req["arrival_s"]), admits[0][0])
            waits.append((arrive, admits[0][0], 4))
        preempt_ticks = [t["tick"] for t in ticks
                         if rid in (t.get("preempted") or [])]
        for pt in preempt_ticks:
            readmit = next((a for a, _ in admits if a > pt), n_ticks)
            waits.append((pt, readmit, 8))
        for start, end, flag in waits:
            row = next((r for a, r in admits if a >= end),
                       admits[-1][1] if admits else 0)
            for tick in range(start, end):
                grid[row][tick // per_col] |= flag
    chars = {0: ".", 4: "q", 8: "x", 12: "x"}

    def cell(c: int) -> str:
        # Activity (P/D/#) beats waiting flags within a bucket.
        return {1: "P", 2: "D", 3: "#"}[c & 3] if c & 3 else chars[c]
    lines = [f"ticks 0..{n_ticks - 1}"
             + (f" ({per_col} ticks/column)" if per_col > 1 else "")
             + f" — mode {mode}"
             + (f", request {rid}" if rid is not None else "")]
    for (sub, s), row in zip(rows, grid):
        label = f"{sub}:{s}" if sub else f"slot {s:>2}"
        lines.append(f"{label:>7} |" + "".join(cell(c) for c in row))
    return "\n".join(lines)


def render_request_table(lifecycles: dict[int, Lifecycle]) -> str:
    lines = [
        "| rid | status | tenant | arrival s | queued ms | prefill ms "
        "| decode ms "
        "| preempt wait ms | handoff ms | preempts | chunks | dticks "
        "| pfx tok "
        "| tokens | ok |",
        "|---|---|---|---|---|---|---|---|---|---|---|---|---|---|---|",
    ]
    for rid in sorted(lifecycles):
        lc = lifecycles[rid]
        b = lc.breakdown
        rec = lc.record or {}
        lines.append(
            f"| {rid} | {_fmt(lc.derived_status)} "
            f"| {rec.get('tenant', 'default')} | {_fmt(lc.arrival_s())} "
            f"| {_fmt(b.get('queued_ms'))} | {_fmt(b.get('prefill_ms'))} "
            f"| {_fmt(b.get('decode_ms'))} | {_fmt(b.get('preempted_ms'))} "
            f"| {_fmt(b.get('handoff_ms'))} "
            f"| {lc.preemptions} | {lc.prefill_chunks} | {lc.decode_ticks} "
            f"| {lc.prefix_hit_tokens} "
            f"| {lc.tokens_accounted}/{_fmt(rec.get('output_tokens'))} "
            f"| {'yes' if lc.consistent else 'NO'} |"
        )
    return "\n".join(lines)


def render_request_detail(lc: Lifecycle) -> str:
    rec = lc.record or {}
    head = [
        f"request {lc.rid} [{lc.mode}] — status {_fmt(lc.derived_status)} "
        f"(record: {_fmt(rec.get('status'))}), "
        f"prompt {_fmt(rec.get('prompt_tokens'))} tokens, "
        f"out {_fmt(rec.get('output_tokens'))} tokens, "
        f"ttft {_fmt(rec.get('ttft_ms'))} ms, "
        f"latency {_fmt(rec.get('latency_ms'))} ms",
        "breakdown: " + ", ".join(f"{k}={_fmt(v)}"
                                  for k, v in lc.breakdown.items()),
        f"arrival t={_fmt(lc.arrival_s())} s; lifecycle:",
    ]
    body = [
        f"  tick {tick:>5} t={now:.4f}s  {kind}"
        + (f" ({detail})" if detail is not None else "")
        for tick, now, kind, detail in lc.events
    ]
    return "\n".join(head + body)


def trace_main(argv: list[str] | None = None) -> int:
    """`mctpu trace RUN [--request ID]` — lifecycle reconstruction.

    Exits 1 when any reconstructed lifecycle disagrees with its
    request record (missing tick trail counts as disagreement): the
    engine and its telemetry drifting apart is a failure, not a
    rendering choice.
    """
    ap = argparse.ArgumentParser(
        prog="mctpu trace",
        description="Reconstruct per-request lifecycles from a serving "
                    "run's metrics JSONL: per-slot tick Gantt + latency "
                    "breakdown (queued/prefill/decode/preempted).",
    )
    ap.add_argument("path", help="metrics JSONL with tick + request records")
    ap.add_argument("--request", type=int, default=None,
                    help="detail one request id instead of the summary")
    ap.add_argument("--slowest", type=int, default=None,
                    help="show only the N slowest requests, keyed on "
                         "recorded latency_ms (ttft_ms for requests "
                         "that never finished) — the same worst-k "
                         "selector `mctpu explain --worst` uses, with "
                         "latency as the key (explain --worst ttft/"
                         "tpot keys on those metrics) (ISSUE 11)")
    ap.add_argument("--mode", default=None,
                    help="restrict to one scheduler mode "
                         "(default: every mode in the file)")
    ap.add_argument("--tenant", default=None,
                    help="restrict the request table and consistency "
                         "check to one tenant's requests (ISSUE 8; "
                         "untagged requests are tenant 'default'; the "
                         "Gantt still draws the whole schedule — slots "
                         "are shared)")
    ap.add_argument("--width", type=int, default=96,
                    help="Gantt width in columns (ticks are bucketed)")
    ap.add_argument("--format", choices=("md", "json"), default="md")
    args = ap.parse_args(argv)

    try:
        runs = [r for r in iter_runs(args.path) if r]
    except (OSError, ValueError) as e:
        print(f"error: {args.path}: {e}", file=sys.stderr)
        return 2
    rc = 0
    for i, records in enumerate(runs, 1):
        by_mode = reconstruct(records)
        if args.mode is not None:
            by_mode = {m: v for m, v in by_mode.items() if m == args.mode}
        if not by_mode:
            continue
        label = args.path if len(runs) == 1 \
            else f"{args.path} (run {i}/{len(runs)})"
        for mode, lifecycles in sorted(by_mode.items()):
            if args.tenant is not None:
                lifecycles = {
                    rid: lc for rid, lc in lifecycles.items()
                    if (lc.record or {}).get("tenant", "default")
                    == args.tenant
                }
                if not lifecycles:
                    continue
            bad = [rid for rid, lc in lifecycles.items() if not lc.consistent]
            if args.slowest is not None and args.request is None:
                # Worst-k drill-down (ISSUE 11 satellite): the shared
                # selector, keyed on the request record's latency (ttft
                # as the fallback for aborted requests that emitted but
                # never finished). The consistency check above already
                # ran over EVERY lifecycle — drift is never hidden by
                # the display filter.
                from .causal import worst_k

                def _lat(lc):
                    rec = lc.record or {}
                    if rec.get("latency_ms") is not None:
                        return rec["latency_ms"]
                    return rec.get("ttft_ms")  # FakeClock latencies can be 0

                keep = worst_k(list(lifecycles.values()), _lat,
                               args.slowest)
                lifecycles = {lc.rid: lc for lc in keep}
                if not lifecycles:
                    continue
            if args.format == "json":
                print(json.dumps({
                    "path": args.path, "run": i, "mode": mode,
                    "requests": len(lifecycles),
                    "inconsistent": sorted(bad),
                    "statuses": _status_counts(lifecycles),
                    "lifecycles": {
                        str(rid): {
                            "status": lc.derived_status,
                            "breakdown": lc.breakdown,
                            "preemptions": lc.preemptions,
                            "handoffs": lc.handoffs,
                            "prefill_chunks": lc.prefill_chunks,
                            "decode_ticks": lc.decode_ticks,
                            "prefix_hits": lc.prefix_hits,
                            "prefix_hit_tokens": lc.prefix_hit_tokens,
                            "tier_readmits": lc.tier_readmits,
                            "spec_rounds": lc.spec_rounds,
                            "spec_accepted": lc.spec_accepted,
                            "tokens": lc.tokens_accounted,
                            "consistent": lc.consistent,
                        }
                        for rid, lc in sorted(lifecycles.items())
                    },
                }))
            elif args.request is not None:
                lc = lifecycles.get(args.request)
                if lc is None:
                    print(f"error: no request {args.request} in mode "
                          f"{mode} of {label}", file=sys.stderr)
                    rc = max(rc, 2)
                    continue
                print(f"## Trace — {label}\n")
                print(render_request_detail(lc))
                print()
                print(render_gantt(records, mode, width=args.width,
                                   rid=args.request))
                print()
            else:
                print(f"## Trace — {label} [{mode}]\n")
                print(render_gantt(records, mode, width=args.width))
                print()
                print(render_request_table(lifecycles))
                print()
            if bad:
                print(f"error: {len(bad)} request(s) with inconsistent "
                      f"lifecycles in mode {mode}: {sorted(bad)[:10]}",
                      file=sys.stderr)
                rc = max(rc, 1)
    return rc


def _status_counts(lifecycles: dict[int, Lifecycle]) -> dict[str, int]:
    counts: dict[str, int] = {}
    for lc in lifecycles.values():
        st = lc.derived_status or "unknown"
        counts[st] = counts.get(st, 0) + 1
    return counts


if __name__ == "__main__":
    sys.exit(trace_main())
