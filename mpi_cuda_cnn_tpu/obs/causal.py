"""Causal critical-path attribution: `mctpu explain` (ISSUE 11).

`mctpu trace` says WHAT happened to a request and `mctpu health`
whether the run met target; this module says WHY a request was slow.
It is the Dapper -> Mystery Machine step (Sigelman et al. 2010; Chow
et al., OSDI 2014) applied to the repo's already-deterministic tick
trail: the serving producers now record the causality they used to
discard — which rids held the slots/pages a blocked admission queued
behind (`blocked` tick entries), which decoding request a preemption
victimized FOR (`preempted_for`), which failover stranded a request
(`failed_over` fleet entries), and when every request's arrival fell
due (`arrived`) — and this module folds that trail into a per-request
causal account whose critical path is blamed category by category:

- self_compute:       the request held a slot and was progressing
                      (prefill chunks + decode ticks + scheduling gaps
                      while resident);
- queued_behind:      waiting for admission behind named holder rids
                      (capacity: their release is what unblocked it) —
                      SLOScheduler quota skip-overs are recorded as
                      their own edge kind ("quota": the request waits
                      on its OWN tenant's occupancy, not the fleet's);
- preempted_by:       evicted and waiting, blamed on the beneficiary
                      whose page need forced the eviction;
- redispatch_replay:  crash failover — from the moment a dead replica
                      stranded the request until it is again producing
                      NEW tokens (re-dispatch wait + re-prefill of the
                      already-committed context);
- router_wait:        fleet arrival -> first dispatch (no replica
                      would take it yet);
- handoff_wait:       disaggregated prefill->decode KV transfer
                      (ISSUE 13) — sealed on the prefill replica until
                      bound decode-ready on the receiver; an aborted
                      transfer transitions to redispatch_replay at the
                      abort marker.

Attribution is in integer TICKS on the producer's own tick axis, so
the decomposition is exact: for every terminal request the category
ticks sum bitwise to its end-to-end tick span (terminal tick − arrival
tick). `blame_check` verifies that conservation, and `explain_main`
additionally replays `obs.timeline.reconstruct`'s lifecycle cross-check
against the engine's own request records — drift exits 1, the same
discipline as `mctpu trace`. Wall-clock milliseconds ride along for
display only (tick `now` stamps); they are never the conserved unit.

The fold is streaming (one pass, no retained tick records), so the
benches run it live at 10^5-storm scale exactly like the alert engine:
`BlameAccumulator` taps the tick/fleet sinks, and the run summary
gains `blame_crc` + per-category totals the CI determinism gate pins
at exact equality run-vs-run. Deliberately jax-free (`mctpu lint`
MCT001): reads records, folds integers, prints tables.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import zlib

from .schema import fmt_cell as _fmt
from .schema import iter_runs

# Category order is part of the CRC contract — append only.
# handoff_wait (ISSUE 13): disaggregated serving's prefill->decode KV
# transfer — from the sealed detach on the prefill replica to the
# decode-ready bind on the receiver (or to the abort that sends the
# request back through redispatch_replay).
# transport_wait (ISSUE 20): the dispatch message's wire time on the
# lossy bus — router send to replica delivery, retransmits and
# partition block included. Zero with the bus off OR faultless (inline
# delivery lands in the same tick), so pre-ISSUE-20 trails fold to
# bitwise-identical rows.
CATEGORIES = ("self_compute", "queued_behind", "preempted_by",
              "redispatch_replay", "router_wait", "handoff_wait",
              "transport_wait")

# Internal wait states -> blame category.
_STATE_CAT = {"active": "self_compute", "queued": "queued_behind",
              "preempt_wait": "preempted_by", "replay": "redispatch_replay",
              "router": "router_wait", "handoff": "handoff_wait",
              "transport": "transport_wait"}


def worst_k(rows, key, k: int):
    """Top-k rows by `key` descending, None-valued rows excluded — THE
    worst-k selector `mctpu explain --worst` and `mctpu trace
    --slowest` share (ISSUE 11 satellite): one ordering, so the two
    tools drill into the same requests."""
    scored = [(key(r), i, r) for i, r in enumerate(rows)
              if key(r) is not None]
    scored.sort(key=lambda t: (-t[0], t[1]))
    return [r for _, _, r in scored[:k]]


@dataclasses.dataclass
class RequestBlame:
    """One request's finished causal account."""

    rid: int
    mode: str
    status: str | None = None
    tenant: str = "default"
    start_tick: int | None = None
    terminal_tick: int | None = None
    ttft_ms: float | None = None
    tpot_ms: float | None = None
    # Integer ticks per category — sums bitwise to span_ticks.
    cats: dict = dataclasses.field(
        default_factory=lambda: dict.fromkeys(CATEGORIES, 0))
    # Display-only wall-clock per category (tick `now` stamps).
    cats_ms: dict = dataclasses.field(
        default_factory=lambda: dict.fromkeys(CATEGORIES, 0.0))
    # Joint blocker attribution: holder rid -> ticks this request spent
    # queued behind it (a segment blames its whole holder set).
    blockers: dict = dataclasses.field(default_factory=dict)
    # Quota skip-over ticks (the "quota"-reason subset of queued_behind
    # — SLOScheduler policy wait, not capacity wait).
    quota_ticks: int = 0
    # Beneficiary rid -> ticks this request waited after being
    # preempted for it.
    preemptors: dict = dataclasses.field(default_factory=dict)
    # (category, start_tick, end_tick, detail) critical-path segments
    # in time order; detail names blockers/beneficiary/replica.
    edges: list = dataclasses.field(default_factory=list)

    @property
    def terminal(self) -> bool:
        return self.terminal_tick is not None

    @property
    def span_ticks(self) -> int | None:
        if self.start_tick is None or self.terminal_tick is None:
            return None
        return self.terminal_tick - self.start_tick

    @property
    def conserved(self) -> bool:
        """THE invariant: category ticks sum exactly to the span."""
        span = self.span_ticks
        return span is not None and span >= 0 \
            and sum(self.cats.values()) == span

    def to_fields(self) -> dict:
        return {
            "rid": self.rid, "status": self.status, "tenant": self.tenant,
            "start_tick": self.start_tick,
            "terminal_tick": self.terminal_tick,
            "span_ticks": self.span_ticks,
            "categories": dict(self.cats),
            "categories_ms": {k: round(v, 3)
                              for k, v in self.cats_ms.items()},
            "quota_ticks": self.quota_ticks,
            "blockers": {str(k): v for k, v in sorted(self.blockers.items())},
            "preemptors": {str(k): v
                           for k, v in sorted(self.preemptors.items())},
            "conserved": self.conserved,
        }


class _ReqState:
    """Mutable per-request fold state. Kept deliberately lean — at
    storm scale tens of thousands of these are live at once, and every
    GC-tracked container here is heap the collector re-scans on every
    full collection (the measured cost at 10^5 requests, PERF.md):
    two fixed lists for the category accounts, lazy dicts only when a
    blocker/beneficiary actually appears, and edges only when a caller
    asked for detail (`mctpu explain`); terminal requests are folded
    into flat canonical rows and freed."""

    __slots__ = ("state", "since_tick", "since_now", "start_tick",
                 "cats", "cats_ms", "last_blocked", "beneficiary",
                 "replica", "blockers", "preemptors", "quota_ticks",
                 "edges", "status", "tenant", "ttft_ms", "tpot_ms")

    def __init__(self, state: str, tick: int, now: float,
                 detail: bool):
        self.state = state
        self.since_tick = tick
        self.since_now = now
        self.start_tick = tick
        self.cats = [0] * len(CATEGORIES)
        self.cats_ms = [0.0] * len(CATEGORIES)
        self.last_blocked = None   # (reason, holders) newest block note
        self.beneficiary = None    # rid a preemption victimized this for
        self.replica = None        # replica name a failover stranded it on
        self.blockers = None       # holder rid -> ticks (lazy)
        self.preemptors = None     # beneficiary rid -> ticks (lazy)
        self.quota_ticks = 0
        self.edges = [] if detail else None
        self.status = None
        self.tenant = "default"
        self.ttft_ms = None
        self.tpot_ms = None

    def close(self, tick: int, now: float, new_state: str | None) -> None:
        """End the current segment at `tick` and enter `new_state`."""
        cat = _CAT_IDX[_STATE_CAT[self.state]]
        ticks = tick - self.since_tick
        self.cats[cat] += ticks
        self.cats_ms[cat] += 1e3 * (now - self.since_now)
        detail = None
        if ticks != 0 or self.state in ("preempt_wait", "replay"):
            if self.state == "queued" and self.last_blocked is not None:
                reason, holders = self.last_blocked
                detail = (reason, holders)
                if self.blockers is None:
                    self.blockers = {}
                for h in holders:
                    self.blockers[h] = self.blockers.get(h, 0) + ticks
                if reason == "quota":
                    self.quota_ticks += ticks
            elif self.state == "preempt_wait":
                detail = self.beneficiary
                if self.beneficiary is not None:
                    if self.preemptors is None:
                        self.preemptors = {}
                    self.preemptors[self.beneficiary] = \
                        self.preemptors.get(self.beneficiary, 0) + ticks
            elif self.state == "replay":
                detail = self.replica
            if ticks != 0 and self.edges is not None:
                self.edges.append((CATEGORIES[cat], self.since_tick,
                                   tick, detail))
        if new_state is not None:
            self.state = new_state
        self.since_tick = tick
        self.since_now = now

    def to_blame(self, rid: int, mode: str,
                 terminal_tick: int | None) -> RequestBlame:
        return RequestBlame(
            rid=rid, mode=mode, status=self.status, tenant=self.tenant,
            start_tick=self.start_tick, terminal_tick=terminal_tick,
            ttft_ms=self.ttft_ms, tpot_ms=self.tpot_ms,
            cats=dict(zip(CATEGORIES, self.cats)),
            cats_ms=dict(zip(CATEGORIES, self.cats_ms)),
            blockers=dict(self.blockers or {}),
            quota_ticks=self.quota_ticks,
            preemptors=dict(self.preemptors or {}),
            edges=list(self.edges or []),
        )


_CAT_IDX = {c: i for i, c in enumerate(CATEGORIES)}


class BlameAccumulator:
    """Streaming blame fold over tick/fleet records (one pass, nothing
    retained per tick). Feed it schema records via `ingest`, or raw
    sink dicts via `ingest_tick` / `ingest_fleet` — the benches tap
    the live sinks exactly like the alert engine, which is what makes
    blame available on `--log summary` storms whose per-tick records
    never reach the JSONL.

    Memory discipline (the 10^5-storm requirement): an announced-but-
    idle request costs one tuple in `_announce` (no state object until
    its first real event), and a terminal request is folded into one
    flat canonical row (tuples of atoms — the GC untracks them) with
    its `_ReqState` freed, so the tracked live set is bounded by
    requests actually in flight, not by the run's total.

    `detail=True` (the `mctpu explain` path) additionally retains a
    full RequestBlame per terminal request — segment edges included —
    for the blame-tree renderings; the canonical rows and CRC are
    identical either way (live == replay, the alerts_crc discipline).
    """

    def __init__(self, detail: bool = False):
        self.detail = detail
        # mode -> rid -> _ReqState, live (in-flight) requests only;
        # per-replica fleet ticks fold into the ONE logical mode
        # "fleet" (a lifecycle spans replicas).
        self._states: dict[str, dict[int, _ReqState]] = {}
        # mode -> rid -> (announce tick, now): arrival fell due, no
        # event yet. The fold's only trace of a quietly queued request.
        self._announce: dict[str, dict[int, tuple]] = {}
        # mode -> rid -> canonical row (the CRC/aggregate substrate):
        # (rid, status, tenant, start, end, cats tuple, quota ticks,
        #  blockers items, preemptors items, conserved).
        self._rows: dict[str, dict[int, tuple]] = {}
        # mode -> rid -> RequestBlame (detail mode only).
        self._blames: dict[str, dict[int, RequestBlame]] = {}
        self.saw_causal_fields = False
        self.saw_ticks = False

    # -- record ingestion ----------------------------------------------

    def ingest(self, rec: dict) -> None:
        ev = rec.get("event")
        if ev == "tick":
            self.ingest_tick(rec)
        elif ev == "fleet":
            self.ingest_fleet(rec)

    def _st(self, mode: str, rid: int, tick: int, now: float,
            state: str) -> _ReqState:
        """The rid's live state, materialized on first use: anchored at
        its announce moment when one was recorded (initial state is
        router for the fleet, queued for an engine), else defensively
        at the current tick in `state`."""
        per = self._states.setdefault(mode, {})
        st = per.get(rid)
        if st is None:
            ann = self._announce.setdefault(mode, {}).pop(rid, None)
            if ann is not None:
                st = _ReqState("router" if mode == "fleet" else "queued",
                               ann[0], ann[1], self.detail)
            else:
                st = _ReqState(state, tick, now, self.detail)
            per[rid] = st
        return st

    def ingest_fleet(self, rec: dict) -> None:
        tick, now = rec.get("tick"), rec.get("now", 0.0)
        if tick is None:
            return
        if "arrived" in rec:
            self.saw_causal_fields = True
        ann = self._announce.setdefault("fleet", {})
        for rid in rec.get("arrived") or []:
            ann[rid] = (tick, now)
        # Lossy transport (ISSUE 20): with the bus on ("transport" block
        # present), a dispatched/redispatched marker is the router's
        # SEND — the request is on the wire until its t_delivered
        # marker, and those ticks are transport_wait. Inline zero-fault
        # delivery puts both markers in the same record (0-tick
        # segments), so faultless bus trails fold identically to direct.
        bus = "transport" in rec
        for rid in rec.get("dispatched") or []:
            st = self._st("fleet", rid, tick, now, "router")
            if st.state == "router":
                st.close(tick, now, "transport" if bus else "queued")
        for rid, name in rec.get("failed_over") or []:
            st = self._st("fleet", rid, tick, now, "replay")
            if st.state != "replay":
                st.close(tick, now, "replay")
            st.replica = name
        # Disaggregated handoff markers (ISSUE 13), processed BEFORE
        # redispatched: an aborted handoff's re-dispatch can land in
        # the same fleet record, and the replay segment must start at
        # the abort, not absorb the handoff wait.
        for rid, _src in rec.get("handoff_started") or []:
            st = self._st("fleet", rid, tick, now, "handoff")
            if st.state != "handoff":
                st.close(tick, now, "handoff")
        for rid, _dst in rec.get("handoff_done") or []:
            st = self._st("fleet", rid, tick, now, "handoff")
            if st.state == "handoff":
                st.close(tick, now, "active")
        for rid, _why in rec.get("handoff_aborted") or []:
            st = self._st("fleet", rid, tick, now, "handoff")
            if st.state == "handoff":
                st.close(tick, now, "replay")
        for rid in rec.get("redispatched") or []:
            st = self._st("fleet", rid, tick, now, "replay")
            if bus:
                if st.state != "transport":
                    st.close(tick, now, "transport")
            elif st.state != "replay":
                # Defensive: a redispatch always follows a failed_over
                # marker; an out-of-order trail still folds, it just
                # starts the replay here.
                st.close(tick, now, "replay")
        # Wire deliveries LAST: a same-tick send+delivery (the inline
        # zero-fault path) must close its 0-tick transport segment
        # after the send opened it. st.replica (set by failed_over)
        # discriminates a redispatch delivery — the re-prefill ahead is
        # crash-caused work, so it re-enters replay, not queued.
        for rid, _name in rec.get("t_delivered") or []:
            st = self._st("fleet", rid, tick, now, "queued")
            if st.state == "transport":
                st.close(tick, now,
                         "replay" if st.replica is not None else "queued")

    def ingest_tick(self, rec: dict) -> None:
        mode = rec.get("mode", "?")
        if mode.startswith("fleet/"):
            mode = "fleet"
        tick, now = rec.get("tick"), rec.get("now", 0.0)
        if tick is None:
            return
        self.saw_ticks = True
        if "arrived" in rec or "blocked" in rec:
            self.saw_causal_fields = True
        per = self._states.setdefault(mode, {})
        arrived = rec.get("arrived")
        if arrived:
            ann = self._announce.setdefault(mode, {})
            for rid in arrived:
                ann[rid] = (tick, now)
        for entry in rec.get("blocked") or []:
            st = self._st(mode, entry[0], tick, now, "queued")
            if st.state in ("queued", "preempt_wait", "replay"):
                note = (entry[1], list(entry[2]))
                if st.state == "queued" and st.last_blocked is not None \
                        and st.last_blocked != note:
                    # The block CHANGED (holders released, or quota
                    # became a capacity wait): split the queued segment
                    # here so the ticks waited so far are billed to the
                    # holders/reason that actually blocked them — the
                    # newest note must not absorb the whole wait.
                    st.close(tick, now, "queued")
                st.last_blocked = note
        terminal = rec.get("terminal")
        if terminal:
            # Tenant/latency land BEFORE finalization below builds the
            # canonical row (the row carries the tenant).
            for t in terminal:
                st = self._st(mode, t["id"], tick, now, "queued")
                st.tenant = t.get("tenant", "default")
                st.ttft_ms = t.get("ttft_ms")
                st.tpot_ms = t.get("tpot_ms")
        for _slot, rid in rec.get("admitted") or []:
            st = self._st(mode, rid, tick, now, "active")
            if st.state in ("queued", "preempt_wait"):
                st.close(tick, now, "active")
            # A replay readmission stays replay until it produces a new
            # token: the re-prefill is crash-caused work, not progress.
        preempted = rec.get("preempted")
        if preempted:
            benef = {v: b for v, b in rec.get("preempted_for") or []}
            for rid in preempted:
                st = per.get(rid)
                if st is None or st.state == "replay":
                    continue  # replay absorbs mid-replay evictions
                st.close(tick, now, "preempt_wait")
                st.beneficiary = benef.get(rid)
        pf = rec.get("prefill")
        if pf and pf[-1] == "emit":
            st = per.get(pf[1])
            if st is not None and st.state == "replay":
                st.close(tick, now, "active")
        for rid in rec.get("finished") or []:
            self._terminal(mode, rid, tick, now, "finished")
        for rid, status in rec.get("aborted") or []:
            self._terminal(mode, rid, tick, now, status)
        if terminal:
            # A terminal entry whose rid never hit the finished/aborted
            # lists (fence-accepted sync only) still finalizes.
            for t in terminal:
                if t["id"] in per:
                    self._terminal(mode, t["id"], tick, now,
                                   t.get("status", "finished"))

    def _terminal(self, mode: str, rid: int, tick: int, now: float,
                  status: str) -> None:
        if rid in self._rows.get(mode, ()):
            return
        st = self._st(mode, rid, tick, now, "queued")
        st.close(tick, now, None)
        st.status = status
        span = tick - st.start_tick
        conserved = span >= 0 and sum(st.cats) == span
        self._rows.setdefault(mode, {})[rid] = (
            rid, status, st.tenant, st.start_tick, tick,
            tuple(st.cats), st.quota_ticks,
            tuple(sorted((st.blockers or {}).items())),
            tuple(sorted((st.preemptors or {}).items())),
            conserved,
        )
        if self.detail:
            self._blames.setdefault(mode, {})[rid] = \
                st.to_blame(rid, mode, tick)
        # Freed: the live set tracks in-flight requests only.
        del self._states[mode][rid]

    # -- results -------------------------------------------------------

    def blames(self) -> dict[str, dict[int, RequestBlame]]:
        """Per-request blame for rendering (detail mode). Non-terminal
        leftovers are included with status None so an incomplete trail
        is visible, not silently dropped."""
        if not self.detail:
            raise ValueError(
                "per-request blame needs BlameAccumulator(detail=True) "
                "— the streaming bench fold keeps aggregates only"
            )
        modes = set(self._blames) | set(self._states) | set(self._rows)
        out: dict[str, dict[int, RequestBlame]] = {}
        for mode in sorted(modes):
            per = dict(self._blames.get(mode, {}))
            for rid, st in self._states.get(mode, {}).items():
                per[rid] = st.to_blame(rid, mode, None)
            out[mode] = dict(sorted(per.items()))
        return out

    def check(self, mode: str) -> list[str]:
        """Conservation + completeness problems for one mode (empty =
        every request terminal and its categories sum bitwise to its
        span — the ISSUE 11 acceptance invariant)."""
        problems = []
        open_rids = sorted(set(self._states.get(mode, ()))
                           | set(self._announce.get(mode, ())))
        for rid in open_rids:
            problems.append(f"rid {rid}: no terminal status in trail")
        for rid, row in sorted(self._rows.get(mode, {}).items()):
            if not row[9]:
                cats = dict(zip(CATEGORIES, row[5]))
                problems.append(
                    f"rid {rid}: blame ticks {sum(row[5])} != "
                    f"span {row[4] - row[3]} "
                    f"({', '.join(f'{k}={v}' for k, v in cats.items())})"
                )
        return problems

    def crc(self, mode: str) -> int:
        """crc32 over the canonical per-request blame of one mode — ONE
        number the determinism gate pins at exact equality (category
        order and field order are part of the contract)."""
        canon = [[row[0], row[1], row[2], row[3], row[4], list(row[5]),
                  row[6], [list(kv) for kv in row[7]],
                  [list(kv) for kv in row[8]]]
                 for _, row in sorted(self._rows.get(mode, {}).items())]
        return zlib.crc32(json.dumps(canon).encode())

    def summary_fields(self, mode: str) -> dict:
        """The `blame` event record's fields (obs.schema family) for
        one mode: aggregate category totals, per-tenant breakdown, and
        the CRC the CI gate pins."""
        rows = self._rows.get(mode, {})
        cats = dict.fromkeys(CATEGORIES, 0)
        tenants: dict[str, dict] = {}
        quota = 0
        for row in rows.values():
            per = tenants.setdefault(row[2], dict.fromkeys(CATEGORIES, 0))
            for c, v in zip(CATEGORIES, row[5]):
                cats[c] += v
                per[c] += v
            quota += row[6]
        open_n = len(self._states.get(mode, ())) \
            + len(self._announce.get(mode, ()))
        return {
            "mode": mode, "requests": len(rows) + open_n,
            "categories": cats, "quota_ticks": quota,
            "tenants": {t: v for t, v in sorted(tenants.items())},
            "conserved": open_n == 0 and all(r[9] for r in rows.values()),
            "crc": self.crc(mode),
        }

    def top_blockers(self, mode: str, k: int = 8) -> list[tuple]:
        """(holder rid, ticks it held others up, victims) ranked — the
        aggregate form of the blocker edges (`mctpu top`'s panel is the
        live twin, fed straight off the tick stream)."""
        held: dict[int, int] = {}
        victims: dict[int, set] = {}
        for row in self._rows.get(mode, {}).values():
            for h, ticks in row[7]:
                held[h] = held.get(h, 0) + ticks
                victims.setdefault(h, set()).add(row[0])
            for h, ticks in row[8]:
                held[h] = held.get(h, 0) + ticks
                victims.setdefault(h, set()).add(row[0])
        ranked = sorted(held.items(), key=lambda kv: (-kv[1], kv[0]))[:k]
        return [(h, t, len(victims[h])) for h, t in ranked]


# -- rendering ---------------------------------------------------------


def render_blame_tree(b: RequestBlame) -> str:
    """One request's blame, category totals then the critical-path
    segments in time order."""
    span = b.span_ticks
    head = [
        f"request {b.rid} [{b.mode}] — {_fmt(b.status)}, "
        f"tenant {b.tenant}, span {_fmt(span)} ticks "
        f"(ticks {_fmt(b.start_tick)}..{_fmt(b.terminal_tick)}), "
        f"ttft {_fmt(b.ttft_ms)} ms, "
        f"conserved {'yes' if b.conserved else 'NO'}",
    ]
    for cat in CATEGORIES:
        ticks = b.cats[cat]
        if ticks == 0 and cat != "self_compute":
            continue
        pct = 100.0 * ticks / span if span else 0.0
        extra = ""
        if cat == "queued_behind" and b.blockers:
            extra = "  behind " + ", ".join(
                f"rid {h} ({t}t)" for h, t in sorted(
                    b.blockers.items(), key=lambda kv: (-kv[1], kv[0])))
            if b.quota_ticks:
                extra += f"  [quota skip-over {b.quota_ticks}t]"
        elif cat == "preempted_by" and b.preemptors:
            extra = "  by " + ", ".join(
                f"rid {h} ({t}t)" for h, t in sorted(
                    b.preemptors.items(), key=lambda kv: (-kv[1], kv[0])))
        head.append(f"  {cat:<18} {ticks:>6} ticks  {pct:5.1f}%  "
                    f"{_fmt(b.cats_ms[cat])} ms{extra}")
    for cat, start, end, detail in b.edges:
        d = ""
        if detail is not None:
            if cat == "queued_behind":
                reason, holders = detail
                d = f"  [{reason}: " + ", ".join(map(str, holders)) + "]"
            elif cat == "preempted_by":
                d = f"  [for rid {detail}]"
            elif cat == "redispatch_replay":
                d = f"  [replica {detail}]"
        head.append(f"    tick {start:>6}..{end:<6} {cat}{d}")
    return "\n".join(head)


def render_aggregate(fields: dict) -> str:
    """Aggregate blame tables: categories, then per-tenant rows."""
    cats = fields["categories"]
    total = sum(cats.values()) or 1
    lines = [
        "| blame (ticks) | " + " | ".join(CATEGORIES) + " | quota | crc |",
        "|---|" + "---|" * (len(CATEGORIES) + 2),
        f"| {fields['mode']} ({fields['requests']} reqs) | "
        + " | ".join(f"{cats[c]} ({100.0 * cats[c] / total:.1f}%)"
                     for c in CATEGORIES)
        + f" | {fields['quota_ticks']} | {_fmt(fields['crc'])} |",
        "",
    ]
    tenants = fields.get("tenants") or {}
    if len(tenants) > 1 or (tenants and "default" not in tenants):
        lines += [
            "| tenant blame (ticks) | " + " | ".join(CATEGORIES) + " |",
            "|---|" + "---|" * len(CATEGORIES),
        ]
        for t, per in tenants.items():
            lines.append(f"| {t} | "
                         + " | ".join(str(per[c]) for c in CATEGORIES)
                         + " |")
        lines.append("")
    return "\n".join(lines)


def render_top_blockers(rows: list[tuple]) -> str:
    if not rows:
        return "(no blocker edges — nothing ever waited behind a holder)"
    lines = ["| top blockers | held others up (ticks) | victims |",
             "|---|---|---|"]
    for rid, ticks, n in rows:
        lines.append(f"| rid {rid} | {ticks} | {n} |")
    return "\n".join(lines)


# -- the CLI -----------------------------------------------------------


def explain_main(argv: list[str] | None = None) -> int:
    """`mctpu explain RUN` — causal blame for a serving run.

    Exits 1 when the trail drifts from the engine's own records (the
    `mctpu trace` lifecycle cross-check) or any terminal request's
    blame fails conservation; 2 on config/legacy-file errors.
    """
    ap = argparse.ArgumentParser(
        prog="mctpu explain",
        description="Causal critical-path attribution from a serving "
                    "run's metrics JSONL: per-request blame trees "
                    "(self/queued-behind/preempted-by/replay/router) "
                    "that sum exactly to end-to-end latency, plus "
                    "aggregate blame and top-blocker tables.",
    )
    ap.add_argument("path", help="metrics JSONL with tick (+ fleet) records")
    ap.add_argument("--request", type=int, default=None,
                    help="blame tree for one request id")
    ap.add_argument("--worst", choices=("ttft", "tpot"), default=None,
                    help="blame trees for the worst-k requests by this "
                         "latency metric")
    ap.add_argument("-k", type=int, default=5,
                    help="how many worst requests (--worst; default 5)")
    ap.add_argument("--tenant", default=None,
                    help="restrict blame accounting to one tenant's "
                         "requests (untagged = 'default')")
    ap.add_argument("--mode", default=None,
                    help="restrict to one scheduler mode")
    ap.add_argument("--format", choices=("md", "json"), default="md")
    args = ap.parse_args(argv)

    # Lazy sibling import: both jax-free; explain reuses trace's
    # reconstruction as the drift check against the request records.
    from .timeline import reconstruct

    try:
        runs = [r for r in iter_runs(args.path) if r]
    except (OSError, ValueError) as e:
        print(f"error: {args.path}: {e}", file=sys.stderr)
        return 2
    rc = 0
    any_mode = False
    for i, records in enumerate(runs, 1):
        acc = BlameAccumulator(detail=True)
        for rec in records:
            acc.ingest(rec)
        if not acc.saw_ticks:
            continue
        if not acc.saw_causal_fields:
            print(f"error: {args.path}: tick records carry no causal "
                  "fields (arrived/blocked) — regenerate the run with "
                  "an ISSUE-11 producer", file=sys.stderr)
            return 2
        lifecycles = reconstruct(records)
        label = args.path if len(runs) == 1 \
            else f"{args.path} (run {i}/{len(runs)})"
        blames = acc.blames()
        for mode in sorted(blames):
            if args.mode is not None and mode != args.mode:
                continue
            per = blames[mode]
            if args.tenant is not None:
                per = {rid: b for rid, b in per.items()
                       if b.tenant == args.tenant}
                if not per:
                    continue
            any_mode = True
            # Drift checks: conservation (this module's invariant) and
            # the lifecycle cross-check vs the engine's own records.
            problems = [p for p in acc.check(mode)
                        if args.tenant is None
                        or p.split(":")[0].removeprefix("rid ").strip()
                        in {str(r) for r in per}]
            lcs = lifecycles.get(mode, {})
            bad = [rid for rid, lc in lcs.items() if not lc.consistent
                   and (args.tenant is None or rid in per)]
            agg = _aggregate(per, mode, acc,
                             full=len(per) == len(blames[mode]))
            if args.format == "json":
                print(json.dumps({
                    "path": args.path, "run": i, "mode": mode,
                    "requests": len(per),
                    "aggregate": agg,
                    "top_blockers": acc.top_blockers(mode),
                    "problems": problems,
                    "inconsistent": sorted(bad),
                    "blames": {str(rid): b.to_fields()
                               for rid, b in sorted(per.items())},
                }))
            elif args.request is not None:
                b = per.get(args.request)
                if b is None:
                    print(f"error: no request {args.request} in mode "
                          f"{mode} of {label}", file=sys.stderr)
                    rc = max(rc, 2)
                    continue
                print(f"## Explain — {label} [{mode}]\n")
                print(render_blame_tree(b))
                print()
            else:
                print(f"## Explain — {label} [{mode}]\n")
                print(render_aggregate(agg))
                print(render_top_blockers(acc.top_blockers(mode)))
                print()
                if args.worst is not None:
                    key = (lambda b: b.ttft_ms) if args.worst == "ttft" \
                        else (lambda b: b.tpot_ms)
                    for b in worst_k(list(per.values()), key, args.k):
                        print(render_blame_tree(b))
                        print()
            if problems:
                print(f"error: {len(problems)} blame account(s) violate "
                      f"conservation/completeness in mode {mode}: "
                      + "; ".join(problems[:5]), file=sys.stderr)
                rc = max(rc, 1)
            if bad:
                print(f"error: {len(bad)} request(s) with lifecycles "
                      f"inconsistent vs engine records in mode {mode}: "
                      f"{sorted(bad)[:10]}", file=sys.stderr)
                rc = max(rc, 1)
    if not any_mode:
        print(f"error: {args.path}: no tick trail to explain "
              "(run with --metrics-jsonl and full logging)",
              file=sys.stderr)
        return 2
    return rc


def _aggregate(per: dict[int, RequestBlame], mode: str,
               acc: BlameAccumulator, *, full: bool) -> dict:
    """Aggregate fields for a (possibly tenant-filtered) request set —
    the full-set form (`full`, decided by the caller that already holds
    the unfiltered mapping) delegates to summary_fields so the rendered
    table and the stamped `blame` record can never disagree."""
    if full:
        return acc.summary_fields(mode)
    cats = dict.fromkeys(CATEGORIES, 0)
    tenants: dict[str, dict] = {}
    quota = 0
    for b in per.values():
        t = tenants.setdefault(b.tenant, dict.fromkeys(CATEGORIES, 0))
        for c in CATEGORIES:
            cats[c] += b.cats[c]
            t[c] += b.cats[c]
        quota += b.quota_ticks
    return {"mode": mode, "requests": len(per), "categories": cats,
            "quota_ticks": quota,
            "tenants": {t: v for t, v in sorted(tenants.items())},
            "conserved": all(b.conserved for b in per.values()),
            # No CRC on a filtered view: the canonical CRC covers the
            # whole mode, and stamping it next to a subset's numbers
            # would invite comparing the two.
            "crc": None}


if __name__ == "__main__":
    sys.exit(explain_main())
