"""The one JSONL record shape every metrics producer shares.

Before this module, each emitter invented its own dialect: MetricsLogger
wrote {"event", "t", ...}, bench.py printed a one-off benchmark object,
scripts/profile_*.py printed ad-hoc rows, and PERF_capture.jsonl mixed
all three plus `# comment` lines. PERF.md tables were then assembled by
hand from the union. One schema ends that: every record carries a
version stamp and an event name, event families declare their required
keys, and `iter_records`/`validate_record` are the single read/check
path used by the `mctpu report` aggregator, the tests, and any future
consumer.

Records are one JSON object per line. Lines starting with '#' are
comments (PERF_capture.jsonl's capture markers) and are skipped by the
reader, so existing capture files stay parseable.
"""

from __future__ import annotations

import json
from pathlib import Path
from collections.abc import Iterable, Iterator

SCHEMA_VERSION = 1

# Keys every record must carry. "t" is seconds since the producer
# started (relative, so records from different processes don't need
# clock agreement); "event" names the record family.
REQUIRED_KEYS = ("schema", "event", "t")

# Per-family required keys (beyond REQUIRED_KEYS). Families not listed
# here are free-form — the schema constrains what the report aggregator
# depends on, not what producers may add.
EVENT_KEYS: dict[str, tuple[str, ...]] = {
    # Training progress (per log interval). "step" is the in-run step.
    "train": ("step", "loss"),
    # Epoch wall-clock (CNN trainer).
    "epoch": ("epoch", "seconds"),
    # Eval sweep result.
    "eval": (),
    # Step-phase wall-clock attribution: milliseconds per step spent in
    # host-side data prep, async dispatch, device compute wait, and
    # checkpointing, over `steps` steps.
    "step_phases": ("steps", "phases_ms"),
    # Compiled-program accounting from XLA cost analysis: FLOPs and
    # bytes per dispatched program, plus HLO collective counts.
    "program": ("flops", "collectives"),
    # Device memory telemetry (per-device bytes; absent stats -> null).
    "memory": ("devices",),
    # Host-side span (obs.trace.span): nested name and duration.
    "span": ("name", "ms"),
    # One served request (serve/engine.py): latency from arrival to
    # first token (ttft_ms) and to completion (latency_ms). Aborted
    # requests carry null where the moment never happened; "status" is
    # the terminal status (finished/expired/cancelled/rejected/failed)
    # — absent in pre-ISSUE-4 records, treated as "finished".
    # "tenant" (ISSUE 8) is the traffic-class identity SLO accounting
    # buckets by — absent in pre-ISSUE-8 records, treated as "default".
    "request": ("id", "mode", "prompt_tokens", "output_tokens",
                "ttft_ms", "latency_ms"),
    # One serving-bench run summary per scheduler mode (serve/bench.py).
    "serve": ("mode", "requests", "tokens_per_s"),
    # One fault-domain occurrence (faults.py / trainers / serve engine):
    # injected faults (kind="injected_*"), supervisor restarts, NaN-guard
    # actions (nonfinite_step / nan_restore), checkpoint fallbacks,
    # preemptions (kind="preempt") and cross-resume topology changes
    # (kind="topology_change" — ISSUE 5), request aborts/rejections,
    # watchdog breaches. Free-form beyond "kind" — the robustness table
    # aggregates by kind.
    "fault": ("kind",),
    # One checkpoint lifecycle moment (trainers, ISSUE 5): "reason" is
    # why it happened (preempt = the preemption snapshot, resume = a
    # restore into a fresh process); "step" is the global step it
    # captures. Interval saves stay un-evented (they'd dominate the
    # stream); the elasticity-relevant moments are what reports need.
    "ckpt": ("step", "reason"),
    # One MetricsRegistry snapshot (obs/metrics.py, ISSUE 6): aggregated
    # counters (monotonic totals), gauges ({value, lo, hi}), and
    # log-bucket histograms ({count, sum, min, max, buckets: [[i, n]]}
    # over obs.metrics.log_bucket_bounds edges). `mctpu top` tails
    # these; `mctpu compare` gates their named values.
    "metrics": ("counters", "gauges", "histograms"),
    # One fleet-router iteration (serve/fleet.py, ISSUE 7): healthy
    # replica count, undispatched backlog, this tick's routing moments
    # (dispatched/redispatched rids) and the per-replica load map
    # {name: [queue, running, free_pages]} the dispatch policy reads.
    # Causality (ISSUE 11): "arrived" (rids whose arrival fell due this
    # tick) and "failed_over" ([[rid, replica]] — requests a failover
    # stranded, ending their active blame segment at the crash).
    # Disaggregation (ISSUE 13): "handoff_started" ([[rid, src]]),
    # "handoff_done" ([[rid, dst]]), "handoff_aborted" ([[rid, reason]])
    # and "handoffs_inflight" — the prefill->decode KV transfer markers,
    # ordered in the JSONL before any replica record of the same tick.
    # Lossy transport (ISSUE 20, --transport only): "transport" (the
    # bus's pre-step counter/link/partition block the replay mirror
    # folds into fleet_digest), "t_delivered" ([[rid, replica]] —
    # dispatches DELIVERED over the wire this tick, distinct from
    # dispatched_to which marks the router's send), "t_terminal"
    # (terminal details harvested from bus messages between ticks),
    # "t_retransmits" ([[kind, dst, rid]]) and "lease_refused"
    # ([[rid, replica]] — commits a replica refused past its lease).
    "fleet": ("tick", "now", "replicas"),
    # One transport-bus lifecycle moment (serve/transport.py, ISSUE 20):
    # kind is partition_open / partition_heal; "name" the isolated
    # replica, "tick"/"heal" the window. Message-level faults stay
    # un-evented as records (they'd rival the tick volume) — the
    # per-tick fleet "transport" block carries the counters.
    "transport": ("kind",),
    # One prefill->decode KV handoff lifecycle moment (serve/fleet.py,
    # ISSUE 13): "state" is started / done / aborted (aborted carries
    # "reason": sender_dead / receiver_dead / dropped / kv_corrupt /
    # decode_pool_empty / cancelled); "src"/"dst" the replica names,
    # "pages" the transfer size, "hid" the handoff sequence number the
    # fleet.handoff fault site triggers on.
    "handoff": ("rid", "state"),
    # One replica lifecycle moment (serve/fleet.py, ISSUE 7): kind is
    # join / crash / dead / restart_scheduled / restart / circuit_open
    # / leave / drain_complete — plus, for disaggregated fleets
    # (ISSUE 13), degraded / restored, whose "name" is the POOL
    # ("prefill"/"decode"), not a replica. Free-form beyond
    # (name, kind) — the fleet report table aggregates by kind per
    # name.
    "replica": ("name", "kind"),
    # One serving-engine scheduler iteration (serve/engine.py, ISSUE 6):
    # the per-tick state `mctpu trace` reconstructs request lifecycles
    # from — queue depth, free pages, and the tick's scheduling moments
    # (admitted [[slot, rid]], prefill [slot, rid, n] | null, decoded
    # [[slot, rid]], finished/preempted/failed rids, aborted
    # [[rid, status]]). "now" is seconds since run start on the
    # engine's (injectable) clock. "terminal" (ISSUE 8) details each
    # request reaching a terminal status this tick ({id, tenant,
    # status, ttft_ms, tpot_ms, queue_wait_ms}) — the streaming
    # good/bad events the SLO burn-rate rules fold. Prefix-sharing
    # runs (ISSUE 9) additionally carry "prefix_hits"
    # ([[rid, matched_tokens]] — the lifecycle marker `mctpu trace`
    # renders) and "prefix" ({shared_pages, retained_pages, hits,
    # misses, hit_tokens, cow_copies, inserts, evictions} — the
    # `mctpu top` cache panel). Causality (ISSUE 11): "arrived" (rids
    # whose arrival fell due this tick — the blame span's anchor),
    # "blocked" ([[rid, reason, holders]] — admission attempts that
    # failed, reason "pages"/"slots"/"quota", holders the occupying
    # rids: the blocker edges `mctpu explain` blames queue waits on),
    # and "preempted_for" ([[victim, beneficiary]] — whose page need
    # forced each eviction). Speculative runs (ISSUE 14) carry "spec"
    # ([[rid, proposed, accepted]] per slot round — a spec decode tick
    # commits 1 + accepted tokens for its rid, which is how `mctpu
    # trace` keeps the token cross-check exact under variable-length
    # commits).
    "tick": ("tick", "now", "queue", "free_pages"),
    # One benchmark headline (bench.py, scripts/bench_decode.py,
    # scripts/bench_speculative.py): "metric" names the measured
    # quantity, "value" its number (null when the capture failed —
    # bench.py's error line still stamps the family), "unit" its unit.
    # `mctpu compare` reads these as dotted `bench.*` metrics. This
    # family was emitted unregistered for three PRs — the exact drift
    # class `mctpu lint` MCT005 now catches at the call site.
    "bench": ("metric", "value", "unit"),
    # One causal-blame summary per mode (obs/causal.py, ISSUE 11):
    # aggregate per-category tick totals ("categories": self_compute /
    # queued_behind / preempted_by / redispatch_replay / router_wait —
    # each request's categories sum bitwise to its end-to-end tick
    # span), per-tenant breakdown ("tenants"), the quota skip-over
    # share ("quota_ticks"), and "crc" — the canonical per-request
    # blame CRC the fleet determinism gate pins at exact equality.
    "blame": ("mode", "requests", "categories"),
    # One SLO-attained goodput measurement (obs/goodput.py, ISSUE 16):
    # "kind" is run (one measured run) / candidate (one topology inside
    # an `mctpu autosize` sweep) / frontier (the sweep's folded
    # goodput-frontier summary + recommendation). run/candidate records
    # carry the Goodput.fields() block (requests, good, duration_s,
    # chips, goodput_rps, per_chip_rps, good_fraction, estimated,
    # thresholds); candidates add their topology spelling + the
    # underlying storm's trace/blame/state CRCs (unchanged by the sweep
    # harness — pinned by test); the frontier adds evaluated/pruned
    # counts, the ranked candidate order, and frontier_crc /
    # recommendation_crc — the numbers the autosize determinism gate
    # pins at 0%/equal.
    "goodput": ("kind",),
    # One chaos-search result (chaos/, ISSUE 19): "kind" is episode
    # (one sampled fault-schedule episode: its --fault-plan spelling,
    # axes label, violation check names, replay tick coverage, and the
    # trace/state/blame/episode CRCs the chaos determinism gate pins
    # at exact equality) / summary (the whole search: episode and
    # violation counts, the folded episodes_crc chain, and — on a
    # failing search — the ddmin-minimized plan + probe count).
    "chaos": ("kind",),
    # One fired alert (obs/alerts.py, ISSUE 8): "rule" names the rule
    # instance, "kind" its class (threshold / rate_of_change / absence
    # / burn_rate), "seq" its position in the run's alert sequence
    # (obs.alerts.alerts_crc pins the whole sequence as one number),
    # "at" the triggering record's timeline stamp; context beyond that
    # is free-form per kind (tenant/metric/burn for burn_rate,
    # field/value/threshold for threshold, family/gap_s for absence).
    "alert": ("seq", "rule", "kind", "severity", "at"),
}


def make_record(event: str, t: float, **fields) -> dict:
    """Assemble a schema-stamped record (does not validate — producers
    that want the check call validate_record on the result)."""
    return {"schema": SCHEMA_VERSION, "event": event, "t": round(t, 4),
            **fields}


def validate_record(rec: dict) -> dict:
    """Check one record against the schema; returns it unchanged.

    Raises ValueError naming every missing key — the error message is
    the schema documentation a producer sees first.
    """
    if not isinstance(rec, dict):
        raise ValueError(f"record must be an object, got {type(rec).__name__}")
    missing = [k for k in REQUIRED_KEYS if k not in rec]
    if missing:
        raise ValueError(f"record missing required keys {missing}: {rec}")
    if not isinstance(rec["schema"], int):
        raise ValueError(f"record schema must be an int: {rec['schema']!r}")
    if rec["schema"] > SCHEMA_VERSION:
        raise ValueError(
            f"record schema v{rec['schema']} is newer than this reader "
            f"(v{SCHEMA_VERSION})"
        )
    extra = EVENT_KEYS.get(rec["event"], ())
    missing = [k for k in extra if k not in rec]
    if missing:
        raise ValueError(
            f"{rec['event']!r} record missing keys {missing}: {rec}"
        )
    return rec


# Comment prefix MetricsLogger writes on each open — the run boundary
# in an append-mode file (iter_runs splits on it; iter_records skips it
# like any other comment).
RUN_MARKER = "# run"


def iter_records(path: str | Path, *, strict: bool = False) -> Iterator[dict]:
    """Yield records from a JSONL file, skipping blank and '#' lines.

    Pre-schema records (no "schema" key) are passed through unvalidated
    unless strict=True — report must keep reading old PERF_capture.jsonl
    files.
    """
    for _, rec in _iter_lines(path, strict=strict):
        if rec is not None:
            yield rec


def iter_runs(path: str | Path, *, strict: bool = False) -> Iterator[list[dict]]:
    """Yield one record list per run, split at RUN_MARKER comment lines
    (append-mode files accumulate runs; aggregating across them would
    blend unrelated numbers). A file with no markers is one run."""
    current: list[dict] = []
    seen_any = False
    for is_marker, rec in _iter_lines(path, strict=strict):
        if is_marker:
            if current or seen_any:
                yield current
                current = []
            seen_any = True
        elif rec is not None:
            current.append(rec)
    if current or not seen_any:
        yield current


def _iter_lines(path: str | Path, *, strict: bool):
    """(is_run_marker, record | None) per line, shared by the readers."""
    with Path(path).open() as fh:
        for lineno, line in enumerate(fh, 1):
            line = line.strip()
            if line.startswith(RUN_MARKER):
                yield True, None
                continue
            if not line or line.startswith("#"):
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                if strict:
                    raise ValueError(f"{path}:{lineno}: bad JSON: {e}") from e
                continue
            if strict or (isinstance(rec, dict) and "schema" in rec):
                validate_record(rec)
            yield False, rec


def load_records(path: str | Path, *, strict: bool = False) -> list[dict]:
    return list(iter_records(path, strict=strict))


def dump_records(records: Iterable[dict], path: str | Path) -> None:
    """Write records as JSONL (the round-trip twin of load_records)."""
    with Path(path).open("w") as fh:
        for rec in records:
            fh.write(json.dumps(rec) + "\n")


def fmt_cell(v, prec: int = 6) -> str:
    """The one table-cell formatter every obs renderer (report, trace,
    top, compare) shares: None is an em-dash (a moment that never
    happened), floats render at `prec` significant digits, dicts as
    sorted k:v pairs. Golden-output tests pin this formatting — change
    it here and every renderer moves together."""
    if v is None:
        return "—"
    if isinstance(v, float):
        return f"{v:.{prec}g}"
    if isinstance(v, dict):
        return ", ".join(f"{k}:{n}" for k, n in sorted(v.items())) or "—"
    return str(v)
