"""Compiled-program accounting: FLOPs/bytes/collectives from the program
XLA actually runs.

Before this module, MFU numerators were analytic formulas
(train/lm.lm_flops_per_token) or hand-derived constants inside bench
scripts, and collective behavior was asserted from reading the source.
Here both are computed properties of the compiled step:

- `analyze(jitted_fn, *args)` lowers + compiles the function for the
  given arguments and reads `cost_analysis()` — FLOPs and bytes of the
  real post-fusion program, the same numbers XProf's roofline uses.
- Collectives are counted two ways, because they appear at two levels:
  `jaxpr_collective_counts` walks the jaxpr (explicit collectives the
  program writes itself — shard_map psum/ppermute/all_to_all), and
  `hlo_collective_counts` scans the compiled HLO (which ALSO includes
  whatever GSPMD inserted). The HLO count is the ground truth for "what
  crosses the interconnect per step"; the jaxpr count is the structural
  check tests pin.

Caveats, so numbers are read honestly: `cost_analysis` reports the
per-module optimized-HLO estimate (per-core on multi-device backends),
and it counts STATIC HLO — a `lax.scan`/`while` body is counted ONCE,
not per trip (measured: a 10-iteration scan of a matmul reports the
same FLOPs as 1 iteration). For a scanned-epoch program the reported
FLOPs are therefore ~one step's, not the dispatch's; producers record
that with `counting="static-body"` and `steps_per_dispatch=1` so
downstream per-step math stays correct. The same staticness applies to
collective counts (a psum inside the scan body counts 1, executes N
times). Finally, `lower().compile()` does not share jit's executable
cache in all JAX versions, so `analyze` can cost one extra compile —
callers on hot paths do it once per program shape and keep it out of
their timing envelopes (StepTimer.exclude).
"""

from __future__ import annotations

import dataclasses
import re

import jax

# Peak dense-matmul throughput per (backend, dtype) — the MFU
# denominator. The ONE table (scripts/bench_lm.py imports it); extend as
# chips appear. CPU has no meaningful MXU peak: peak_flops returns None
# there and MFU reports null rather than a number against a fake peak.
PEAK_TFLOPS: dict[str, float] = {
    "tpu_v5e_bf16": 197.0,
    "tpu_v5e_f32": 49.0,
}

# Jaxpr primitive names that are cross-device collectives.
COLLECTIVE_PRIMS = frozenset({
    "psum", "pmax", "pmin", "ppermute", "pbroadcast", "all_gather",
    "all_to_all", "reduce_scatter", "psum_scatter",
})

# HLO instruction names that are collectives (async forms appear as
# NAME-start/NAME-done pairs — counting '-start' or the bare name, and
# never '-done', counts each collective once).
_HLO_COLLECTIVE_RE = re.compile(
    r"\b(all-reduce|all-gather|all-to-all|collective-permute"
    r"|reduce-scatter)(-start)?\("
)


@dataclasses.dataclass
class ProgramCosts:
    """Accounting for ONE compiled program (which may run many train
    steps per dispatch — scanned epochs; `flops` is per dispatch).

    The alias/memory fields are the donation ledger: `aliased_outputs`
    counts entries in the compiled HLO's input_output_alias table (one
    per donated buffer XLA actually aliased), `alias_bytes` is their
    total size, and `temp_bytes` the program's live scratch — together
    the mechanical proof that donate_argnums took effect (a shape or
    layout mismatch silently degrades donation to a copy). All None when
    the backend exposes no memory analysis."""

    flops: float | None
    bytes_accessed: float | None
    collectives: dict[str, int]
    aliased_outputs: int = 0
    alias_bytes: float | None = None
    temp_bytes: float | None = None
    output_bytes: float | None = None
    argument_bytes: float | None = None

    def to_fields(self) -> dict:
        """The record fields a "program" event carries (obs.schema)."""
        return {
            "flops": self.flops,
            "bytes": self.bytes_accessed,
            "collectives": self.collectives,
            "aliased_outputs": self.aliased_outputs,
            "alias_bytes": self.alias_bytes,
            "temp_bytes": self.temp_bytes,
        }


def peak_flops(dtype: str = "bfloat16", *, backend: str | None = None,
               override_tflops: float | None = None) -> float | None:
    """Peak FLOP/s for the MFU denominator, or None when the backend has
    no registered peak. An override names the chip's bf16 peak; the f32
    peak scales by the same ratio as v5e (the MXU's f32 path)."""
    if override_tflops is not None:
        bf16 = override_tflops
    elif (backend or jax.default_backend()) == "tpu":
        bf16 = PEAK_TFLOPS["tpu_v5e_bf16"]
    else:
        return None
    if dtype in ("bfloat16", "bf16"):
        return bf16 * 1e12
    return bf16 * 1e12 * PEAK_TFLOPS["tpu_v5e_f32"] / PEAK_TFLOPS["tpu_v5e_bf16"]


def mfu(flops: float | None, seconds: float, peak: float | None) -> float | None:
    """Model FLOPs utilization; None whenever a factor is unknown."""
    if not flops or not peak or seconds <= 0:
        return None
    return flops / seconds / peak


def _normalize_cost_analysis(ca) -> dict:
    """cost_analysis() returns a dict on some backends/versions and a
    one-element list of dicts on others; normalize to one dict."""
    if ca is None:
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return dict(ca)


def hlo_collective_counts(hlo_text: str) -> dict[str, int]:
    """Count collective instructions in compiled HLO text."""
    counts: dict[str, int] = {}
    for m in _HLO_COLLECTIVE_RE.finditer(hlo_text):
        name = m.group(1)
        counts[name] = counts.get(name, 0) + 1
    return counts


def _walk_jaxpr(jaxpr, counts: dict[str, int]) -> None:
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name in COLLECTIVE_PRIMS:
            counts[name] = counts.get(name, 0) + 1
        for v in eqn.params.values():
            # Recurse into sub-jaxprs (jit/scan/while/cond/shard_map
            # bodies) wherever they appear in the params tree.
            for sub in jax.tree_util.tree_leaves(
                v, is_leaf=lambda x: hasattr(x, "jaxpr") or hasattr(x, "eqns")
            ):
                inner = getattr(sub, "jaxpr", sub)
                if hasattr(inner, "eqns"):
                    _walk_jaxpr(inner, counts)


def jaxpr_collective_counts(fn, *args, **kwargs) -> dict[str, int]:
    """Count explicit collective primitives in fn's jaxpr (static count:
    a ppermute inside a scan body counts once, not per iteration)."""
    closed = jax.make_jaxpr(fn)(*args, **kwargs)
    counts: dict[str, int] = {}
    _walk_jaxpr(closed.jaxpr, counts)
    return counts


def hlo_alias_count(hlo_text: str) -> int:
    """Number of input->output buffer aliases in a compiled module — the
    entries of the header's `input_output_alias={ {i}: (p, {}, kind) }`
    table, each tagged `may-alias` or `must-alias`. 0 means donation
    (if requested) was dropped entirely."""
    head = hlo_text.split("\n", 1)[0]
    return head.count("may-alias") + head.count("must-alias")


def _memory_fields(compiled) -> dict:
    """alias/temp/output/argument bytes from XLA memory analysis; {} when
    the backend doesn't expose it."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return {}
    if ma is None:
        return {}
    out = {}
    for field, attr in (
        ("alias_bytes", "alias_size_in_bytes"),
        ("temp_bytes", "temp_size_in_bytes"),
        ("output_bytes", "output_size_in_bytes"),
        ("argument_bytes", "argument_size_in_bytes"),
    ):
        v = getattr(ma, attr, None)
        if v is not None:
            out[field] = float(v)
    return out


def analyze(fn, *args, **kwargs) -> ProgramCosts:
    """Lower + compile `fn` for these args and read the XLA accounting.

    `fn` must be jit-wrapped (anything with .lower — jax.jit output).
    Raises whatever lowering/compilation raises; use `try_analyze` on
    paths that must never fail for telemetry's sake.
    """
    compiled = fn.lower(*args, **kwargs).compile()
    costs = _normalize_cost_analysis(compiled.cost_analysis())
    try:
        hlo = compiled.as_text()
    except Exception:
        hlo = ""
    return ProgramCosts(
        flops=costs.get("flops"),
        bytes_accessed=costs.get("bytes accessed"),
        collectives=hlo_collective_counts(hlo),
        aliased_outputs=hlo_alias_count(hlo),
        **_memory_fields(compiled),
    )


def tree_bytes(tree) -> int:
    """Total bytes of a pytree of arrays (the donatable size of a state
    argument — the denominator assert_donation checks alias_bytes
    against)."""
    return sum(
        int(getattr(l, "nbytes", 0))
        for l in jax.tree_util.tree_leaves(tree)
    )


def donation_report(fn, *args, **kwargs) -> dict | None:
    """Compile fn(*args) and report whether its donated argument 0 (the
    state pytree, by the repo-wide donate_jit convention) was actually
    aliased: {"aliased_outputs", "alias_bytes", "state_bytes",
    "fraction"}. None when the backend resists AOT analysis."""
    costs = try_analyze(fn, *args, **kwargs)
    if costs is None:
        return None
    state_bytes = tree_bytes(args[0]) if args else 0
    alias = costs.alias_bytes
    return {
        "aliased_outputs": costs.aliased_outputs,
        "alias_bytes": alias,
        "state_bytes": state_bytes,
        "fraction": (
            alias / state_bytes if alias is not None and state_bytes else None
        ),
    }


def assert_donation(fn, *args, min_fraction: float = 0.9, label: str = "step",
                    **kwargs) -> dict:
    """The compile-time donation guard: raise unless at least
    `min_fraction` of the state argument's bytes are input/output-aliased
    in the compiled program. Small unaliased leaves (a scalar step
    counter XLA folds, adamw's count) are why the bar is a byte fraction,
    not a leaf count. Returns the donation_report on success; raises
    RuntimeError when analysis is unavailable (a guard that silently
    passes is no guard)."""
    rep = donation_report(fn, *args, **kwargs)
    if rep is None:
        raise RuntimeError(
            f"{label}: donation guard could not analyze the compiled "
            "program on this backend"
        )
    frac = rep["fraction"]
    if rep["aliased_outputs"] and frac is None:
        # The HLO alias table proves donation took effect but the
        # backend exposes no memory_analysis() to size it — that is
        # missing ACCOUNTING, not dropped donation; report it as the
        # unavailable-analysis case the docstring promises.
        raise RuntimeError(
            f"{label}: donation happened ({rep['aliased_outputs']} "
            "aliased outputs) but this backend exposes no memory "
            "analysis to check the byte fraction"
        )
    if not rep["aliased_outputs"] or frac is None or frac < min_fraction:
        raise AssertionError(
            f"{label}: expected >= {min_fraction:.0%} of the state's "
            f"{rep['state_bytes']} bytes aliased input->output, got "
            f"{rep['alias_bytes']} over {rep['aliased_outputs']} aliases "
            "— donation was dropped (donate flag off, or an output "
            "shape/layout mismatch degraded it to a copy)"
        )
    return rep


def try_analyze(fn, *args, **kwargs) -> ProgramCosts | None:
    """analyze(), or None if anything about this backend/function resists
    AOT lowering — telemetry must degrade, not break the train loop."""
    try:
        return analyze(fn, *args, **kwargs)
    except Exception:
        return None


def log_program(metrics, label: str, fn, *args,
                steps_per_dispatch: int = 1,
                counting: str = "program",
                compute_dtype: str = "float32") -> bool:
    """Analyze `fn(*args)` and emit ONE "program" record to `metrics`
    (a utils.logging.MetricsLogger). Returns False when analysis failed
    — the ONE emit path both trainers share, so the record shape cannot
    drift between them.

    counting="static-body" marks a scanned program whose body XLA counts
    once (see module docstring): such producers pass
    steps_per_dispatch=1 so flops stay ~per-step."""
    costs = try_analyze(fn, *args)
    if costs is None:
        return False
    metrics.log(
        "program", label=label, steps_per_dispatch=steps_per_dispatch,
        counting=counting, backend=jax.default_backend(),
        compute_dtype=compute_dtype, **costs.to_fields(),
    )
    return True
