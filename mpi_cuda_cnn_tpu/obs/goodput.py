"""SLO-attained goodput: per-chip requests/s that meet EVERY objective.

Throughput without latency is a lie at capacity-planning time: a
topology can post the best tokens/s while blowing every TPOT budget
(PERF.md's 2:2 disagg split does exactly that). DistServe (PAPERS.md)
names the metric that actually sizes fleets — GOODPUT, the rate of
requests whose TTFT and TPOT objectives BOTH hold, normalized per chip.
This module is that one definition, deliberately jax-free and
wall-clock-free like obs/slo.py: a pure fold over a run's terminal
events, so two identical-seed runs produce bitwise-identical goodput.

A request is GOOD iff it finished AND every latency objective its
tenant's SLO declares (ttft_ms / tpot_ms / queue_wait_ms — the joint,
not any single axis) holds at the objective's threshold. Goodput is
good requests / run duration; per-chip divides by the serving chip
count (fleet replicas; 1 for a single engine).

Two paths, mirroring `mctpu health`'s fidelity order:

1. exact — per-tick `terminal` entries / `request` records via the
   obs.slo accountant's own classify (one good/bad definition, shared
   with health verdicts and the burn rules);
2. estimate — summary-only runs (`--log summary` storms): finished
   counts from statuses, per-axis good fractions from the registry's
   log-bucket histograms, joint approximated as their product
   (independence assumption — flagged `estimated`, like health's
   `est` rows).

Results are emitted as the versioned `goodput` schema family
(obs/schema.EVENT_KEYS): kind="run" for a single measured run,
kind="candidate"/"frontier" for `mctpu autosize` sweep output.
"""

from __future__ import annotations

import dataclasses

from .schema import make_record
from .slo import (
    LATENCY_METRICS,
    SLOSpec,
    collect_terminals,
    hist_good_fraction,
    run_mode,
)


@dataclasses.dataclass
class Goodput:
    """One goodput measurement. `requests` is every terminal seen,
    `good` the joint-SLO-attained finished count. `thresholds` records
    the per-metric thresholds applied (wildcard-tenant view) so a
    stamped record is self-describing."""

    requests: int
    good: int
    duration_s: float
    chips: int
    estimated: bool
    thresholds: dict

    @property
    def goodput_rps(self) -> float | None:
        if self.duration_s <= 0:
            return None
        return self.good / self.duration_s

    @property
    def per_chip_rps(self) -> float | None:
        rps = self.goodput_rps
        return None if rps is None else rps / max(self.chips, 1)

    @property
    def good_fraction(self) -> float | None:
        return self.good / self.requests if self.requests else None

    def fields(self) -> dict:
        """Flat field dict (what `goodput` records and frontier rows
        carry; rounding pins the bitwise determinism contract)."""
        rps = self.goodput_rps
        per = self.per_chip_rps
        frac = self.good_fraction
        return {
            "requests": self.requests,
            "good": self.good,
            "duration_s": round(self.duration_s, 4),
            "chips": self.chips,
            "goodput_rps": None if rps is None else round(rps, 3),
            "per_chip_rps": None if per is None else round(per, 3),
            "good_fraction": None if frac is None else round(frac, 6),
            "estimated": self.estimated,
            "thresholds": self.thresholds,
        }


def latency_objectives(spec: SLOSpec, tenant: str) -> list:
    """The tenant's latency objectives (ttft/tpot/queue-wait — the
    joint goodput judges). Availability is implied by the finished
    requirement; a spec with NO latency objectives yields [] and the
    request is judged on finishing alone (degenerate but honest)."""
    return [o for o in spec.objectives(tenant)
            if o.metric in LATENCY_METRICS]


def spec_thresholds(spec: SLOSpec) -> dict:
    """{metric: threshold_ms} for the wildcard tenant — the stamp a
    goodput record carries so readers know what was judged."""
    return {o.metric: o.threshold_ms
            for o in latency_objectives(spec, "*")}


def is_good(term: dict, spec: SLOSpec) -> bool:
    """True iff one terminal-field dict finished and holds EVERY
    latency objective its tenant declares (obs.slo.Objective.classify
    — the one good/bad definition health verdicts use; a latency
    moment that was never measured counts as not-good here: goodput is
    a guarantee, and an unmeasured TTFT guarantees nothing)."""
    if term.get("status", "finished") != "finished":
        return False
    tenant = term.get("tenant") or "default"
    for obj in latency_objectives(spec, tenant):
        v = term.get(obj.metric)
        if v is None or v > obj.threshold_ms:
            return False
    return True


def goodput_from_terminals(terminals: list[tuple[float, str, dict]],
                           spec: SLOSpec, *, duration_s: float,
                           chips: int = 1) -> Goodput:
    """Exact goodput from (event_time, mode, terminal-field) triples
    (obs.slo.collect_terminals shape) over a known run duration."""
    good = sum(1 for _, _, term in terminals if is_good(term, spec))
    return Goodput(requests=len(terminals), good=good,
                   duration_s=duration_s, chips=chips, estimated=False,
                   thresholds=spec_thresholds(spec))


def _mode_durations(records: list[dict]) -> dict[str, float]:
    """Per-mode run duration: the serve summary's duration_s when
    stamped, else the newest timeline stamp seen for the mode."""
    out: dict[str, float] = {}
    for rec in records:
        mode = run_mode(rec)
        if rec.get("event") == "serve" and rec.get("duration_s"):
            out[mode] = max(out.get(mode, 0.0), float(rec["duration_s"]))
        elif rec.get("event") == "tick":
            now = rec.get("now", rec.get("t", 0.0)) or 0.0
            out.setdefault(mode, 0.0)
            out[mode] = max(out[mode], float(now))
    return out


def _chips_from_records(records: list[dict]) -> int:
    """Serving chip count: the fleet summary's replica count (initial
    — what the budget paid for, not what survived crashes), else 1."""
    for rec in reversed(records):
        if rec.get("event") == "serve":
            n = rec.get("replicas_initial") or rec.get("replicas")
            if n:
                return int(n)
    return 1


def goodput_from_summary(records: list[dict],
                         spec: SLOSpec, *, chips: int | None = None
                         ) -> Goodput | None:
    """Histogram-estimated goodput for a summary-only run: finished
    counts from the serve statuses, each latency axis' good fraction
    from the registry's log-bucket histograms, joint as their product
    (flagged estimated). None with nothing to judge."""
    from .metrics import log_bucket_bounds

    serves = [r for r in records if r.get("event") == "serve"]
    if not serves:
        return None
    requests = sum(r.get("requests") or 0 for r in serves)
    finished = sum((r.get("statuses") or {}).get("finished", 0)
                   for r in serves)
    duration = sum(r.get("duration_s") or 0.0 for r in serves)
    snaps: dict[str, dict] = {}
    for rec in records:
        if rec.get("event") == "metrics":
            snaps[run_mode(rec)] = rec  # newest per mode wins
    bounds = log_bucket_bounds()
    good_f = float(finished)
    for obj in latency_objectives(spec, "*"):
        total = 0
        frac = 0.0
        for snap in snaps.values():
            est = hist_good_fraction(
                (snap.get("histograms") or {}).get(f"serve.{obj.metric}",
                                                   {}),
                bounds, obj.threshold_ms)
            if est is not None:
                total += est[0]
                frac += est[0] * est[1]
        if total:
            good_f *= frac / total
    return Goodput(requests=requests, good=int(round(good_f)),
                   duration_s=duration,
                   chips=chips if chips else _chips_from_records(records),
                   estimated=True, thresholds=spec_thresholds(spec))


def goodput_from_records(records: list[dict], spec: SLOSpec,
                         *, chips: int | None = None) -> Goodput | None:
    """Goodput for one run's records: exact from the terminal trail
    when present, histogram estimate otherwise (the health fidelity
    order). None when the file holds nothing judgeable."""
    terminals = collect_terminals(records)
    if terminals:
        durs = _mode_durations(records)
        duration = sum(durs.values()) if durs else max(
            (t for t, _, _ in terminals), default=0.0)
        return goodput_from_terminals(
            terminals, spec, duration_s=duration,
            chips=chips if chips else _chips_from_records(records))
    return goodput_from_summary(records, spec, chips=chips)


def tenant_goodput_rps(records: list[dict], spec: SLOSpec
                       ) -> dict[str, float | None]:
    """Per-tenant attained goodput (requests/s per chip) for `mctpu
    health`'s verdict column — the SAME is_good fold, bucketed by
    tenant. None (em-dash) when the tenant declares no latency
    objectives or the file has no exact terminal trail (the estimate
    path has no per-tenant joint histograms — no estimate beats a
    wrong one, the health convention)."""
    terminals = collect_terminals(records)
    if not terminals:
        return {}
    durs = _mode_durations(records)
    duration = sum(durs.values()) if durs else max(
        (t for t, _, _ in terminals), default=0.0)
    chips = _chips_from_records(records)
    good: dict[str, int] = {}
    for _, _, term in terminals:
        tenant = term.get("tenant") or "default"
        good.setdefault(tenant, 0)
        if is_good(term, spec):
            good[tenant] += 1
    out: dict[str, float | None] = {}
    for tenant, n in sorted(good.items()):
        if not latency_objectives(spec, tenant) or duration <= 0:
            out[tenant] = None
        else:
            out[tenant] = round(n / duration / max(chips, 1), 3)
    return out


def goodput_record(g: Goodput, t: float, *, kind: str,
                   **extra) -> dict:
    """One `goodput` schema-family record (versioned via obs.schema)."""
    return make_record("goodput", t, kind=kind, **g.fields(), **extra)


def default_goodput_spec(ttft_ms: float = 500.0,
                         tpot_ms: float = 50.0) -> SLOSpec:
    """The spec goodput tools apply when no --slo names one: TTFT and
    TPOT thresholds for every tenant (targets are irrelevant to the
    per-request joint — 0.99 is a placeholder the dataclass demands)."""
    from .slo import Objective

    return SLOSpec(tenants={"*": [
        Objective("ttft_ms", 0.99, threshold_ms=float(ttft_ms)),
        Objective("tpot_ms", 0.99, threshold_ms=float(tpot_ms)),
    ]})
