"""obs — the telemetry subsystem.

Four pillars, one record schema:

- `trace`:  spans/annotations with ONE naming scheme across XProf device
            traces and the JSONL metrics stream.
- `cost`:   FLOPs/bytes/collectives of the COMPILED step via XLA cost
            analysis and HLO/jaxpr walks — MFU as a computed property,
            not a hand-typed constant.
- `device`: HBM occupancy/peaks from device.memory_stats(), degrading
            to None on backends without allocator stats.
- `schema`: the versioned JSONL record shape shared by MetricsLogger,
            bench.py, and `mctpu report`; `report` renders any run file
            into the markdown tables PERF.md used to assemble by hand.
"""

from .cost import (  # noqa: F401
    COLLECTIVE_PRIMS,
    PEAK_TFLOPS,
    ProgramCosts,
    analyze,
    hlo_collective_counts,
    jaxpr_collective_counts,
    mfu,
    peak_flops,
    try_analyze,
)
from .device import (  # noqa: F401
    device_memory_stats,
    hbm_peak_bytes,
    memory_snapshot,
)
from .metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    log_bucket_bounds,
    percentiles_from_record,
)
from .report import render_markdown, report_main, summarize  # noqa: F401
from .schema import (  # noqa: F401
    RUN_MARKER,
    SCHEMA_VERSION,
    dump_records,
    iter_records,
    iter_runs,
    load_records,
    make_record,
    validate_record,
)
from .trace import annotate, current_path, span  # noqa: F401
