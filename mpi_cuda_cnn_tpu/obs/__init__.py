"""obs — the telemetry subsystem.

Four pillars, one record schema:

- `trace`:  spans/annotations with ONE naming scheme across XProf device
            traces and the JSONL metrics stream.
- `cost`:   FLOPs/bytes/collectives of the COMPILED step via XLA cost
            analysis and HLO/jaxpr walks — MFU as a computed property,
            not a hand-typed constant.
- `device`: HBM occupancy/peaks from device.memory_stats(), degrading
            to None on backends without allocator stats.
- `schema`: the versioned JSONL record shape shared by MetricsLogger,
            bench.py, and `mctpu report`; `report` renders any run file
            into the markdown tables PERF.md used to assemble by hand.

Plus the SLO layer on top of the schema (ISSUE 8): `slo` (declarative
per-tenant objectives, error budgets, multi-window burn-rate math),
`alerts` (the streaming rule engine whose live and replayed sequences
are bitwise-identical), and `health` (`mctpu health` — per-tenant
verdict tables with a CI exit code).
"""

from .cost import (  # noqa: F401
    COLLECTIVE_PRIMS,
    PEAK_TFLOPS,
    ProgramCosts,
    analyze,
    hlo_collective_counts,
    jaxpr_collective_counts,
    mfu,
    peak_flops,
    try_analyze,
)
from .device import (  # noqa: F401
    device_memory_stats,
    hbm_peak_bytes,
    memory_snapshot,
)
from .metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    log_bucket_bounds,
    percentiles_from_record,
)
from .alerts import AlertEngine, alerts_crc  # noqa: F401
from .health import evaluate as evaluate_health  # noqa: F401
from .health import health_main  # noqa: F401
from .report import render_markdown, report_main, summarize  # noqa: F401
from .slo import Objective, SLOSpec  # noqa: F401
from .schema import (  # noqa: F401
    RUN_MARKER,
    SCHEMA_VERSION,
    dump_records,
    iter_records,
    iter_runs,
    load_records,
    make_record,
    validate_record,
)
from .trace import annotate, current_path, span  # noqa: F401
