"""Tracing spans: one name, visible in BOTH XProf and the JSONL stream.

Two tools, one naming scheme:

- `annotate(name)` — for code under `jax.jit`/`shard_map` tracing: a
  `jax.named_scope` so the region's HLO ops carry the name into XProf /
  TensorBoard device traces. Zero runtime cost (it is metadata on the
  traced ops).
- `span(name, metrics=...)` — for HOST-side regions (epoch loops, eval
  sweeps, checkpoint saves): nests via a stack, wraps
  `jax.profiler.TraceAnnotation` so the host track of an XProf capture
  shows the same name, and on exit emits a {"event": "span"} record to
  the metrics stream. XProf traces and the JSONL therefore agree on
  names — the point of pillar (1) in the obs design.

Span names compose with '/' as they nest: span("epoch") containing
span("eval") emits "epoch/eval". Host spans measure wall-clock only;
they do NOT force device completion (a span around an async dispatch
measures the dispatch, which is exactly the async split StepTimer
accounts for).
"""

from __future__ import annotations

import contextlib
import threading
import time

import jax

_state = threading.local()


def _stack() -> list[str]:
    if not hasattr(_state, "stack"):
        _state.stack = []
    return _state.stack


def current_path() -> str:
    """The '/'-joined path of open host spans on this thread ('' at top)."""
    return "/".join(_stack())


def annotate(name: str):
    """Named scope for traced code — the in-jit half of the span API."""
    return jax.named_scope(name)


@contextlib.contextmanager
def span(name: str, metrics=None, **fields):
    """Host-side named span. Emits one "span" record on exit when a
    metrics logger (utils.logging.MetricsLogger) is passed; always
    annotates the profiler's host track so an XProf capture taken over
    the region shows the same name."""
    stack = _stack()
    stack.append(name)
    path = "/".join(stack)
    t0 = time.perf_counter()
    try:
        with jax.profiler.TraceAnnotation(path):
            yield path
    finally:
        dt_ms = (time.perf_counter() - t0) * 1e3
        popped = stack.pop()
        assert popped == name
        if metrics is not None:
            metrics.log("span", name=path, ms=round(dt_ms, 3), **fields)
