"""Declarative SLOs: per-tenant objectives, error budgets, burn rates.

ROADMAP item 2 (multi-tenant SLO-aware admission/preemption) needs a
measurement layer before any scheduler can act on it: WHAT counts as a
good event for a tenant, HOW MUCH error budget a run has spent, and HOW
FAST it is burning. This module is that layer, deliberately jax-free
and wall-clock-free: every number is a pure fold over terminal-request
events on the run's own timeline (the engine/fleet clock — a FakeClock
in deterministic runs), so two identical-seed runs produce bitwise-
identical SLO verdicts.

The spec is a JSON file in the ci/*_gate.json idiom::

    {"tenants": {"*": {"availability": 0.999,
                       "ttft_ms":  {"target": 0.95, "threshold_ms": 500},
                       "tpot_ms":  {"target": 0.95, "threshold_ms": 100},
                       "queue_wait_ms": {"target": 0.9,
                                         "threshold_ms": 1000}},
                 "t0": {"availability": 0.9999}},
     "burn": {"windows_s": [[60, 5], [300, 30]], "max_rate": 10.0},
     "train": {"loss_spike_pct": 100.0, "max_restarts": 0,
               "max_nonfinite": 0, "step_ms_p99_ms": null},
     "rules": [ ...extra obs.alerts rules... ],
     "max_alerts": 0}

- `tenants` maps a tenant name (or the "*" wildcard every unlisted
  tenant falls back to) to its objectives. `availability` is a bare
  target fraction; the latency objectives pair a target with the
  threshold that separates good from bad.
- `burn` configures multi-window multi-burn-rate alerting (Google SRE
  Workbook ch. 5): each [long_s, short_s] pair fires only when BOTH
  windows burn faster than `max_rate` — the long window filters noise,
  the short window makes the alert reset quickly once the problem
  stops. Burn rate 1.0 = spending exactly the whole error budget over
  the window; `max_rate` is the multiple of that baseline considered
  page-worthy.
- `train` bounds the training-run health rules `mctpu health` applies
  to the `train` event stream.
- `rules` is extra obs.alerts rule specs appended to the burn rules.
- `max_alerts` (optional): a run firing more alerts than this is a
  health violation — CI's "zero expected alerts" contract.

Good/bad classification (`Objective.classify`):

- availability: finished = good; expired/failed/rejected = bad;
  cancelled = not an event (a client abort is not the server's
  failure).
- latency objectives: finished requests only (failures are already
  charged to availability — double-charging them here would make one
  outage burn every budget at once); good iff the measured value is at
  or under the threshold.
"""

from __future__ import annotations

import dataclasses
import json
import math
from collections import deque
from pathlib import Path

# Objective metrics a spec may name. "availability" classifies by
# status; the rest compare a terminal-event latency to a threshold.
LATENCY_METRICS = ("ttft_ms", "tpot_ms", "queue_wait_ms")

DEFAULT_BURN_WINDOWS = ((60.0, 5.0), (300.0, 30.0))
DEFAULT_MAX_BURN = 10.0


@dataclasses.dataclass(frozen=True)
class Objective:
    """One SLO objective: `target` fraction of events must be good.
    threshold_ms separates good from bad for latency metrics; None for
    availability."""

    metric: str
    target: float
    threshold_ms: float | None = None

    def __post_init__(self):
        if self.metric != "availability" and self.metric not in LATENCY_METRICS:
            raise ValueError(
                f"objective metric {self.metric!r}: want 'availability' "
                f"or one of {LATENCY_METRICS}"
            )
        if not (0.0 < self.target < 1.0):
            raise ValueError(
                f"objective {self.metric}: target must be in (0, 1), "
                f"got {self.target}"
            )
        if self.metric != "availability" and self.threshold_ms is None:
            raise ValueError(
                f"objective {self.metric}: latency objectives need "
                "threshold_ms"
            )

    def classify(self, term: dict) -> bool | None:
        """good True / bad False / None (not an event for this
        objective) for one terminal-request field dict (the tick
        `terminal` entry / `request` record shape)."""
        status = term.get("status", "finished")
        if status == "cancelled":
            return None
        if self.metric == "availability":
            return status == "finished"
        if status != "finished":
            return None
        v = term.get(self.metric)
        if v is None:
            # The null-moment convention: a moment that was never
            # measured (pre-ISSUE-6 request records lack queue_wait_ms)
            # is not an event — calling it bad would fail a healthy run.
            return None
        return v <= self.threshold_ms


def budget_remaining(good: int, bad: int, target: float) -> float | None:
    """Fraction of the run's error budget left: 1.0 = untouched, 0.0 =
    exactly exhausted, negative = overspent. The budget is
    (1 - target) * events; None with no events (nothing to judge)."""
    total = good + bad
    if total == 0:
        return None
    allowed = (1.0 - target) * total
    return 1.0 - bad / allowed


class WindowedEvents:
    """Good/bad events on one timeline with sliding-window counts.

    observe() is O(amortized 1) per (event, window); the deques hold
    (t, good) pairs inside each window and evict as time advances. The
    math reads only event times the producer stamped — no clock, no
    randomness — which is what makes burn evaluation replay-identical.
    """

    __slots__ = ("windows_s", "_dq", "_bad", "good", "bad", "max_burn")

    def __init__(self, windows_s):
        # Flat, deduplicated window lengths (a [long, short] pair shares
        # storage with any other pair naming the same length).
        self.windows_s = tuple(sorted({float(w) for pair in windows_s
                                       for w in pair}, reverse=True))
        self._dq = {w: deque() for w in self.windows_s}
        self._bad = {w: 0 for w in self.windows_s}
        self.good = 0
        self.bad = 0
        self.max_burn = {w: 0.0 for w in self.windows_s}

    def observe(self, t: float, good: bool, target: float) -> None:
        self.good += good
        self.bad += not good
        for w in self.windows_s:
            dq = self._dq[w]
            dq.append((t, good))
            self._bad[w] += not good
            while dq and dq[0][0] <= t - w:
                _, g = dq.popleft()
                self._bad[w] -= not g
            self.max_burn[w] = max(self.max_burn[w],
                                   self.burn_rate(w, target))

    def burn_rate(self, window_s: float, target: float) -> float:
        """Error-budget burn multiple over the window: bad fraction
        divided by the budgeted bad fraction (1 - target). 1.0 = the
        budget spends exactly at its sustainable rate."""
        dq = self._dq[window_s]
        if not dq:
            return 0.0
        return (self._bad[window_s] / len(dq)) / (1.0 - target)

    def worst_burn(self) -> float:
        return max(self.max_burn.values(), default=0.0)


class Accountant:
    """Per-(tenant, objective) windowed good/bad accounting — the one
    fold both the streaming burn-rate alert rule (obs.alerts) and the
    end-of-run `mctpu health` verdicts drive, so an alert and the
    verdict that explains it can never disagree on the numbers."""

    def __init__(self, spec: "SLOSpec"):
        self.spec = spec
        # (tenant, metric) -> WindowedEvents
        self.events: dict[tuple[str, str], WindowedEvents] = {}

    def observe(self, term: dict, t: float):
        """Fold one terminal-request field dict at event time `t`;
        yields (tenant, objective, window_events, good) per objective
        the event scored under (the alert rule hooks this)."""
        tenant = term.get("tenant") or "default"
        for obj in self.spec.objectives(tenant):
            good = obj.classify(term)
            if good is None:
                continue
            key = (tenant, obj.metric)
            we = self.events.get(key)
            if we is None:
                we = self.events[key] = WindowedEvents(self.spec.windows)
            we.observe(t, good, obj.target)
            yield tenant, obj, we, good

    def observe_all(self, rec: dict, now: float):
        """Fold every `terminal` entry of one tick record at time
        `now` — the per-record form the streaming burn rule drives."""
        for term in rec.get("terminal") or ():
            yield from self.observe(term, now)

    def tenants(self) -> list[str]:
        return sorted({t for t, _ in self.events})


class SLOSpec:
    """Parsed SLO spec (module docstring grammar)."""

    def __init__(self, *, tenants: dict[str, list[Objective]],
                 windows=DEFAULT_BURN_WINDOWS,
                 max_burn: float = DEFAULT_MAX_BURN,
                 train: dict | None = None, rules: list[dict] | None = None,
                 max_alerts: int | None = None):
        if not tenants:
            raise ValueError("SLO spec: need at least one tenant entry "
                             '("*" covers every tenant)')
        self.tenants = tenants
        self.windows = tuple((float(lo), float(sh)) for lo, sh in windows)
        for lo, sh in self.windows:
            if not (lo > sh > 0):
                raise ValueError(
                    f"burn window [{lo}, {sh}]: want long_s > short_s > 0"
                )
        self.max_burn = float(max_burn)
        self.train = dict(train or {})
        self.rules = list(rules or ())
        self.max_alerts = max_alerts

    def objectives(self, tenant: str) -> list[Objective]:
        """The tenant's objectives (exact entry, else the "*" wildcard,
        else none — an unlisted tenant with no wildcard is not judged)."""
        return self.tenants.get(tenant, self.tenants.get("*", []))

    @classmethod
    def from_dict(cls, spec: dict) -> SLOSpec:
        tenants: dict[str, list[Objective]] = {}
        raw = spec.get("tenants")
        if not isinstance(raw, dict) or not raw:
            raise ValueError(
                'SLO spec: need a non-empty "tenants" object '
                '(use "*" for an all-tenants default)'
            )
        for tenant, objs in raw.items():
            if not isinstance(objs, dict):
                raise ValueError(
                    f"SLO spec: tenant {tenant!r} entry must be an object"
                )
            parsed = []
            for metric, v in objs.items():
                if metric == "availability":
                    parsed.append(Objective("availability", float(v)))
                else:
                    if not isinstance(v, dict):
                        raise ValueError(
                            f"SLO spec: {tenant}.{metric} must be "
                            '{"target": ..., "threshold_ms": ...}'
                        )
                    parsed.append(Objective(
                        metric, float(v["target"]),
                        threshold_ms=float(v["threshold_ms"]),
                    ))
            tenants[tenant] = parsed
        burn = spec.get("burn") or {}
        return cls(
            tenants=tenants,
            windows=burn.get("windows_s", DEFAULT_BURN_WINDOWS),
            max_burn=burn.get("max_rate", DEFAULT_MAX_BURN),
            train=spec.get("train"),
            rules=spec.get("rules"),
            max_alerts=spec.get("max_alerts"),
        )

    @classmethod
    def load(cls, path: str | Path) -> SLOSpec:
        try:
            return cls.from_dict(json.loads(Path(path).read_text()))
        except (KeyError, TypeError, json.JSONDecodeError) as e:
            raise ValueError(f"{path}: bad SLO spec: {e}") from e


def default_spec() -> SLOSpec:
    """The spec `mctpu health` applies with no --slo: availability
    99% for every tenant, no latency objectives (thresholds are
    deployment-specific — declare them), default burn windows."""
    return SLOSpec(tenants={"*": [Objective("availability", 0.99)]})


@dataclasses.dataclass
class Verdict:
    """One (tenant, objective) SLO verdict row."""

    tenant: str
    metric: str
    target: float
    threshold_ms: float | None
    events: int
    good: int
    bad: int
    worst_burn: float | None
    estimated: bool = False  # True when derived from histogram buckets

    @property
    def attainment(self) -> float | None:
        total = self.good + self.bad
        return self.good / total if total else None

    @property
    def budget_left(self) -> float | None:
        return budget_remaining(self.good, self.bad, self.target)

    @property
    def violated(self) -> bool:
        a = self.attainment
        return a is not None and a < self.target


def verdicts_from_terminals(terminals: list[tuple[float, str, dict]],
                            spec: SLOSpec) -> list[Verdict]:
    """Exact verdicts from (event_time, mode, terminal-field) triples —
    the full-log path (tick `terminal` entries or `request` records).

    Accounting is MODE-scoped before merging: a serve-bench file holds
    static and continuous runs of the same workload on two independent
    timelines, and windowed burn math assumes one non-decreasing clock
    — so each mode folds its own Accountant, then the verdict sums the
    good/bad counts and takes the worst burn across modes (the table
    stays per-tenant, as the health contract promises)."""
    accs: dict[str, Accountant] = {}
    for t, mode, term in terminals:
        acc = accs.get(mode)
        if acc is None:
            acc = accs[mode] = Accountant(spec)
        for _ in acc.observe(term, t):
            pass
    merged: dict[tuple[str, str], Verdict] = {}
    for acc in accs.values():
        for (tenant, metric), we in sorted(acc.events.items()):
            obj = next(o for o in spec.objectives(tenant)
                       if o.metric == metric)
            v = merged.get((tenant, metric))
            if v is None:
                v = merged[(tenant, metric)] = Verdict(
                    tenant=tenant, metric=metric, target=obj.target,
                    threshold_ms=obj.threshold_ms, events=0, good=0,
                    bad=0, worst_burn=0.0,
                )
            v.events += we.good + we.bad
            v.good += we.good
            v.bad += we.bad
            v.worst_burn = round(max(v.worst_burn, we.worst_burn()), 3)
    out = [merged[k] for k in sorted(merged)]
    judged = {v.tenant for v in out}
    # Spec-named tenants that saw no traffic still get zero-event rows:
    # a tenant silently receiving nothing is a finding, not a blank.
    for tenant in sorted(set(spec.tenants) - judged - {"*"}):
        for obj in spec.objectives(tenant):
            out.append(Verdict(
                tenant=tenant, metric=obj.metric, target=obj.target,
                threshold_ms=obj.threshold_ms, events=0, good=0, bad=0,
                worst_burn=None,
            ))
    return out


def hist_good_fraction(fields: dict, bounds: list[float],
                       threshold: float) -> tuple[int, float] | None:
    """(total, good fraction) of a Histogram.to_fields() dict against a
    threshold: full buckets at-or-under the threshold count good, the
    straddling bucket contributes linearly (the same interpolation the
    percentile estimator uses). Deterministic; None with no counts."""
    total = fields.get("count", 0)
    if not total:
        return None
    good = 0.0
    for i, c in fields.get("buckets", []):
        lo = bounds[i - 1] if i > 0 else 0.0
        hi = bounds[i] if i < len(bounds) else math.inf
        if hi <= threshold:
            good += c
        elif lo < threshold < hi:
            good += c * (threshold - lo) / (hi - lo)
    return total, good / total


def verdicts_from_summary(records: list[dict],
                          spec: SLOSpec) -> list[Verdict]:
    """Approximate verdicts for a summary-only run (`--log summary`
    storms keep per-tick JSONL out of the file): availability from the
    per-tenant status counts in the `serve` summaries, latency
    attainment ESTIMATED from the registry's log-bucket histograms
    (flagged `estimated` in the table — bucket interpolation, not exact
    counts). Burn rates need the event stream and stay None here.
    Multiple `serve` summaries (serve-bench's two modes) sum; the
    newest `metrics` snapshot per mode contributes its histograms
    (registries are per-mode and cumulative within one)."""
    from .metrics import log_bucket_bounds

    serves = [r for r in records if r.get("event") == "serve"]
    if not serves:
        return []
    statuses: dict[str, dict[str, int]] = {}
    for rec in serves:
        blocks = rec.get("tenants") or {
            "default": {"statuses": rec.get("statuses") or {}},
        }
        for tenant, block in blocks.items():
            per = statuses.setdefault(tenant, {})
            for st, n in (block.get("statuses") or {}).items():
                per[st] = per.get(st, 0) + n
    snaps: dict[str, dict] = {}
    for rec in records:
        if rec.get("event") == "metrics":
            snaps[run_mode(rec)] = rec  # newest per mode wins
    bounds = log_bucket_bounds()
    out = []
    for tenant, per in sorted(statuses.items()):
        for obj in spec.objectives(tenant):
            if obj.metric == "availability":
                good = per.get("finished", 0)
                bad = sum(n for st, n in per.items()
                          if st not in ("finished", "cancelled"))
                out.append(Verdict(
                    tenant=tenant, metric=obj.metric, target=obj.target,
                    threshold_ms=None, events=good + bad, good=good,
                    bad=bad, worst_burn=None,
                ))
                continue
            if tenant == "default" and len(statuses) > 1:
                # Untagged traffic has no per-tenant histogram twin, and
                # the global `serve.*` histogram also holds every TAGGED
                # tenant's observations — estimating "default" from it
                # in a mixed run would dilute the verdict with other
                # tenants' latencies. No estimate beats a wrong one;
                # availability above stays exact.
                continue
            name = (f"serve.tenant.{tenant}.{obj.metric}"
                    if tenant != "default" else f"serve.{obj.metric}")
            total = 0
            good_f = 0.0
            for snap in snaps.values():
                est = hist_good_fraction(
                    (snap.get("histograms") or {}).get(name, {}),
                    bounds, obj.threshold_ms)
                if est is not None:
                    total += est[0]
                    good_f += est[0] * est[1]
            if total == 0:
                continue
            good = int(round(good_f))
            out.append(Verdict(
                tenant=tenant, metric=obj.metric, target=obj.target,
                threshold_ms=obj.threshold_ms, events=total, good=good,
                bad=total - good, worst_burn=None, estimated=True,
            ))
    return out


def run_mode(rec: dict) -> str:
    """A record's run-scope key: every replica of one fleet shares one
    clock, so "fleet/<name>" tick modes fold into the one logical mode
    "fleet" (the obs.timeline convention)."""
    mode = rec.get("mode", "?")
    return "fleet" if isinstance(mode, str) and mode.startswith("fleet/") \
        else mode


def collect_terminals(records: list[dict]) -> list[tuple[float, str, dict]]:
    """(event_time, mode, terminal-fields) triples from one run's
    records.

    Prefers the per-tick `terminal` entries (streamed at the moment the
    request left the system — the same events the live alert engine
    folded); falls back to `request` records (their completion moment
    is arrival_s + latency_ms — the "t" stamp is when the producer
    LOGGED them, usually end of run). tpot for request records is
    derived with the one TPOT formula."""
    ticks = []
    for rec in records:
        if rec.get("event") != "tick":
            continue
        for term in rec.get("terminal") or ():
            ticks.append((rec.get("now", rec.get("t", 0.0)),
                          run_mode(rec), term))
    if ticks:
        return ticks
    out = []
    for rec in records:
        if rec.get("event") != "request":
            continue
        lat, ttft = rec.get("latency_ms"), rec.get("ttft_ms")
        tpot = None
        if (rec.get("status", "finished") == "finished" and lat is not None
                and ttft is not None):
            tpot = (lat - ttft) / max(rec.get("output_tokens", 1) - 1, 1)
        t = (rec.get("arrival_s", 0.0) or 0.0) + (lat or 0.0) / 1e3
        out.append((t, run_mode(rec), {
            "id": rec.get("id"),
            "tenant": rec.get("tenant") or "default",
            "status": rec.get("status", "finished"),
            "ttft_ms": ttft,
            "tpot_ms": tpot,
            "queue_wait_ms": rec.get("queue_wait_ms"),
        }))
    # Events must fold in time order WITHIN each mode: request records
    # are logged in rid order, not completion order, and windowed burn
    # math assumes a non-decreasing timeline.
    out.sort(key=lambda p: (p[1], p[0], p[2].get("id") or 0))
    return out


# -- training health ---------------------------------------------------

# Bounds `train` health rules apply when the spec does not override
# them: any loss doubling step-over-step is a spike, and a healthy CI
# run restarts zero times with zero non-finite steps.
TRAIN_DEFAULTS = {
    "loss_spike_pct": 100.0,
    "max_loss_spikes": 0,
    "max_restarts": 0,
    "max_nonfinite": 0,
    "step_ms_p99_ms": None,
}


@dataclasses.dataclass
class TrainVerdict:
    rule: str
    value: float | None
    bound: float | None
    violated: bool
    detail: str | None = None


def train_health(records: list[dict], spec: SLOSpec) -> list[TrainVerdict]:
    """Health rules over the training event stream: loss-spike count,
    step_ms p99 against a declared ceiling, restart and non-finite-step
    rates from the fault trail. Returns [] for runs with no train
    records (a serving file is not judged as a training run)."""
    from .metrics import Histogram

    trains = [r for r in records if r.get("event") == "train"]
    if not trains:
        return []
    cfg = {**TRAIN_DEFAULTS, **spec.train}
    out = []

    losses = [(r.get("step"), r["loss"]) for r in trains
              if isinstance(r.get("loss"), (int, float))]
    spikes = []
    for (_, prev), (step, cur) in zip(losses, losses[1:]):
        if prev > 0 and (cur - prev) / prev * 100.0 > cfg["loss_spike_pct"]:
            spikes.append(step)
    out.append(TrainVerdict(
        rule=f"loss_spike (> +{cfg['loss_spike_pct']:g}% per interval)",
        value=len(spikes), bound=cfg["max_loss_spikes"],
        violated=len(spikes) > cfg["max_loss_spikes"],
        detail=f"at steps {spikes}" if spikes else None,
    ))

    faults = [r for r in records if r.get("event") == "fault"]
    restarts = sum(1 for r in faults if r.get("kind") == "restart")
    nonfinite = sum(1 for r in faults if r.get("kind") == "nonfinite_step")
    out.append(TrainVerdict(
        rule="restarts", value=restarts, bound=cfg["max_restarts"],
        violated=restarts > cfg["max_restarts"],
    ))
    out.append(TrainVerdict(
        rule="nonfinite_steps", value=nonfinite, bound=cfg["max_nonfinite"],
        violated=nonfinite > cfg["max_nonfinite"],
    ))

    if cfg["step_ms_p99_ms"] is not None:
        snap = next((r for r in reversed(records)
                     if r.get("event") == "metrics"
                     and "train.step_ms" in (r.get("histograms") or {})),
                    None)
        p99 = None
        if snap is not None:
            h = Histogram.from_fields(snap["histograms"]["train.step_ms"])
            p99 = h.percentile(99)
        out.append(TrainVerdict(
            rule="step_ms_p99", value=None if p99 is None else round(p99, 3),
            bound=cfg["step_ms_p99_ms"],
            violated=p99 is not None and p99 > cfg["step_ms_p99_ms"],
        ))
    return out
