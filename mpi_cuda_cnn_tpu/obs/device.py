"""Device telemetry: HBM occupancy/peaks via `device.memory_stats()`.

The numbers PERF.md's memory claims were previously read off profiler
screenshots or inferred from OOMs. `memory_stats()` is the allocator's
own accounting (bytes_in_use, peak_bytes_in_use, ...); TPU and GPU
backends expose it, CPU returns None — every function here degrades to
None/empty rather than raising, so telemetry can be threaded through
trainers unconditionally.
"""

from __future__ import annotations

import jax

# The allocator keys we record (when present). peak_bytes_in_use is the
# one that answers "does this config fit"; bytes_in_use the steady state.
_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
         "largest_alloc_size")


def device_memory_stats(device) -> dict | None:
    """This device's allocator stats, or None when the backend has none."""
    stats_fn = getattr(device, "memory_stats", None)
    if stats_fn is None:
        return None
    try:
        stats = stats_fn()
    except Exception:
        return None
    if not stats:
        return None
    return {k: int(stats[k]) for k in _KEYS if k in stats}


def memory_snapshot(devices=None) -> list[dict]:
    """One entry per device: {"id", "platform", "stats": {...} | null}.
    The "memory" event's `devices` field (obs.schema)."""
    out = []
    for d in devices or jax.devices():
        out.append({
            "id": d.id,
            "platform": d.platform,
            "stats": device_memory_stats(d),
        })
    return out


def emit_step_telemetry(metrics, timer, steps: int, *, devices=None,
                        **fields) -> None:
    """Emit the per-interval telemetry record pair — "step_phases" (the
    timer's per-step phase attribution) and "memory" (a device
    snapshot) — to `metrics` when its JSONL sink is open. The ONE emit
    path both trainers share, so the record shapes cannot drift."""
    if metrics is None or not metrics.jsonl_enabled or steps <= 0:
        return
    metrics.log("step_phases", steps=steps, phases_ms=timer.phases_ms(),
                **fields)
    metrics.log("memory", devices=memory_snapshot(devices), **fields)


def hbm_peak_bytes(devices=None) -> int | None:
    """Max peak_bytes_in_use across devices; None when no device
    exposes stats (CPU) — callers emit null, tests skip cleanly."""
    peaks = [
        e["stats"]["peak_bytes_in_use"]
        for e in memory_snapshot(devices)
        if e["stats"] and "peak_bytes_in_use" in e["stats"]
    ]
    return max(peaks) if peaks else None
