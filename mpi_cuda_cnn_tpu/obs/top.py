"""`mctpu top` — a live terminal dashboard over a metrics JSONL.

Tails a run file (live, while a bench/trainer writes it) or replays a
finished one, and renders the engine/trainer gauges refreshing in
place: queue depth with a recent-history sparkline, running/prefilling
slots, free pages, chunked-prefill backlog, counter totals, and the
latency-histogram percentiles from the newest `metrics` snapshot. This
is the single-process precursor of the fleet router's replica view
(ROADMAP item 4): the same records, one engine instead of N.

Deliberately jax-free (imports only obs.schema/obs.metrics/obs.alerts):
`top` must run on any machine that can read the file, including while
the training process owns every accelerator.

Modes:
- default: follow — re-read appended records every --refresh seconds,
  redraw in place; Ctrl-C exits.
- --once:  ingest the whole file, print ONE frame without ANSI control
  codes, exit (the test/CI path — also what you want in a pipe).
- --replay: step through a finished file frame by frame at --refresh
  per frame (a tape of the run, slowed down to watchable).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from collections import deque
from pathlib import Path

from .alerts import format_alert
from .metrics import percentiles_from_record
from .schema import RUN_MARKER, fmt_cell, validate_record

_SPARK = "▁▂▃▄▅▆▇█"


def sparkline(values, width: int = 24) -> str:
    """Last `width` values as block characters, scaled to their max."""
    vals = list(values)[-width:]
    if not vals:
        return ""
    hi = max(max(vals), 1e-9)
    return "".join(_SPARK[min(int(v / hi * (len(_SPARK) - 1)), 7)]
                   for v in vals)


def bar(value, hi, width: int = 16) -> str:
    """A [####....] gauge bar of value against its running max."""
    if value is None:
        return " " * (width + 2)
    hi = max(hi if hi else value, value, 1e-9)
    n = int(round(value / hi * width))
    return "[" + "#" * n + "." * (width - n) + "]"


class TopState:
    """Aggregated view of the records seen so far (one run)."""

    def __init__(self, history: int = 48):
        self.records = 0
        self.t = 0.0
        self.metrics: dict[str, dict] = {}   # newest snapshot per label
        self.tick: dict[str, dict] = {}      # newest tick per mode
        self.queue_hist: dict[str, deque] = {}
        self.train: dict | None = None
        self.epochs = 0
        self.epoch_s = None
        self.serve: dict[str, dict] = {}
        self.faults: dict[str, int] = {}
        self.fleet: dict | None = None       # newest fleet-router tick
        self.pending_hist: deque = deque(maxlen=history)
        self.replica_kinds: dict[str, int] = {}
        # ROUTER panel (ISSUE 18): newest per-replica cumulative
        # [routed hits, dispatches] split (cache_aware fleet records
        # only) and the live-replica-count trail the scale-event
        # sparkline renders from.
        self.route: dict[str, list] | None = None
        self.replicas_hist: deque = deque(maxlen=history)
        # Alert stream (ISSUE 8): rolling recent window + per-rule and
        # per-severity totals for the ALERTS panel.
        self.alerts_recent: deque = deque(maxlen=6)
        self.alerts_total = 0
        self.alerts_by_rule: dict[str, int] = {}
        self.alerts_by_sev: dict[str, int] = {}
        # Per-replica free-pages high-water (an empty replica's free
        # count = its pool size): the fixed scale its pressure bar
        # renders against.
        self.free_hi: dict[str, float] = {}
        # Host-tier occupancy high-water per mode (ISSUE 17): the scale
        # the host-tier bar renders against until the serve record's
        # host_pages stamp gives the true capacity.
        self.tier_hi: dict[str, float] = {}
        # TOP-BLOCKERS (ISSUE 11): ticks each holder rid kept a blocked
        # admission waiting (joint attribution over the tick records'
        # `blocked` entries), plus the block-reason mix.
        self.blockers: dict[int, int] = {}
        self.block_reasons: dict[str, int] = {}
        # GOODPUT (ISSUE 16): autosize sweep candidates in arrival
        # order, plus the newest frontier summary record.
        self.goodput_cands: deque = deque(maxlen=8)
        self.goodput_frontier: dict | None = None
        # TRANSPORT panel (ISSUE 20): newest per-tick bus block from
        # the fleet records (cumulative counters + live partitions),
        # running lease-refusal/retransmit-marker totals, and the
        # partition open/heal lifecycle counts.
        self.transport: dict | None = None
        self.lease_refused = 0
        self.transport_kinds: dict[str, int] = {}
        self._history = history

    def reset(self) -> None:
        self.__init__(self._history)

    def ingest(self, rec: dict) -> None:
        self.records += 1
        self.t = max(self.t, rec.get("t", 0.0) or 0.0)
        ev = rec.get("event")
        if ev == "metrics":
            self.metrics[rec.get("mode", "train")] = rec
        elif ev == "tick":
            mode = rec.get("mode", "?")
            self.tick[mode] = rec
            self.queue_hist.setdefault(
                mode, deque(maxlen=self._history)
            ).append(rec.get("queue", 0))
            hu = (rec.get("prefix") or {}).get("host_used")
            if hu is not None:
                self.tier_hi[mode] = max(self.tier_hi.get(mode, 0.0), hu)
            for entry in rec.get("blocked") or []:
                rid, reason, holders = entry[0], entry[1], entry[2]
                self.block_reasons[reason] = \
                    self.block_reasons.get(reason, 0) + 1
                for h in holders:
                    self.blockers[h] = self.blockers.get(h, 0) + 1
        elif ev == "train":
            self.train = rec
        elif ev == "epoch":
            self.epochs += 1
            self.epoch_s = rec.get("seconds")
        elif ev == "serve":
            self.serve[rec.get("mode", "?")] = rec
        elif ev == "fault":
            kind = rec.get("kind", "?")
            self.faults[kind] = self.faults.get(kind, 0) + 1
        elif ev == "fleet":
            self.fleet = rec
            self.pending_hist.append(rec.get("pending", 0))
            self.replicas_hist.append(rec.get("replicas", 0))
            if rec.get("transport") is not None:
                self.transport = rec["transport"]
            self.lease_refused += len(rec.get("lease_refused") or [])
            if rec.get("route") is not None:
                self.route = rec["route"]
            for name, triple in (rec.get("load") or {}).items():
                free = (triple + [None, None, None])[2]
                if free is not None:
                    self.free_hi[name] = max(self.free_hi.get(name, 0.0),
                                             free)
        elif ev == "replica":
            kind = rec.get("kind", "?")
            self.replica_kinds[kind] = self.replica_kinds.get(kind, 0) + 1
        elif ev == "transport":
            kind = rec.get("kind", "?")
            self.transport_kinds[kind] = \
                self.transport_kinds.get(kind, 0) + 1
        elif ev == "goodput":
            if rec.get("kind") == "frontier":
                self.goodput_frontier = rec
            else:  # candidate / run measurements stream in live
                self.goodput_cands.append(rec)
        elif ev == "alert":
            self.alerts_total += 1
            self.alerts_recent.append(rec)
            rule = rec.get("rule", "?")
            sev = rec.get("severity", "?")
            self.alerts_by_rule[rule] = self.alerts_by_rule.get(rule, 0) + 1
            self.alerts_by_sev[sev] = self.alerts_by_sev.get(sev, 0) + 1


def _fmt(v) -> str:
    # 4 significant digits, not the tables' 6 — a refreshing dashboard
    # column must not jitter in width.
    return fmt_cell(v, prec=4)


def _pcts(snap: dict, name: str) -> str:
    p = percentiles_from_record(snap, name)
    if p["p50"] is None:
        return "—"
    return "/".join(_fmt(p[k]) for k in ("p50", "p95", "p99"))


def render(state: TopState, path: str, width: int = 96) -> str:
    """One dashboard frame (pure string — no ANSI; callers position)."""
    lines = [f"mctpu top — {path}  records={state.records}  "
             f"t={state.t:.2f}s"]
    for mode in sorted(set(state.tick) | set(m for m in state.metrics
                                             if m != "train")):
        if mode == "fleet" or mode.startswith("fleet/"):
            continue  # fleet + per-replica ticks render in FLEET below
        tk = state.tick.get(mode, {})
        snap = state.metrics.get(mode, {})
        counters = snap.get("counters", {})
        gauges = snap.get("gauges", {})
        free = tk.get("free_pages")
        free_hi = (gauges.get("serve.free_pages") or {}).get("hi")
        lines.append("")
        lines.append(
            f"ENGINE [{mode}]  tick {_fmt(tk.get('tick'))}  "
            f"queue {_fmt(tk.get('queue')):>4} "
            f"{sparkline(state.queue_hist.get(mode, []))}"
        )
        lines.append(
            f"  running {_fmt(tk.get('running'))}  "
            f"prefilling {_fmt(tk.get('prefilling'))}  "
            f"free pages {_fmt(free)} {bar(free, free_hi)}  "
            f"backlog {_fmt(tk.get('backlog'))} tok"
        )
        # Always-on health counts (ISSUE 7 satellite): the engine has
        # counted these since ISSUE 4/6 but the panel never showed them
        # — a zero is information (nothing preempted, no slow ticks).
        lines.append(
            f"  preemptions {_fmt(counters.get('serve.preemptions', 0))}  "
            "watchdog-slow "
            f"{_fmt(counters.get('serve.watchdog_slow_ticks', 0))}"
        )
        pfx = tk.get("prefix")
        if pfx:
            # Prefix-cache panel (ISSUE 9): hit/COW/evict totals plus
            # shared / LRU-retained / free page bars — the residency
            # picture behind the hit rate.
            total = pfx.get("hits", 0) + pfx.get("misses", 0)
            rate = pfx.get("hits", 0) / total if total else 0.0
            pool_hi = (gauges.get("serve.free_pages") or {}).get("hi")
            lines.append(
                f"  prefix: hit rate {rate:.0%} "
                f"({_fmt(pfx.get('hit_tokens'))} tok)  "
                f"cow {_fmt(pfx.get('cow_copies'))}  "
                f"evict {_fmt(pfx.get('evictions'))}  "
                f"shared {_fmt(pfx.get('shared_pages'))} "
                f"{bar(pfx.get('shared_pages'), pool_hi, width=8)} "
                f"lru {_fmt(pfx.get('retained_pages'))} "
                f"{bar(pfx.get('retained_pages'), pool_hi, width=8)} "
                f"free {_fmt(free)} {bar(free, pool_hi, width=8)}"
            )
        if pfx and "host_used" in pfx:
            # Host-tier panel (ISSUE 17): spilled-page occupancy bar
            # against the tier capacity (the serve record's host_pages
            # stamp, or the running high-water while the run is live)
            # plus the spill/readmit/refusal/eviction totals.
            cap = ((state.serve.get(mode) or {}).get("host_pages")
                   or state.tier_hi.get(mode))
            lines.append(
                f"  host tier: used {_fmt(pfx.get('host_used'))} "
                f"{bar(pfx.get('host_used'), cap, width=10)}  "
                f"spill {_fmt(pfx.get('spills'))}  "
                f"readmit {_fmt(pfx.get('readmits'))}  "
                f"refused {_fmt(pfx.get('refusals'))}  "
                f"host-evict {_fmt(pfx.get('host_evictions'))}"
            )
        if counters:
            lines.append(
                "  totals: "
                + "  ".join(
                    f"{k.removeprefix('serve.')} {_fmt(v)}"
                    for k, v in counters.items()
                    if k.startswith("serve.")
                )
            )
        if snap.get("histograms"):
            lines.append(
                f"  ms p50/p95/p99 — ttft {_pcts(snap, 'serve.ttft_ms')}"
                f"  tpot {_pcts(snap, 'serve.tpot_ms')}"
                f"  queue-wait {_pcts(snap, 'serve.queue_wait_ms')}"
            )
        sv = state.serve.get(mode)
        if sv:
            lines.append(
                f"  final: {_fmt(sv.get('tokens_per_s'))} tok/s  "
                f"ticks {_fmt(sv.get('decode_ticks'))}  "
                f"preempt {_fmt(sv.get('preemptions'))}  "
                f"wd-slow {_fmt(sv.get('watchdog_slow_ticks'))}  "
                f"statuses {json.dumps(sv.get('statuses'))}"
            )
    if state.fleet is not None or state.replica_kinds:
        fl = state.fleet or {}
        lines.append("")
        lines.append(
            f"FLEET  tick {_fmt(fl.get('tick'))}  "
            f"replicas {_fmt(fl.get('replicas'))}  "
            f"pending {_fmt(fl.get('pending')):>5} "
            f"{sparkline(state.pending_hist)}"
            # Disaggregated serving (ISSUE 13): KV transfers in flight.
            + (f"  handoffs-inflight {fl['handoffs_inflight']}"
               if fl.get("handoffs_inflight") is not None else "")
        )
        # Per-replica load rows: what least-loaded dispatch reads —
        # queue depth, occupied slots, free pages — plus each replica's
        # recent queue sparkline from its own tick trail.
        load = fl.get("load") or {}
        for name in sorted(load):
            q, running, free = (load[name] + [None, None, None])[:3]
            hist = state.queue_hist.get(f"fleet/{name}", [])
            lines.append(
                f"  {name:<4} queue {_fmt(q):>4} {sparkline(hist, 16):<16} "
                f"running {_fmt(running)}  free pages {_fmt(free)} "
                f"{bar(free, state.free_hi.get(name), width=10)}"
            )
        if state.replica_kinds:
            lines.append("  lifecycle: " + "  ".join(
                f"{k}:{v}" for k, v in sorted(state.replica_kinds.items())))
        if state.route is not None:
            # ROUTER panel (ISSUE 18): per-replica routed-hit-rate bars
            # (cumulative routed hits / dispatches — where cache-aware
            # scoring is landing its overlap wins) plus the scale-event
            # trail: live replica count sparkline + applied up/down
            # totals from the lifecycle stream.
            sv = state.serve.get("fleet") or {}
            rh, rm = sv.get("route_hits"), sv.get("route_misses")
            tot = (rh or 0) + (rm or 0)
            lines.append(
                "  ROUTER  "
                + (f"routed {rh}/{tot} ({100.0 * rh / tot:.0f}%)  "
                   f"hit tokens {_fmt(sv.get('route_hit_tokens'))}"
                   if tot else "routing live")
            )
            for name in sorted(state.route):
                hits, disp = (state.route[name] + [0, 0])[:2]
                frac = hits / disp if disp else 0.0
                lines.append(
                    f"    {name:<4} hits {_fmt(hits):>5}/{_fmt(disp):<5} "
                    f"{bar(frac, 1.0, width=16)} {frac:.0%}"
                )
            ups = state.replica_kinds.get("scale_up", 0)
            downs = state.replica_kinds.get("scale_down", 0)
            if ups or downs or len(state.replicas_hist) > 1:
                lines.append(
                    f"  SCALE  ups {ups}  downs {downs}  replicas "
                    f"{sparkline(state.replicas_hist)} "
                    f"now {_fmt(fl.get('replicas'))}"
                )
        sv0 = state.serve.get("fleet") or {}
        if state.transport is not None or sv0.get("msgs_sent") is not None:
            # TRANSPORT panel (ISSUE 20): the lossy bus live — per-tick
            # cumulative counters from the fleet records (full log),
            # falling back to the run summary's msgs_* totals.
            t = state.transport or {
                "sent": sv0.get("msgs_sent"),
                "delivered": sv0.get("msgs_delivered"),
                "dropped": sv0.get("msgs_dropped"),
                "duped": sv0.get("msgs_duped"),
                "deduped": sv0.get("msgs_deduped"),
                "retransmits": sv0.get("retransmits"),
                "partitions": sv0.get("partitions"),
                "inflight": 0, "unacked": 0, "links": [],
                "partitioned": [],
            }
            lines.append(
                f"  TRANSPORT  sent {_fmt(t['sent'])}  "
                f"delivered {_fmt(t['delivered'])}  "
                f"dropped {_fmt(t['dropped'])}  duped {_fmt(t['duped'])}  "
                f"deduped {_fmt(t['deduped'])}  "
                f"retransmits {_fmt(t['retransmits'])}"
            )
            open_p = t.get("partitioned") or []
            lines.append(
                f"    wire inflight {_fmt(t['inflight'])}  "
                f"unacked {_fmt(t['unacked'])}  "
                f"links {len(t.get('links') or [])}  "
                f"partitions {_fmt(t['partitions'])}"
                + ("  OPEN: " + ", ".join(f"{n} heals@{u}"
                                          for n, u in open_p)
                   if open_p else "")
                + f"  lease refused "
                  f"{state.lease_refused or sv0.get('lease_refusals') or 0}"
            )
            if state.transport_kinds:
                lines.append("    lifecycle: " + "  ".join(
                    f"{k}:{v}"
                    for k, v in sorted(state.transport_kinds.items())))
        snap = state.metrics.get("fleet", {})
        if snap.get("counters"):
            lines.append(
                "  totals: "
                + "  ".join(
                    f"{k.removeprefix('fleet.')} {_fmt(v)}"
                    for k, v in snap["counters"].items()
                    if k.startswith("fleet.")
                )
            )
        if snap.get("histograms"):
            lines.append(
                f"  ms p50/p95/p99 — ttft {_pcts(snap, 'serve.ttft_ms')}"
                f"  tpot {_pcts(snap, 'serve.tpot_ms')}"
                f"  queue-wait {_pcts(snap, 'serve.queue_wait_ms')}"
            )
        sv = state.serve.get("fleet")
        if sv:
            lines.append(
                f"  final: {_fmt(sv.get('tokens_per_s'))} tok/s  "
                f"dispatches {_fmt(sv.get('dispatches'))}  "
                f"redispatches {_fmt(sv.get('redispatches'))}  "
                f"fenced {_fmt(sv.get('fenced_discards'))}  "
                f"statuses {json.dumps(sv.get('statuses'))}"
            )
    if state.goodput_cands or state.goodput_frontier:
        # GOODPUT (ISSUE 16): the autosize sweep as it streams — most
        # recent candidates with their SLO-attained per-chip rate, then
        # the frontier's recommendation once the sweep folds.
        lines.append("")
        fr = state.goodput_frontier or {}
        lines.append(
            "GOODPUT  evaluated "
            f"{_fmt(fr.get('evaluated', len(state.goodput_cands)))}"
            + (f"  pruned {_fmt(fr['pruned'])}" if fr.get("pruned")
               else "")
            + (f"  seeded {fr['seeded_from']}" if fr.get("seeded_from")
               else "")
        )
        for r in state.goodput_cands:
            est = " est" if r.get("estimated") else ""
            lines.append(
                f"  {r.get('cand', 'run'):<36} "
                f"good {_fmt(r.get('good')):>5}/{_fmt(r.get('requests'))}"
                f"  {_fmt(r.get('per_chip_rps'))} r/s/chip{est}  "
                f"ttft p99 {_fmt(r.get('ttft_p99_ms'))}  "
                f"tpot p99 {_fmt(r.get('tpot_p99_ms'))}"
            )
        if fr.get("recommendation"):
            lines.append(
                f"  ➤ recommend {fr['recommendation']}  "
                f"{_fmt(fr.get('best_per_chip_rps'))} good r/s/chip  "
                f"crc {_fmt(fr.get('recommendation_crc'))}"
            )
    snap = state.metrics.get("train")
    if state.train or snap or state.epochs:
        tr = state.train or {}
        lines.append("")
        lines.append(
            f"TRAIN  step {_fmt(tr.get('step'))}  "
            f"loss {_fmt(tr.get('loss'))}  epochs {state.epochs}"
            + (f"  last epoch {_fmt(state.epoch_s)}s" if state.epoch_s
               else "")
        )
        if snap:
            c, g = snap.get("counters", {}), snap.get("gauges", {})
            tps = (g.get("train.tokens_per_s") or {}).get("value")
            lines.append(
                f"  heartbeats {_fmt(c.get('train.heartbeats'))}  "
                f"restarts {_fmt(c.get('train.restarts'))}  "
                f"steps {_fmt(c.get('train.steps'))}"
                + (f"  tokens/s {_fmt(tps)}" if tps is not None else "")
            )
            if snap.get("histograms"):
                lines.append(
                    f"  step ms p50/p95/p99 {_pcts(snap, 'train.step_ms')}"
                )
    if state.blockers:
        # TOP-BLOCKERS (ISSUE 11): who is holding admissions up RIGHT
        # NOW — the live twin of `mctpu explain`'s blocker table.
        top = sorted(state.blockers.items(),
                     key=lambda kv: (-kv[1], kv[0]))[:8]
        lines.append("")
        lines.append(
            "TOP BLOCKERS  blocked-attempt ticks by holder — "
            + "  ".join(f"rid {rid}:{n}" for rid, n in top)
        )
        lines.append("  reasons: " + "  ".join(
            f"{k}:{v}" for k, v in sorted(state.block_reasons.items())))
    if state.alerts_total:
        # ALERTS panel (ISSUE 8): totals plus the rolling tail — the
        # live view of what the streaming rule engine fired so far.
        lines.append("")
        lines.append(
            f"ALERTS  fired {state.alerts_total}  "
            + "  ".join(f"{k}:{v}"
                        for k, v in sorted(state.alerts_by_sev.items()))
        )
        lines.append("  rules: " + "  ".join(
            f"{k}:{v}" for k, v in sorted(state.alerts_by_rule.items())))
        for a in state.alerts_recent:
            # ONE alert-line spelling, shared with `mctpu health`
            # (obs.alerts.format_alert — jax-free like this module).
            lines.append("  " + format_alert(a))
    if state.faults:
        lines.append("")
        lines.append("FAULTS  " + "  ".join(
            f"{k}:{v}" for k, v in sorted(state.faults.items())))
    return "\n".join(line[:width] for line in lines)


def _parse_line(line: str):
    """(is_run_marker, record | None) — the tail-follow twin of
    schema._iter_lines, tolerant of torn/partial writes."""
    line = line.strip()
    if line.startswith(RUN_MARKER):
        return True, None
    if not line or line.startswith("#"):
        return False, None
    try:
        rec = json.loads(line)
    except json.JSONDecodeError:
        return False, None
    if isinstance(rec, dict) and "schema" in rec:
        try:
            validate_record(rec)
        except ValueError:
            return False, None
    return False, rec if isinstance(rec, dict) else None


def top_main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="mctpu top",
        description="Live dashboard over a metrics JSONL: tail a "
                    "running bench/trainer (default), print one frame "
                    "(--once), or replay a finished run (--replay).",
    )
    ap.add_argument("path", help="metrics JSONL to tail")
    ap.add_argument("--refresh", type=float, default=0.5,
                    help="seconds between redraws (follow/replay)")
    ap.add_argument("--once", action="store_true",
                    help="ingest everything, print one frame, exit "
                         "(no ANSI — safe in pipes/CI)")
    ap.add_argument("--replay", action="store_true",
                    help="replay a finished file one frame per "
                         "--refresh instead of tailing")
    ap.add_argument("--frames", type=int, default=0,
                    help="stop after N redraws (0 = until Ctrl-C / "
                         "end of replay) — the bounded-session escape "
                         "hatch for scripts")
    ap.add_argument("--width", type=int, default=110)
    args = ap.parse_args(argv)

    path = Path(args.path)
    if not path.exists():
        print(f"error: {path}: no such file", file=sys.stderr)
        return 2
    state = TopState()

    if args.once or args.replay:
        with path.open() as fh:
            lines = fh.readlines()
        if args.once:
            for line in lines:
                marker, rec = _parse_line(line)
                if marker:
                    state.reset()  # frame shows the file's LAST run
                elif rec is not None:
                    state.ingest(rec)
            print(render(state, str(path), width=args.width))
            return 0
        # Replay: one frame per tick/metrics record batch.
        frames = 0
        for line in lines:
            marker, rec = _parse_line(line)
            if marker:
                state.reset()
                continue
            if rec is None:
                continue
            state.ingest(rec)
            if rec.get("event") in ("tick", "metrics", "train", "epoch"):
                sys.stdout.write("\x1b[2J\x1b[H"
                                 + render(state, str(path),
                                          width=args.width) + "\n")
                sys.stdout.flush()
                frames += 1
                if args.frames and frames >= args.frames:
                    return 0
                time.sleep(args.refresh)
        print(render(state, str(path), width=args.width))
        return 0

    # Follow: poll for appended complete lines, redraw in place.
    frames = 0
    buf = ""
    try:
        with path.open() as fh:
            while True:
                chunk = fh.read()
                if chunk:
                    buf += chunk
                    *complete, buf = buf.split("\n")
                    for line in complete:
                        marker, rec = _parse_line(line)
                        if marker:
                            state.reset()
                        elif rec is not None:
                            state.ingest(rec)
                sys.stdout.write("\x1b[2J\x1b[H"
                                 + render(state, str(path),
                                          width=args.width) + "\n")
                sys.stdout.flush()
                frames += 1
                if args.frames and frames >= args.frames:
                    return 0
                time.sleep(args.refresh)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(top_main())
