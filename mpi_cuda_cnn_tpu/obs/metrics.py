"""Runtime metrics: counters, gauges, and log-bucket histograms.

The JSONL substrate (obs.schema) records *moments*; nothing aggregated
them at runtime — the serving engine emitted per-request records with
no queue-depth time series and no percentile accounting, and a live
`mctpu top` had nothing to tail. This registry is that aggregation
layer, deliberately jax-free and wall-clock-free in its MATH:

- `Counter`:   monotonically increasing totals (decode ticks, tokens
               emitted, restarts, heartbeats).
- `Gauge`:     last-set values with a running min/max (queue depth,
               free pages, tokens/s).
- `Histogram`: fixed LOG-SPACED buckets (Prometheus-style cumulative-
               free counts): observation math is pure arithmetic on the
               observed value — no clock reads, no randomness — so a
               registry driven by a faults.FakeClock produces bitwise-
               identical snapshots run to run. Percentiles are
               estimated by linear interpolation inside the bucket
               (upper-bound conservative at the tail).

The injectable `clock` is used ONLY to stamp snapshot records ("t" on
the emitted `metrics` event) — never inside aggregation — which is what
makes telemetry tests deterministic under FakeClock (the PR-4
contract).

Snapshots are schema-validated `metrics` events; `mctpu top` tails
them, `mctpu report` summarizes them, and `mctpu compare` gates their
named values against a baseline.
"""

from __future__ import annotations

import math

from .schema import make_record, validate_record

# Default histogram range: 10 us .. ~100 s in milliseconds terms
# (1e-2 ms .. 1e5 ms) at 10 buckets/decade — wide enough for TTFT and
# step times alike; values outside land in the open edge buckets.
DEFAULT_LO = 1e-2
DEFAULT_HI = 1e5
BUCKETS_PER_DECADE = 10


def pct_nearest(vals: list[float], q: float) -> float | None:
    """Nearest-rank percentile (no interpolation): conservative at the
    tail on small request counts. THE serving percentile convention —
    serve/engine.ServeResult.summary(), the fleet summary, and `mctpu
    report`'s per-request table all use this one function, so they can
    never disagree on identical data. Lives here (not obs/report.py)
    so the jax-free scheduler/fleet layer can import it without
    pulling report's cost-analysis stack (`mctpu lint` MCT001)."""
    s = sorted(vals)
    if not s:
        return None
    i = min(len(s) - 1, max(0, -(-int(q) * len(s) // 100) - 1))
    return round(s[i], 3)


def log_bucket_bounds(lo: float = DEFAULT_LO, hi: float = DEFAULT_HI,
                      per_decade: int = BUCKETS_PER_DECADE) -> list[float]:
    """Upper bounds of log-spaced buckets covering [lo, hi]. The bounds
    are a pure function of (lo, hi, per_decade) — every producer and
    consumer derives the same edges, so bucket counts are comparable
    across runs without shipping the edges in every record."""
    if not (lo > 0 and hi > lo):
        raise ValueError(f"need 0 < lo < hi, got lo={lo} hi={hi}")
    n = int(round(math.log10(hi / lo) * per_decade))
    return [lo * 10 ** (i / per_decade) for i in range(1, n + 1)]


class Counter:
    """A monotonically increasing total."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counter increment must be >= 0, got {amount}")
        self.value += amount


class Gauge:
    """Last-set value with a running min/max envelope (the envelope is
    what `mctpu top` scales its bars against)."""

    __slots__ = ("value", "lo", "hi")

    def __init__(self):
        self.value = None
        self.lo = None
        self.hi = None

    def set(self, value: float) -> None:
        value = float(value)
        self.value = value
        self.lo = value if self.lo is None else min(self.lo, value)
        self.hi = value if self.hi is None else max(self.hi, value)


class Histogram:
    """Fixed log-spaced-bucket histogram with exact count/sum/min/max.

    `bounds` are bucket UPPER bounds (ascending); observations above
    the last bound land in a final overflow bucket, at-or-below the
    first bound in bucket 0. Deterministic: observing the same sequence
    of values yields identical state — no clock, no sampling.
    """

    __slots__ = ("bounds", "counts", "count", "sum", "min", "max")

    def __init__(self, bounds: list[float] | None = None):
        self.bounds = list(bounds) if bounds is not None \
            else log_bucket_bounds()
        if any(b <= a for a, b in zip(self.bounds, self.bounds[1:])):
            raise ValueError("histogram bounds must be ascending")
        self.counts = [0] * (len(self.bounds) + 1)
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None

    def observe(self, value: float) -> None:
        value = float(value)
        # Binary search would be O(log n); n is ~70 and observe runs on
        # the host between ticks — linear keeps it obvious.
        for i, b in enumerate(self.bounds):
            if value <= b:
                self.counts[i] += 1
                break
        else:
            self.counts[-1] += 1
        self.count += 1
        self.sum += value
        self.min = value if self.min is None else min(self.min, value)
        self.max = value if self.max is None else max(self.max, value)

    def percentile(self, q: float) -> float | None:
        """Estimate the q-th percentile (0..100) from bucket counts by
        linear interpolation inside the winning bucket, clamped to the
        exact observed min/max (so p0/p100 are never estimates)."""
        if self.count == 0:
            return None
        rank = max(1, math.ceil(q / 100.0 * self.count))
        seen = 0
        for i, c in enumerate(self.counts):
            if c == 0:
                continue
            if seen + c >= rank:
                lo = self.bounds[i - 1] if i > 0 else (
                    self.min if self.min is not None else 0.0)
                hi = self.bounds[i] if i < len(self.bounds) else self.max
                frac = (rank - seen) / c
                est = lo + (hi - lo) * frac
                return min(max(est, self.min), self.max)
            seen += c
        return self.max

    def to_fields(self) -> dict:
        """The compact record form: sparse nonzero buckets as
        [index, count] pairs (a 70-bucket histogram with 5 live buckets
        ships 5 pairs, not 70 zeros)."""
        return {
            "count": self.count,
            "sum": round(self.sum, 4),
            "min": self.min if self.min is None else round(self.min, 4),
            "max": self.max if self.max is None else round(self.max, 4),
            "buckets": [[i, c] for i, c in enumerate(self.counts) if c],
        }

    @classmethod
    def from_fields(cls, fields: dict,
                    bounds: list[float] | None = None) -> Histogram:
        """Rebuild from to_fields() output — the consumer half used by
        `mctpu top`/report to compute percentiles from a record."""
        h = cls(bounds)
        for i, c in fields.get("buckets", []):
            h.counts[i] = int(c)
        h.count = int(fields.get("count", sum(h.counts)))
        h.sum = float(fields.get("sum", 0.0))
        h.min = fields.get("min")
        h.max = fields.get("max")
        return h


class MetricsRegistry:
    """One process's named counters/gauges/histograms + snapshotting.

    `clock` has the time.perf_counter call shape and is read ONLY when a
    snapshot is stamped; aggregation (inc/set/observe) never touches it,
    which is the determinism contract tests pin under faults.FakeClock.
    """

    def __init__(self, *, clock=None):
        import time

        self._clock = clock if clock is not None else time.perf_counter
        self._t0 = self._clock()
        self.counters: dict[str, Counter] = {}
        self.gauges: dict[str, Gauge] = {}
        self.histograms: dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        return self.counters.setdefault(name, Counter())

    def gauge(self, name: str) -> Gauge:
        return self.gauges.setdefault(name, Gauge())

    def histogram(self, name: str,
                  bounds: list[float] | None = None) -> Histogram:
        h = self.histograms.get(name)
        if h is None:
            h = self.histograms[name] = Histogram(bounds)
        return h

    # -- convenience single-call forms ---------------------------------

    def inc(self, name: str, amount: float = 1.0) -> None:
        self.counter(name).inc(amount)

    def set(self, name: str, value: float) -> None:
        self.gauge(name).set(value)

    def observe(self, name: str, value: float | None) -> None:
        """None observations are skipped (aborted requests carry null
        where a moment never happened — the serving convention)."""
        if value is not None:
            self.histogram(name).observe(value)

    # -- snapshotting --------------------------------------------------

    def snapshot_fields(self, **extra) -> dict:
        """The `metrics` event's fields (no schema/event/t stamp)."""
        return {
            "counters": {k: round(c.value, 6)
                         for k, c in sorted(self.counters.items())},
            "gauges": {k: {"value": g.value, "lo": g.lo, "hi": g.hi}
                       for k, g in sorted(self.gauges.items())},
            "histograms": {k: h.to_fields()
                           for k, h in sorted(self.histograms.items())},
            **extra,
        }

    def snapshot(self, **extra) -> dict:
        """A schema-validated `metrics` record stamped with the
        injectable clock (the only clock read in this module)."""
        rec = make_record("metrics", self._clock() - self._t0,
                          **self.snapshot_fields(**extra))
        return validate_record(rec)

    def emit(self, metrics, **extra) -> None:
        """Log one snapshot through a MetricsLogger when its JSONL sink
        is open (the trainers' cheap-no-sink discipline)."""
        if metrics is not None and metrics.jsonl_enabled:
            metrics.log("metrics", **self.snapshot_fields(**extra))


def percentiles_from_record(rec: dict, name: str,
                            qs=(50, 95, 99)) -> dict[str, float | None]:
    """p50/p95/p99 (by default) of one named histogram inside a
    `metrics` record — the consumer-side helper top/report share."""
    fields = rec.get("histograms", {}).get(name)
    if not fields:
        return {f"p{q}": None for q in qs}
    h = Histogram.from_fields(fields)
    return {f"p{q}": h.percentile(q) for q in qs}
