"""`mctpu health RUN [--slo slo.json]` — run health verdicts.

One command that turns a finished run file plus a declarative SLO spec
into a per-tenant verdict table — attainment vs target, error budget
remaining, worst burn rate seen, alerts fired — and an exit code CI can
gate on: 0 healthy, 1 violated, 2 config/file error. Training runs get
the train-stream health rules (loss spikes, restart / non-finite-step
rates, step_ms p99 ceiling) in the same invocation.

Alert cross-check (--verify-alerts): the file's SLO-derived alert
sequence is REPLAYED from the records (obs.alerts' pure-fold contract)
and, when the file carries live alert records from a full-log run, the
two sequences must match CRC-exactly — the alert-path twin of `mctpu
trace`'s lifecycle cross-check: telemetry drifting from what its own
records imply is a failure, not a rendering choice. Opt-in because it
is only meaningful when --slo names the SAME spec the live run used (a
different spec legitimately replays a different sequence); summary-only
files (`--log summary` storms) skip it even when asked — their live
alerts were fed from sink records the file deliberately omits.

Verdict sources, in order of fidelity:

1. per-tick `terminal` entries / `request` records — exact good/bad
   counts and burn rates (obs.slo.verdicts_from_terminals);
2. summary-only fallback — availability from per-tenant status counts,
   latency attainment estimated from the registry's log-bucket
   histograms (rows flagged `est`).

Like `mctpu compare`, the LAST run segment of an append-mode file is
the one judged.
"""

from __future__ import annotations

import argparse
import json
import sys

from .alerts import AlertEngine, alerts_crc, format_alert
from .goodput import tenant_goodput_rps
from .schema import fmt_cell as _fmt
from .schema import iter_runs
from .slo import (
    SLOSpec,
    collect_terminals,
    default_spec,
    train_health,
    verdicts_from_summary,
    verdicts_from_terminals,
)


def evaluate(records: list[dict], spec: SLOSpec,
             verify_alerts: bool = False) -> dict:
    """One run's health evaluation (the JSON output shape)."""
    terminals = collect_terminals(records)
    if terminals:
        verdicts = verdicts_from_terminals(terminals, spec)
        source = "events"
    else:
        verdicts = verdicts_from_summary(records, spec)
        source = "summary" if verdicts else "none"

    engine = AlertEngine(slo=spec)
    replayed = engine.replay(records)
    # Projection keeps the CRC identity keys AND the per-kind context
    # (field/family/metric/value...) the rendered alert lines name.
    live = [{k: v for k, v in r.items()
             if k not in ("schema", "event", "t")}
            for r in records if r.get("event") == "alert"]
    has_ticks = any(r.get("event") == "tick" for r in records)
    live_crc = alerts_crc(live) if live else None
    crc_checked = verify_alerts and bool(live) and has_ticks
    crc_ok = (live_crc == engine.crc) if crc_checked else None
    # The alert set the verdicts judge: live records when the file
    # carries alerts the replay cannot reproduce (a `--log summary`
    # storm fed the live engine from sink records the file omits —
    # replaying such a file finds nothing, and a max_alerts gate that
    # only counted the replay would wave through the very alerts the
    # file shows). With a tick trail, replay and live must agree
    # (--verify-alerts pins it) and the replay is authoritative.
    judged = live if (live and not has_ticks) else replayed
    judged_crc = live_crc if (live and not has_ticks) else engine.crc

    alerts_by_tenant: dict[str, int] = {}
    for a in judged:
        key = a.get("tenant") or a.get("group") or "-"
        alerts_by_tenant[str(key)] = alerts_by_tenant.get(str(key), 0) + 1

    # Per-tenant SLO-attained goodput (obs/goodput.py, ISSUE 16): the
    # verdict table's capacity column — requests/s per chip whose
    # latency objectives ALL held. Exact-trail only; {} (em-dash
    # column) on summary-only files.
    tenant_rps = tenant_goodput_rps(records, spec)

    trains = train_health(records, spec)
    if source == "none" and trains:
        source = "train"
    violations = [f"{v.tenant}/{v.metric}" for v in verdicts if v.violated]
    violations += [f"train:{t.rule}" for t in trains if t.violated]
    if crc_ok is False:
        violations.append("alert_crc_mismatch")
    if spec.max_alerts is not None and len(judged) > spec.max_alerts:
        violations.append(f"alerts_fired>{spec.max_alerts}")
    return {
        "source": source,
        "verdicts": verdicts,
        "train": trains,
        "alerts": judged,
        "alerts_fired": len(judged),
        "alerts_crc": judged_crc,
        "alert_crc_checked": crc_checked,
        "alert_crc_ok": crc_ok,
        "alerts_by_tenant": alerts_by_tenant,
        "tenant_goodput": tenant_rps,
        "violations": violations,
        "healthy": not violations,
    }


def render_verdicts(ev: dict) -> str:
    lines = []
    if ev["verdicts"]:
        lines += [
            "| tenant | objective | events | good | bad | attainment "
            "| target | budget left | worst burn | goodput r/s "
            "| alerts | verdict |",
            "|---|---|---|---|---|---|---|---|---|---|---|---|",
        ]
        for v in ev["verdicts"]:
            obj = v.metric + (f"<={v.threshold_ms:g}ms"
                              if v.threshold_ms is not None else "")
            att = v.attainment
            lines.append(
                f"| {v.tenant} | {obj}{' (est)' if v.estimated else ''} "
                f"| {v.events} | {v.good} | {v.bad} "
                f"| {_fmt(None if att is None else round(att, 6))} "
                f"| {v.target:g} "
                f"| {_fmt(None if v.budget_left is None else round(v.budget_left, 4))} "
                f"| {_fmt(v.worst_burn)} "
                f"| {_fmt(ev['tenant_goodput'].get(v.tenant))} "
                f"| {ev['alerts_by_tenant'].get(v.tenant, 0)} "
                f"| {'VIOLATED' if v.violated else 'ok'} |"
            )
        lines.append("")
    if ev["train"]:
        lines += ["| train rule | value | bound | verdict |",
                  "|---|---|---|---|"]
        for t in ev["train"]:
            lines.append(
                f"| {t.rule} | {_fmt(t.value)} | {_fmt(t.bound)} "
                f"| {'VIOLATED' if t.violated else 'ok'}"
                f"{' — ' + t.detail if t.detail else ''} |"
            )
        lines.append("")
    crc_note = ""
    if ev["alert_crc_checked"]:
        crc_note = (" (live record cross-check: "
                    + ("ok" if ev["alert_crc_ok"] else "MISMATCH") + ")")
    lines.append(f"alerts fired: {ev['alerts_fired']}  "
                 f"crc: {ev['alerts_crc']}{crc_note}")
    for a in ev["alerts"][:20]:
        lines.append("  " + format_alert(a))
    if len(ev["alerts"]) > 20:
        lines.append(f"  ... {len(ev['alerts']) - 20} more")
    return "\n".join(lines)


def health_main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="mctpu health",
        description="Per-tenant SLO verdicts + alert replay for a "
                    "finished run file; exit 1 on violation (the CI "
                    "health gate), 2 on config/file errors.",
    )
    ap.add_argument("path", help="metrics JSONL run file")
    ap.add_argument("--slo", default=None,
                    help="SLO spec JSON (obs.slo grammar); default: "
                         "99%% availability per tenant, no latency "
                         "objectives")
    ap.add_argument("--verify-alerts", action="store_true",
                    help="cross-check the file's live alert records "
                         "against a replay under THIS spec (CRC exact; "
                         "mismatch is a violation) — use when --slo is "
                         "the same spec the run's --slo used")
    ap.add_argument("--format", choices=("md", "json"), default="md")
    args = ap.parse_args(argv)

    try:
        spec = SLOSpec.load(args.slo) if args.slo else default_spec()
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    try:
        runs = [r for r in iter_runs(args.path) if r]
    except (OSError, ValueError) as e:
        print(f"error: {args.path}: {e}", file=sys.stderr)
        return 2
    if not runs:
        print(f"error: {args.path}: no records", file=sys.stderr)
        return 2
    ev = evaluate(runs[-1], spec, verify_alerts=args.verify_alerts)
    if ev["source"] == "none" and not ev["train"]:
        print(f"error: {args.path}: no serving or training records to "
              "judge", file=sys.stderr)
        return 2
    if args.format == "json":
        print(json.dumps({
            "path": args.path,
            "source": ev["source"],
            "healthy": ev["healthy"],
            "violations": ev["violations"],
            "alerts_fired": ev["alerts_fired"],
            "alerts_crc": ev["alerts_crc"],
            "alert_crc_ok": ev["alert_crc_ok"],
            "tenant_goodput": ev["tenant_goodput"],
            "verdicts": [
                {"tenant": v.tenant, "metric": v.metric,
                 "events": v.events, "good": v.good, "bad": v.bad,
                 "attainment": v.attainment, "target": v.target,
                 "budget_left": v.budget_left,
                 "worst_burn": v.worst_burn, "estimated": v.estimated,
                 "violated": v.violated}
                for v in ev["verdicts"]
            ],
            "train": [
                {"rule": t.rule, "value": t.value, "bound": t.bound,
                 "violated": t.violated}
                for t in ev["train"]
            ],
            "alerts": ev["alerts"],
        }))
    else:
        print(f"## Health — {args.path} [{ev['source']}]\n")
        print(render_verdicts(ev))
        if not ev["healthy"]:
            print(f"\nUNHEALTHY: {', '.join(ev['violations'])}")
    return 0 if ev["healthy"] else 1


if __name__ == "__main__":
    sys.exit(health_main())
