"""`mctpu autosize` — blame-seeded offline goodput-frontier search.

The fleet has every elastic mechanism but every topology was still
hand-picked; PERF.md's disagg tables show sizing is the whole game
(1:3 beats 2:2 on both axes at the banked mix). This is the decision
layer ROADMAP item 2(a) names: the offline capacity search DistServe
(PAPERS.md) runs — enumerate candidate topologies at a fixed chip
budget, run each as a seeded SimCompute storm, score by SLO-attained
goodput (obs/goodput.py), fold into a goodput frontier, recommend the
top candidate. Splitwise's production-shaped heavy-tail mixes enter as
the `--len-dist` sweep axis.

Everything is deterministic by construction: the candidate list is a
pure function of the flags (and, under --seed-from, of the blame
profile read from a finished run), every storm runs on a FakeClock
with the seeded workload regenerated per candidate, and the frontier
and recommendation are CRC-stamped like trace/blame/state — two runs
with identical (seed, spec) produce bitwise-identical output, which
is exactly what CI's autosize determinism gate compares at 0%/equal.

Blame seeding (`--seed-from RUN`): the run's `mctpu explain` blame
profile (its `blame` record) says WHERE latency ticks went, and each
dominant category implies which part of the topology space is worth
searching:

- handoff_wait dominant  -> the decode pool is starving KV adoptions:
  keep unified + decode-heavy splits (decode > prefill), drop the
  rest;
- queued_behind dominant -> admission/batch-bound: pool fragmentation
  is the suspect, keep unified + balanced splits (|P - D| <= 1);
- preempted_by dominant  -> memory pressure on the decode side: keep
  unified + splits with decode >= prefill.

The pruned sweep evaluates measurably fewer candidates than the
exhaustive one while selecting the same recommendation (pinned by
test) — the point of reading telemetry before burning sweep compute.

This module is jax-free (ci/lint_manifest.json): the storms run
SimCompute replicas — device-free pure-token compute — which is what
makes a 10^5-request what-if sweep cheap enough to run on a laptop.
"""

from __future__ import annotations

import argparse
import json
import sys
import zlib

from .goodput import (
    default_goodput_spec,
    goodput_from_terminals,
    goodput_record,
    spec_thresholds,
)
from .schema import fmt_cell as _fmt
from .schema import RUN_MARKER, iter_runs, make_record, validate_record
from .slo import SLOSpec, collect_terminals

# Blame categories a dominance read considers, in tie-break priority
# order (a tie is resolved toward the earlier entry — deterministic).
SEED_CATEGORIES = ("handoff_wait", "queued_behind", "preempted_by")


def candidate_topologies(budget: int) -> list[tuple[str, dict | None]]:
    """The exhaustive topology list at a fixed chip budget: the unified
    fleet plus every prefill:decode split. Order is deterministic
    (unified first, then prefill-ascending) — the exhaustive
    evaluation order."""
    topos: list[tuple[str, dict | None]] = [("unified", None)]
    for p in range(1, budget):
        topos.append((f"{p}:{budget - p}",
                      {"prefill": p, "decode": budget - p}))
    return topos


def blame_profile(records: list[dict]) -> dict | None:
    """The newest `blame` record's per-category totals, or None."""
    for rec in reversed(records):
        if rec.get("event") == "blame":
            return dict(rec.get("categories") or {})
    return None


def dominant_category(categories: dict) -> str | None:
    """The dominant seed category of a blame profile (None when every
    considered category is zero — nothing to seed from)."""
    best = max(SEED_CATEGORIES,
               key=lambda c: (categories.get(c, 0) or 0,
                              -SEED_CATEGORIES.index(c)))
    return best if (categories.get(best, 0) or 0) > 0 else None


def seeded_topologies(budget: int, dominant: str | None
                      ) -> list[tuple[str, dict | None]]:
    """Order + prune the topology list from a blame dominance read
    (module docstring rules). No dominance -> exhaustive."""
    topos = candidate_topologies(budget)
    if dominant is None:
        return topos
    unified = [t for t in topos if t[1] is None]
    splits = [t for t in topos if t[1] is not None]
    if dominant == "handoff_wait":
        keep = [t for t in splits if t[1]["decode"] > t[1]["prefill"]]
    elif dominant == "queued_behind":
        keep = [t for t in splits
                if abs(t[1]["prefill"] - t[1]["decode"]) <= 1]
    else:  # preempted_by
        keep = [t for t in splits if t[1]["decode"] >= t[1]["prefill"]]
    # Decode-heaviest first: the blame said the decode side is where
    # capacity decides, so the most likely winners run first.
    keep.sort(key=lambda t: (-t[1]["decode"], t[0]))
    return unified + keep


# Tri-state sweep-axis flags resolved to candidate values: "both"
# sweeps, anything else pins. Values listed off-first so evaluation
# order (and thus candidate numbering) is deterministic.
_PREFIX_AXIS = {"off": [False], "on": [True], "both": [False, True]}
_SPEC_AXIS = {"off": ["off"], "lookup": ["lookup"],
              "both": ["off", "lookup"]}
_LEN_AXIS = {"uniform": ["uniform"], "lognormal": ["lognormal"],
             "both": ["uniform", "lognormal"]}
_SCHED_AXIS = {"fcfs": ["fcfs"], "slo": ["slo"],
               "both": ["fcfs", "slo"]}
_SPILL_AXIS = {"off": [False], "on": [True], "both": [False, True]}


def run_candidate(args, spec: SLOSpec, *, pools: dict | None,
                  scheduler: str, prefix: bool, spec_mode: str,
                  len_dist: str, spill: bool = False) -> dict:
    """One candidate topology as a seeded SimCompute storm — the SAME
    fleet construction fleet-bench uses (defaults and all), so the
    storm's trace/blame/state CRCs are unchanged by the sweep harness
    (pinned by test). Returns the flat candidate row."""
    from ..faults import FakeClock
    from .causal import BlameAccumulator
    from .metrics import MetricsRegistry
    # The one sanctioned non-jax-free import: serve/fleet.py is
    # transitively jax-free on the SimCompute path (EngineCompute's
    # engine import is lazy) but hosts the engine-compute factory too,
    # so it stays outside the manifest; the sim-only use here is the
    # same deliberate exception faults.py documents for its jax sites.
    from ..serve.fleet import (  # mctpu: disable=MCT001
        Fleet,
        SimCompute,
        make_fleet_workload,
    )
    from ..serve.pool import pages_for
    from ..serve.scheduler import SLOPolicy

    budget = args.budget
    max_len = args.prompt_max + args.out_max
    pages = args.pages or args.slots * pages_for(max_len,
                                                 args.page_size) + 1
    reqs = make_fleet_workload(
        n=args.requests, vocab=args.vocab, prompt_min=args.prompt_min,
        prompt_max=args.prompt_max, out_min=args.out_min,
        out_max=args.out_max, rate=args.rate, seed=args.seed,
        deadline_s=args.deadline_ms / 1e3, tenants=args.tenants,
        len_dist=len_dist, prefix_mix=args.prefix_mix,
        templates=args.templates,
    )
    clock = FakeClock()
    registry = MetricsRegistry(clock=clock)
    blame = BlameAccumulator()
    fleet = Fleet(
        lambda name: SimCompute(vocab=args.vocab,
                                chunk=args.prefill_chunk,
                                salt=args.seed),
        replicas=budget, slots=args.slots, num_pages=pages,
        page_size=args.page_size, max_len=max_len,
        policy="least_loaded", heartbeat_miss=3, backoff_base=0.05,
        max_flaps=3, redispatch="resume", tick_s=args.tick_ms / 1e3,
        check_every=16, clock=clock, registry=registry,
        fleet_sink=blame.ingest_fleet,
        replica_tick_sink=blame.ingest_tick,
        prefix=prefix,
        sched_policy=(SLOPolicy(slo_spec=spec) if scheduler == "slo"
                      else None),
        spec=spec_mode, spec_k=8, spec_ngram=2,
        pools=dict(pools) if pools else None, handoff_ticks=1,
        log_handoffs=False,
        host_pages=(args.host_pages or pages) if spill else 0,
    )
    result = fleet.run(reqs)
    s = result.summary()
    bf = blame.summary_fields("fleet")
    terminals = collect_terminals(
        [{"event": "request", **r} for r in result.request_records()])
    g = goodput_from_terminals(terminals, spec,
                               duration_s=s["duration_s"], chips=budget)
    topo = (f"{pools['prefill']}:{pools['decode']}" if pools
            else "unified")
    return {
        "cand": "/".join((topo, scheduler, len_dist,
                          "prefix" if prefix else "noprefix", spec_mode)
                         + (("spill",) if spill else ())),
        "topology": topo,
        "scheduler": scheduler,
        "prefix": prefix,
        "spec": spec_mode,
        "spill": spill,
        "len_dist": len_dist,
        **g.fields(),
        "finished": (s.get("statuses") or {}).get("finished", 0),
        "tokens_per_s": s["tokens_per_s"],
        "ttft_p99_ms": s["ttft_p99_ms"],
        "tpot_p99_ms": s["tpot_p99_ms"],
        "trace_crc": s["trace_crc"],
        "blame_crc": bf["crc"],
        "state_crc": s["state_crc"],
    }


def _rank_key(row: dict):
    """Frontier order: per-chip goodput desc, then TPOT p99 asc, TTFT
    p99 asc, then candidate spelling — total and deterministic."""
    inf = float("inf")
    per = row.get("per_chip_rps")
    return (-(per if per is not None else -inf),
            row.get("tpot_p99_ms") if row.get("tpot_p99_ms") is not None
            else inf,
            row.get("ttft_p99_ms") if row.get("ttft_p99_ms") is not None
            else inf,
            row["cand"])


def _crc(obj) -> int:
    return zlib.crc32(json.dumps(obj, sort_keys=True).encode())


def sweep(args, spec: SLOSpec, dominant: str | None) -> dict:
    """Run the whole sweep; returns {rows, frontier, recommendation,
    ...} — a pure function of (args, spec, dominant)."""
    topos = seeded_topologies(args.budget, dominant)
    exhaustive = len(candidate_topologies(args.budget))
    axes = []
    for ldist in _LEN_AXIS[args.len_dist]:
        for sched in _SCHED_AXIS[args.schedulers]:
            for pfx in _PREFIX_AXIS[args.prefix]:
                for spm in _SPEC_AXIS[args.spec]:
                    for spl in _SPILL_AXIS[args.spill]:
                        if spl and not pfx:
                            # The host tier spills prefix-tree pages;
                            # spill-on/prefix-off has nothing to spill.
                            continue
                        axes.append((ldist, sched, pfx, spm, spl))
    rows = []
    for topo, pools in topos:
        for ldist, sched, pfx, spm, spl in axes:
            rows.append(run_candidate(
                args, spec, pools=pools, scheduler=sched, prefix=pfx,
                spec_mode=spm, len_dist=ldist, spill=spl))
    ranked = sorted(rows, key=_rank_key)
    rec = ranked[0] if ranked else None
    return {
        "rows": rows,
        "ranked": ranked,
        "recommendation": rec,
        "evaluated": len(rows),
        "pruned": (exhaustive - len(topos)) * len(axes),
        "seeded_from": dominant,
        "frontier_crc": _crc(ranked),
        "recommendation_crc": _crc(rec),
        "thresholds": spec_thresholds(spec),
    }


def render_frontier(res: dict, args) -> str:
    """The frontier + recommendation as markdown (what PERF.md's
    capacity-planning section banks)."""
    lines = [
        f"## Goodput frontier — budget {args.budget} chips, "
        f"{args.requests} requests @ {args.rate:g} req/s, seed "
        f"{args.seed}",
        "",
        "thresholds: " + ", ".join(
            f"{k}<={v:g}ms" for k, v in res["thresholds"].items()),
        "",
        "| rank | topology | sched | len dist | prefix | spec | spill "
        "| good | good frac | per-chip r/s | tok/s | TTFT p99 ms "
        "| TPOT p99 ms |",
        "|---|---|---|---|---|---|---|---|---|---|---|---|---|",
    ]
    for i, r in enumerate(res["ranked"], 1):
        lines.append(
            f"| {i} | {r['topology']} | {r['scheduler']} "
            f"| {r['len_dist']} | {'on' if r['prefix'] else 'off'} "
            f"| {r['spec']} | {'on' if r.get('spill') else 'off'} "
            f"| {r['good']} | {_fmt(r['good_fraction'])} "
            f"| {_fmt(r['per_chip_rps'])} | {_fmt(r['tokens_per_s'])} "
            f"| {_fmt(r['ttft_p99_ms'])} | {_fmt(r['tpot_p99_ms'])} |"
        )
    lines.append("")
    rec = res["recommendation"]
    if rec is not None:
        lines.append(
            f"recommendation: {rec['cand']} — "
            f"{_fmt(rec['per_chip_rps'])} good req/s/chip "
            f"({rec['good']}/{rec['requests']} attained)"
        )
    seeded = res["seeded_from"]
    lines.append(
        f"evaluated {res['evaluated']} candidates"
        + (f" (blame-seeded on {seeded}: pruned {res['pruned']})"
           if seeded else " (exhaustive)")
    )
    lines.append(f"frontier crc: {res['frontier_crc']}  "
                 f"recommendation crc: {res['recommendation_crc']}")
    return "\n".join(lines)


def emit_records(res: dict, path: str) -> None:
    """Append the sweep as `goodput` schema records (one run segment:
    candidates in evaluation order, then the frontier summary) — the
    file `mctpu report`/`top`/`compare` consume and the CI determinism
    gate diffs."""
    from pathlib import Path

    g_fields = ("requests", "good", "duration_s", "chips",
                "goodput_rps", "per_chip_rps", "good_fraction",
                "estimated", "thresholds")
    with Path(path).open("a") as fh:
        fh.write(f"{RUN_MARKER} mctpu autosize\n")
        t = 0.0
        for row in res["rows"]:
            t = max(t, row["duration_s"])
            fh.write(json.dumps(validate_record(make_record(
                "goodput", row["duration_s"], kind="candidate",
                **row))) + "\n")
        rec = res["recommendation"]
        fh.write(json.dumps(validate_record(make_record(
            "goodput", t, kind="frontier",
            evaluated=res["evaluated"], pruned=res["pruned"],
            seeded_from=res["seeded_from"],
            order=[r["cand"] for r in res["ranked"]],
            recommendation=None if rec is None else rec["cand"],
            **({f"best_{k}": rec[k] for k in g_fields}
               if rec is not None else {}),
            frontier_crc=res["frontier_crc"],
            recommendation_crc=res["recommendation_crc"]))) + "\n")


def autosize_main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="mctpu autosize",
        description="Offline goodput-frontier capacity search over "
                    "seeded SimCompute fleets: candidate topologies at "
                    "a fixed chip budget, scored by SLO-attained "
                    "goodput, optionally ordered/pruned by a finished "
                    "run's blame profile (--seed-from). Deterministic: "
                    "identical (seed, spec) runs produce bitwise-"
                    "identical frontiers, CRC-stamped.",
    )
    ap.add_argument("--budget", type=int, default=4,
                    help="chips (sim replicas) every candidate spends")
    ap.add_argument("--requests", type=int, default=2000)
    ap.add_argument("--rate", type=float, default=200.0,
                    help="Poisson arrival rate, fleet-clock req/s")
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--page-size", type=int, default=16)
    ap.add_argument("--pages", type=int, default=0,
                    help="pages per replica (0 = size for slots "
                         "full-length sequences)")
    ap.add_argument("--prefill-chunk", type=int, default=32)
    ap.add_argument("--vocab", type=int, default=512)
    ap.add_argument("--prompt-min", type=int, default=8)
    ap.add_argument("--prompt-max", type=int, default=96)
    ap.add_argument("--out-min", type=int, default=8)
    ap.add_argument("--out-max", type=int, default=96)
    ap.add_argument("--deadline-ms", type=float, default=0.0)
    ap.add_argument("--tenants", type=int, default=0)
    ap.add_argument("--tick-ms", type=float, default=1.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--len-dist", default="uniform",
                    choices=["uniform", "lognormal", "both"],
                    help="workload length mix axis (both = sweep the "
                         "uniform AND heavy-tail mixes)")
    ap.add_argument("--schedulers", default="fcfs",
                    choices=["fcfs", "slo", "both"],
                    help="per-replica batching policy axis")
    ap.add_argument("--prefix", default="off",
                    choices=["off", "on", "both"],
                    help="prefix-sharing KV cache axis")
    ap.add_argument("--spec", default="off",
                    choices=["off", "lookup", "both"],
                    help="speculative decoding axis")
    ap.add_argument("--spill", default="off",
                    choices=["off", "on", "both"],
                    help="host-tier prefix-cache spill axis (spill-on "
                         "candidates require the prefix axis on; the "
                         "spill-on/prefix-off combos are skipped)")
    ap.add_argument("--host-pages", type=int, default=0,
                    help="host-tier capacity for spill-on candidates "
                         "(0 = match the device pool size)")
    ap.add_argument("--prefix-mix", type=float, default=0.0,
                    help="fraction of requests sharing a workload "
                         "prefix template (what gives the prefix and "
                         "spill axes something to hit)")
    ap.add_argument("--templates", type=int, default=0,
                    help="seeded shared-prefix template pool size "
                         "(0 = legacy two-template mix; default "
                         "workload CRCs are bitwise-unchanged)")
    ap.add_argument("--slo", default=None,
                    help="SLO spec JSON (obs.slo grammar) whose latency "
                         "objectives define goodput; default: "
                         "--ttft-ms/--tpot-ms thresholds")
    ap.add_argument("--ttft-ms", type=float, default=500.0,
                    help="TTFT threshold when no --slo names a spec")
    ap.add_argument("--tpot-ms", type=float, default=50.0,
                    help="TPOT threshold when no --slo names a spec")
    ap.add_argument("--seed-from", default=None,
                    help="finished run JSONL whose blame profile "
                         "(`mctpu explain` categories) orders and "
                         "prunes the topology sweep")
    ap.add_argument("--metrics-jsonl", default=None,
                    help="append `goodput` records here (candidates + "
                         "frontier — what the CI determinism gate "
                         "compares)")
    ap.add_argument("--format", choices=("md", "json"), default="md")
    args = ap.parse_args(argv)

    if args.budget < 2:
        print(f"error: --budget {args.budget}: a capacity search over "
              "one chip has nothing to decide (want >= 2)",
              file=sys.stderr)
        return 2
    if args.spill == "on" and args.prefix == "off":
        print("error: --spill on needs the prefix axis (--prefix "
              "on/both): the host tier spills prefix-tree pages",
              file=sys.stderr)
        return 2
    try:
        spec = (SLOSpec.load(args.slo) if args.slo
                else default_goodput_spec(args.ttft_ms, args.tpot_ms))
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    dominant = None
    if args.seed_from:
        try:
            runs = [r for r in iter_runs(args.seed_from) if r]
        except (OSError, ValueError) as e:
            print(f"error: {args.seed_from}: {e}", file=sys.stderr)
            return 2
        profile = blame_profile(runs[-1]) if runs else None
        if profile is None:
            print(f"error: {args.seed_from}: no blame record to seed "
                  "from (run fleet-bench, or `mctpu explain` the file "
                  "first)", file=sys.stderr)
            return 2
        dominant = dominant_category(profile)

    res = sweep(args, spec, dominant)
    if args.metrics_jsonl:
        emit_records(res, args.metrics_jsonl)
    if args.format == "json":
        print(json.dumps({
            "budget": args.budget, "seed": args.seed,
            "seeded_from": res["seeded_from"],
            "evaluated": res["evaluated"], "pruned": res["pruned"],
            "thresholds": res["thresholds"],
            "frontier": res["ranked"],
            "recommendation": res["recommendation"],
            "frontier_crc": res["frontier_crc"],
            "recommendation_crc": res["recommendation_crc"],
        }))
    else:
        print(render_frontier(res, args))
    return 0


if __name__ == "__main__":
    sys.exit(autosize_main())
