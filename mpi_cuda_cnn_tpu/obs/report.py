"""Aggregate a metrics JSONL run into the tables PERF.md used to get by
hand.

`mctpu report run.jsonl` (or `python scripts/obs_report.py run.jsonl`)
reads any file of schema records (obs.schema — pre-schema lines pass
through, '#' comments skip) and renders per-event summary tables:
training trajectory, epoch wall-clocks, step-phase attribution,
compiled-program accounting (FLOPs, bytes, collectives, MFU when a peak
is known), device-memory peaks, and host spans. JSON output (--format
json) feeds scripts; markdown is for pasting into PERF.md.
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
from collections.abc import Iterable

from .cost import mfu, peak_flops
from .metrics import Histogram, pct_nearest
from .schema import fmt_cell as _fmt
from .schema import iter_runs


def _merge_hist_fields(a: dict, b: dict) -> dict:
    """Sum two Histogram.to_fields() dicts (same implied bucket edges —
    obs.metrics.log_bucket_bounds): bucket counts added index-wise,
    count/sum added, min/max enveloped. The cross-segment half of
    --merge: one restarted process's histogram continues the other's."""
    counts = {i: c for i, c in a.get("buckets", [])}
    for i, c in b.get("buckets", []):
        counts[i] = counts.get(i, 0) + c
    mins = [m for m in (a.get("min"), b.get("min")) if m is not None]
    maxs = [m for m in (a.get("max"), b.get("max")) if m is not None]
    return {
        "count": a.get("count", 0) + b.get("count", 0),
        "sum": a.get("sum", 0.0) + b.get("sum", 0.0),
        "min": min(mins) if mins else None,
        "max": max(maxs) if maxs else None,
        "buckets": sorted([i, c] for i, c in counts.items()),
    }


def _request_group_row(rs: list[dict]) -> dict:
    """Aggregate one group of `request` records (a mode, or a
    (mode, tenant) pair) into the shared serving-row fields — ONE
    implementation of the finished-only filter, the TPOT formula, and
    the nearest-rank percentiles, so the per-mode and per-tenant tables
    can never drift apart. Latency stats cover FINISHED requests only:
    an aborted request carries null where the moment never happened
    (pre-ISSUE-4 records have no status and count finished)."""
    fin = [r for r in rs if r.get("status", "finished") == "finished"]
    ttft = [r["ttft_ms"] for r in fin if r.get("ttft_ms") is not None]
    # Per-output-token latency after the first token (TPOT).
    tpot = [
        (r["latency_ms"] - r["ttft_ms"]) / max(r["output_tokens"] - 1, 1)
        for r in fin
        if r.get("latency_ms") is not None and r.get("ttft_ms") is not None
    ]
    statuses: dict[str, int] = {}
    for r in rs:
        st = r.get("status", "finished")
        statuses[st] = statuses.get(st, 0) + 1
    # Quota skip-over wait (ISSUE 11 satellite): the SLOScheduler policy
    # share of queue wait, split from capacity waits. Absent in
    # pre-ISSUE-11 records -> no column data (renders as an em-dash).
    quota = [r["queue_wait_quota_ms"] for r in rs
             if r.get("queue_wait_quota_ms") is not None]
    return {
        "requests": len(rs),
        "statuses": statuses,
        "output_tokens": sum(r["output_tokens"] for r in rs),
        "ttft_p50_ms": _pct(ttft, 50),
        "ttft_p99_ms": _pct(ttft, 99),
        "tpot_p50_ms": _pct(tpot, 50),
        "tpot_p99_ms": _pct(tpot, 99),
        "quota_wait_p99_ms": _pct(quota, 99),
    }


def _by_event(records: Iterable[dict]) -> dict[str, list[dict]]:
    out: dict[str, list[dict]] = {}
    for r in records:
        if isinstance(r, dict) and "event" in r:
            out.setdefault(r["event"], []).append(r)
    return out


def summarize(records: Iterable[dict], *,
              peak_tflops: float | None = None) -> dict:
    """Aggregate records into one summary dict (the JSON output form)."""
    ev = _by_event(records)
    summary: dict = {
        "events": {k: len(v) for k, v in sorted(ev.items())},
        "duration_s": max((r.get("t", 0.0) for v in ev.values() for r in v),
                          default=0.0),
    }

    trains = ev.get("train", [])
    if trains:
        losses = [r["loss"] for r in trains if r.get("loss") is not None]
        summary["train"] = {
            "records": len(trains),
            "first_loss": losses[0] if losses else None,
            "last_loss": losses[-1] if losses else None,
            "min_loss": min(losses) if losses else None,
            "last_step": trains[-1].get("step"),
        }

    epochs = ev.get("epoch", [])
    if epochs:
        secs = [r["seconds"] for r in epochs]
        summary["epochs"] = {
            "count": len(epochs),
            "mean_s": statistics.fmean(secs),
            "median_s": statistics.median(secs),
            "best_s": min(secs),
        }

    evals = ev.get("eval", [])
    if evals:
        summary["eval"] = {k: v for k, v in evals[-1].items()
                           if k not in ("schema", "event", "t")}

    phases = ev.get("step_phases", [])
    if phases:
        steps = sum(r["steps"] for r in phases)
        totals: dict[str, float] = {}
        for r in phases:
            for name, ms in r["phases_ms"].items():
                totals[name] = totals.get(name, 0.0) + ms * r["steps"]
        summary["step_phases"] = {
            "steps": steps,
            "per_step_ms": {k: v / max(steps, 1) for k, v in totals.items()},
        }

    programs = ev.get("program", [])
    if programs:
        progs = []
        for r in programs:
            p = {
                "label": r.get("label", "step"),
                "flops": r.get("flops"),
                "bytes": r.get("bytes"),
                "steps_per_dispatch": r.get("steps_per_dispatch", 1),
                "collectives": r.get("collectives", {}),
                "backend": r.get("backend"),
                # Donation ledger + live scratch (obs.cost alias/memory
                # fields; absent in pre-PR-2 records -> None).
                "aliased_outputs": r.get("aliased_outputs"),
                "alias_bytes": r.get("alias_bytes"),
                "temp_bytes": r.get("temp_bytes"),
            }
            flops, n = p["flops"], p["steps_per_dispatch"] or 1
            p["flops_per_step"] = flops / n if flops else None
            peak = peak_flops(
                r.get("compute_dtype", "bfloat16"),
                backend=p["backend"], override_tflops=peak_tflops,
            ) if (p["backend"] == "tpu" or peak_tflops) else None
            sp = summary.get("step_phases", {}).get("per_step_ms", {})
            step_s = sum(sp.values()) / 1e3 if sp else None
            p["mfu"] = (mfu(p["flops_per_step"], step_s, peak)
                        if step_s else None)
            progs.append(p)
        summary["programs"] = progs

    memories = ev.get("memory", [])
    if memories:
        peaks = [
            d["stats"]["peak_bytes_in_use"]
            for r in memories for d in r["devices"]
            if d.get("stats") and "peak_bytes_in_use" in d["stats"]
        ]
        summary["memory"] = {
            "records": len(memories),
            "hbm_peak_bytes": max(peaks) if peaks else None,
        }

    requests = ev.get("request", [])
    if requests:
        by_mode: dict[str, list[dict]] = {}
        for r in requests:
            by_mode.setdefault(r.get("mode", "?"), []).append(r)
        rows = []
        for mode, rs in sorted(by_mode.items()):
            rows.append({
                "mode": mode,
                **_request_group_row(rs),
                "prompt_tokens": sum(r["prompt_tokens"] for r in rs),
                "preemptions": sum(r.get("preemptions", 0) for r in rs),
            })
        summary["requests"] = rows
        # Per-tenant serving table (ISSUE 8): only when any record is
        # tenant-tagged — a single-tenant run must not grow a table
        # that duplicates the per-mode rows above.
        if any(r.get("tenant") not in (None, "default") for r in requests):
            by_mt: dict[tuple[str, str], list[dict]] = {}
            for r in requests:
                key = (r.get("mode", "?"), r.get("tenant") or "default")
                by_mt.setdefault(key, []).append(r)
            summary["tenants"] = [
                {"mode": mode, "tenant": tenant, **_request_group_row(rs)}
                for (mode, tenant), rs in sorted(by_mt.items())
            ]

    blames = ev.get("blame", [])
    if blames:
        # Causal blame summaries (obs/causal.py, ISSUE 11): one row per
        # `blame` record (per mode, per segment under --merge).
        summary["blame"] = [
            {k: r.get(k) for k in
             ("mode", "requests", "categories", "quota_ticks",
              "tenants", "conserved", "crc")}
            for r in blames
        ]

    goodputs = ev.get("goodput", [])
    if goodputs:
        # Autosize sweep output (obs/autosize.py, ISSUE 16): candidate
        # rows in frontier order (the frontier record's ranking), plus
        # the recommendation line. Standalone kind="run" measurements
        # surface as candidates of a one-row frontier.
        cands = {r.get("cand", "run"): r for r in goodputs
                 if r.get("kind") in ("candidate", "run")}
        frontier = next((r for r in reversed(goodputs)
                         if r.get("kind") == "frontier"), None)
        order = (frontier or {}).get("order") or sorted(cands)
        summary["autosize"] = {
            "candidates": [
                {k: cands[c].get(k) for k in
                 ("cand", "topology", "scheduler", "len_dist", "prefix",
                  "spec", "requests", "good", "good_fraction",
                  "per_chip_rps", "goodput_rps", "tokens_per_s",
                  "ttft_p99_ms", "tpot_p99_ms", "estimated")}
                for c in order if c in cands
            ],
            **({k: frontier.get(k) for k in
                ("evaluated", "pruned", "seeded_from", "recommendation",
                 "frontier_crc", "recommendation_crc")}
               if frontier else {}),
        }

    chaos = ev.get("chaos", [])
    if chaos:
        # Chaos-search output (chaos/, ISSUE 19): one row per sampled
        # episode (plan spelling, axes, oracle verdict, CRCs), plus the
        # search summary — and, when the search failed, the minimized
        # repro plan.
        csum = next((r for r in reversed(chaos)
                     if r.get("kind") == "summary"), None)
        summary["chaos"] = {
            "rows": [
                {k: r.get(k) for k in
                 ("episode", "seed", "axes", "plan", "faults",
                  "requests", "violations", "replay_ticks",
                  "episode_crc", "trace_crc", "state_crc", "blame_crc")}
                for r in chaos if r.get("kind") == "episode"
            ],
            **({k: csum.get(k) for k in
                ("episodes", "violations", "failed", "episodes_crc",
                 "min_plan", "shrink_probes")
                if k in csum} if csum else {}),
        }

    alerts = ev.get("alert", [])
    if alerts:
        by_rule: dict[str, int] = {}
        by_sev: dict[str, int] = {}
        for r in alerts:
            by_rule[r.get("rule", "?")] = by_rule.get(r.get("rule", "?"),
                                                      0) + 1
            by_sev[r.get("severity", "?")] = by_sev.get(
                r.get("severity", "?"), 0) + 1
        summary["alerts"] = {
            "count": len(alerts),
            "by_rule": dict(sorted(by_rule.items())),
            "by_severity": dict(sorted(by_sev.items())),
        }

    faults = ev.get("fault", [])
    ckpts = ev.get("ckpt", [])
    if faults or ckpts:
        by_kind: dict[str, int] = {}
        for r in faults:
            kind = r.get("kind", "?")
            by_kind[kind] = by_kind.get(kind, 0) + 1
        summary["robustness"] = {
            "events": len(faults),
            "by_kind": dict(sorted(by_kind.items())),
            "restarts": by_kind.get("restart", 0),
            "nonfinite_steps": by_kind.get("nonfinite_step", 0),
            "checkpoint_fallbacks": by_kind.get("ckpt_fallback", 0),
            # Elasticity trail (ISSUE 5): preemption snapshots taken,
            # resumes that changed the mesh underneath the run.
            "preemptions": by_kind.get("preempt", 0),
            "topology_changes": by_kind.get("topology_change", 0),
            "ckpt_events": {
                reason: sum(1 for r in ckpts if r.get("reason") == reason)
                for reason in sorted({r.get("reason", "?") for r in ckpts})
            },
        }

    replicas = ev.get("replica", [])
    fleets = ev.get("fleet", [])
    if replicas or fleets:
        # Replica lifecycle (ISSUE 7): joins/crashes/restarts/circuit
        # opens per replica, plus the last router-tick state. The fleet
        # run's aggregate counters land in the `serve` table below
        # (mode "fleet") like any other serving summary.
        by_replica: dict[str, dict[str, int]] = {}
        for r in replicas:
            per = by_replica.setdefault(r.get("name", "?"), {})
            kind = r.get("kind", "?")
            per[kind] = per.get(kind, 0) + 1
        kinds: dict[str, int] = {}
        for per in by_replica.values():
            for k, v in per.items():
                kinds[k] = kinds.get(k, 0) + v
        last = fleets[-1] if fleets else {}
        summary["fleet"] = {
            "replica_events": len(replicas),
            "by_kind": dict(sorted(kinds.items())),
            "by_replica": {name: dict(sorted(per.items()))
                           for name, per in sorted(by_replica.items())},
            "ticks_logged": len(fleets),
            "replicas_last": last.get("replicas"),
            "pending_last": last.get("pending"),
            # Cache-aware routing (ISSUE 18): the newest fleet record's
            # cumulative per-replica [routed hits, dispatches] split —
            # the ROUTING table's rows (absent off cache_aware).
            "route_last": last.get("route"),
        }

    # Lossy transport (ISSUE 20): the bus's cumulative message counters
    # from the run summary (present on every --transport run, faults or
    # not), plus partition open/heal lifecycle counts from the
    # `transport` event records.
    t_serve = next((r for r in ev.get("serve", [])
                    if r.get("msgs_sent") is not None), None)
    t_events = ev.get("transport", [])
    if t_serve is not None or t_events:
        t_kinds: dict[str, int] = {}
        for r in t_events:
            k = r.get("kind", "?")
            t_kinds[k] = t_kinds.get(k, 0) + 1
        summary["transport"] = {
            **({k: t_serve.get(k) for k in
                ("msgs_sent", "msgs_delivered", "msgs_dropped",
                 "msgs_duped", "msgs_delayed", "msgs_deduped",
                 "retransmits", "lease_refusals", "partitions",
                 "lease_ticks")} if t_serve is not None else {}),
            "events": dict(sorted(t_kinds.items())),
        }

    handoffs = ev.get("handoff", [])
    if handoffs:
        # Disaggregated KV handoffs (ISSUE 13): lifecycle counts by
        # state, aborts broken down by reason.
        by_state: dict[str, int] = {}
        by_reason: dict[str, int] = {}
        for r in handoffs:
            st = r.get("state", "?")
            by_state[st] = by_state.get(st, 0) + 1
            if st == "aborted":
                why = r.get("reason", "?")
                by_reason[why] = by_reason.get(why, 0) + 1
        summary["handoffs"] = {
            "events": len(handoffs),
            "by_state": dict(sorted(by_state.items())),
            "aborts_by_reason": dict(sorted(by_reason.items())),
            "pages": sum(r.get("pages", 0) for r in handoffs
                         if r.get("state") == "done"),
        }

    serves = ev.get("serve", [])
    if serves:
        summary["serve"] = [
            {k: r.get(k) for k in
             ("mode", "requests", "statuses", "output_tokens",
              "decode_ticks", "prefill_chunks", "preemptions",
              "watchdog_slow_ticks", "tokens_per_s",
              "ttft_p50_ms", "ttft_p99_ms", "tpot_p50_ms", "tpot_p99_ms",
              "prefix_hits", "prefix_misses", "prefix_hit_tokens",
              "prefix_cow", "prefix_evictions",
              "host_pages", "tier_spills", "tier_readmits",
              "tier_refusals", "tier_host_evictions",
              "policy", "autoscale", "route_hits", "route_misses",
              "route_hit_tokens", "scale_ups", "scale_downs",
              "replica_ticks",
              "spec_rounds", "spec_proposed", "spec_accepted")}
            for r in serves
        ]

    snaps = ev.get("metrics", [])
    if snaps:
        # The NEWEST registry snapshot per (segment, label): within one
        # process counters/histograms are cumulative, so the last
        # snapshot subsumes the earlier ones — but each relaunched
        # process (a supervisor restart under --merge, tagged "_seg" by
        # report_main) restarts its registry at zero, so segment-latest
        # snapshots are FOLDED: counters summed, histograms merged
        # bucket-wise, gauges last-segment-wins. "mode" labels serving
        # registries; trainers default to train.
        latest: dict[tuple[int, str], dict] = {}
        for r in snaps:
            latest[(r.get("_seg", 0), r.get("mode", "train"))] = r
        folded: dict[str, dict] = {}
        for (_, label), r in sorted(latest.items()):
            f = folded.setdefault(
                label, {"counters": {}, "gauges": {}, "histograms": {}})
            for k, v in (r.get("counters") or {}).items():
                f["counters"][k] = f["counters"].get(k, 0) + v
            for k, g in (r.get("gauges") or {}).items():
                f["gauges"][k] = (g or {}).get("value")
            for k, fields in (r.get("histograms") or {}).items():
                prev = f["histograms"].get(k)
                f["histograms"][k] = fields if prev is None \
                    else _merge_hist_fields(prev, fields)
        out: dict[str, dict] = {}
        for label, f in sorted(folded.items()):
            hists = {}
            for name, fields in sorted(f["histograms"].items()):
                h = Histogram.from_fields(fields)
                hists[name] = {
                    "count": h.count,
                    "p50": h.percentile(50),
                    "p95": h.percentile(95),
                    "p99": h.percentile(99),
                    "min": h.min,
                    "max": h.max,
                }
            out[label] = {
                "counters": dict(sorted(f["counters"].items())),
                "gauges": dict(sorted(f["gauges"].items())),
                "histograms": hists,
            }
        summary["metrics"] = out

    spans = ev.get("span", [])
    if spans:
        agg: dict[str, list[float]] = {}
        for r in spans:
            agg.setdefault(r["name"], []).append(r["ms"])
        summary["spans"] = {
            name: {"count": len(ms), "total_ms": sum(ms),
                   "mean_ms": statistics.fmean(ms)}
            for name, ms in sorted(agg.items())
        }
    return summary


_pct = pct_nearest


def render_markdown(summary: dict, title: str = "Run report") -> str:
    """The summary as markdown tables — what PERF.md sections are made
    of, generated instead of hand-assembled."""
    lines = [f"## {title}", ""]
    lines += [
        f"Records: "
        + ", ".join(f"{k}={v}" for k, v in summary["events"].items())
        + f"; duration {summary['duration_s']:.4g} s",
        "",
    ]
    if "train" in summary:
        t = summary["train"]
        lines += [
            "| training | records | first loss | last loss | min loss | last step |",
            "|---|---|---|---|---|---|",
            f"| | {t['records']} | {_fmt(t['first_loss'])} "
            f"| {_fmt(t['last_loss'])} | {_fmt(t['min_loss'])} "
            f"| {_fmt(t['last_step'])} |",
            "",
        ]
    if "epochs" in summary:
        e = summary["epochs"]
        lines += [
            "| epochs | mean s | median s | best s |",
            "|---|---|---|---|",
            f"| {e['count']} | {e['mean_s']:.4g} | {e['median_s']:.4g} "
            f"| {e['best_s']:.4g} |",
            "",
        ]
    if "eval" in summary:
        kv = summary["eval"]
        lines += ["| eval | " + " | ".join(kv) + " |",
                  "|---|" + "---|" * len(kv),
                  "| last | " + " | ".join(_fmt(v) for v in kv.values()) + " |",
                  ""]
    if "step_phases" in summary:
        sp = summary["step_phases"]
        names = sorted(sp["per_step_ms"])
        lines += [
            "| step phases (ms/step) | " + " | ".join(names)
            + " | total | steps |",
            "|---|" + "---|" * (len(names) + 2),
            "| | "
            + " | ".join(f"{sp['per_step_ms'][n]:.4g}" for n in names)
            + f" | {sum(sp['per_step_ms'].values()):.4g} | {sp['steps']} |",
            "",
        ]
    if "programs" in summary:
        lines += [
            "| program | flops/dispatch | bytes | aliased (live-mem) "
            "| temp bytes | steps/dispatch | flops/step | collectives "
            "| MFU |",
            "|---|---|---|---|---|---|---|---|---|",
        ]
        for p in summary["programs"]:
            mfu_s = f"{p['mfu'] * 100:.1f}%" if p.get("mfu") else "—"
            # Donation column: how many outputs alias their inputs and
            # how many bytes update IN PLACE (state that never needs a
            # second live copy at the optimizer update).
            alias_s = "—"
            if p.get("aliased_outputs"):
                ab = p.get("alias_bytes")
                alias_s = f"{p['aliased_outputs']}"
                if ab:
                    alias_s += f" ({_fmt(ab)} B)"
            lines.append(
                f"| {p['label']} | {_fmt(p['flops'])} | {_fmt(p['bytes'])} "
                f"| {alias_s} | {_fmt(p.get('temp_bytes'))} "
                f"| {p['steps_per_dispatch']} | {_fmt(p['flops_per_step'])} "
                f"| {_fmt(p['collectives'])} | {mfu_s} |"
            )
        lines.append("")
    if "requests" in summary:
        lines += [
            "| serving (per-request) | requests | statuses | out tokens "
            "| preempt | TTFT p50 ms | TTFT p99 ms | tok p50 ms "
            "| tok p99 ms | quota wait p99 ms |",
            "|---|---|---|---|---|---|---|---|---|---|",
        ]
        for r in summary["requests"]:
            lines.append(
                f"| {r['mode']} | {r['requests']} "
                f"| {_fmt(r.get('statuses'))} | {r['output_tokens']} "
                f"| {r['preemptions']} | {_fmt(r['ttft_p50_ms'])} "
                f"| {_fmt(r['ttft_p99_ms'])} | {_fmt(r['tpot_p50_ms'])} "
                f"| {_fmt(r['tpot_p99_ms'])} "
                f"| {_fmt(r.get('quota_wait_p99_ms'))} |"
            )
        lines.append("")
    if "tenants" in summary:
        lines += [
            "| tenant traffic | tenant | requests | statuses "
            "| out tokens | TTFT p50 ms | TTFT p99 ms | tok p50 ms "
            "| tok p99 ms | quota wait p99 ms |",
            "|---|---|---|---|---|---|---|---|---|---|",
        ]
        for r in summary["tenants"]:
            lines.append(
                f"| {r['mode']} | {r['tenant']} | {r['requests']} "
                f"| {_fmt(r['statuses'])} | {r['output_tokens']} "
                f"| {_fmt(r['ttft_p50_ms'])} | {_fmt(r['ttft_p99_ms'])} "
                f"| {_fmt(r['tpot_p50_ms'])} | {_fmt(r['tpot_p99_ms'])} "
                f"| {_fmt(r.get('quota_wait_p99_ms'))} |"
            )
        lines.append("")
    if "blame" in summary:
        # Causal blame (ISSUE 11): aggregate critical-path attribution
        # per mode — where the run's request-latency ticks actually
        # went, with the quota skip-over share split out.
        from .causal import CATEGORIES as _BLAME_CATS

        lines += [
            "| blame (ticks) | requests | "
            + " | ".join(c.replace("_", " ") for c in _BLAME_CATS)
            + " | quota skip | conserved | crc |",
            "|---|" + "---|" * (len(_BLAME_CATS) + 4),
        ]
        for r in summary["blame"]:
            cats = r.get("categories") or {}
            lines.append(
                f"| {r['mode']} | {_fmt(r.get('requests'))} | "
                + " | ".join(_fmt(cats.get(c)) for c in _BLAME_CATS)
                + f" | {_fmt(r.get('quota_ticks'))} "
                f"| {'yes' if r.get('conserved') else 'NO'} "
                f"| {_fmt(r.get('crc'))} |"
            )
        lines.append("")
    if "autosize" in summary:
        # Goodput frontier (obs/autosize.py, ISSUE 16): candidate rows
        # in frontier order plus the sweep's recommendation line.
        az = summary["autosize"]
        lines += [
            "| frontier | topology | sched | len dist | prefix | spec "
            "| good | good frac | per-chip r/s | tok/s | TTFT p99 ms "
            "| TPOT p99 ms |",
            "|---|" + "---|" * 11,
        ]
        for i, r in enumerate(az["candidates"], 1):
            est = " (est)" if r.get("estimated") else ""
            lines.append(
                f"| {i}{est} | {_fmt(r.get('topology'))} "
                f"| {_fmt(r.get('scheduler'))} | {_fmt(r.get('len_dist'))} "
                f"| {'on' if r.get('prefix') else 'off'} "
                f"| {_fmt(r.get('spec'))} | {_fmt(r.get('good'))} "
                f"| {_fmt(r.get('good_fraction'))} "
                f"| {_fmt(r.get('per_chip_rps'))} "
                f"| {_fmt(r.get('tokens_per_s'))} "
                f"| {_fmt(r.get('ttft_p99_ms'))} "
                f"| {_fmt(r.get('tpot_p99_ms'))} |"
            )
        lines.append("")
        if az.get("recommendation") is not None:
            seeded = az.get("seeded_from")
            lines += [
                "| autosize | recommendation | evaluated | pruned "
                "| seeded from | frontier crc | recommendation crc |",
                "|---|" + "---|" * 6,
                f"| | {az['recommendation']} | {_fmt(az.get('evaluated'))} "
                f"| {_fmt(az.get('pruned'))} | {_fmt(seeded)} "
                f"| {_fmt(az.get('frontier_crc'))} "
                f"| {_fmt(az.get('recommendation_crc'))} |",
                "",
            ]
    if "chaos" in summary:
        # Chaos search (chaos/, ISSUE 19): one row per sampled episode,
        # then the search summary line (and the minimized repro plan
        # when the search failed).
        ch = summary["chaos"]
        lines += [
            "| chaos ep | axes | plan | faults | violations "
            "| replay ticks | episode crc |",
            "|---|" + "---|" * 6,
        ]
        for r in ch["rows"]:
            viol = r.get("violations") or []
            lines.append(
                f"| {_fmt(r.get('episode'))} | {_fmt(r.get('axes'))} "
                f"| `{r.get('plan') or '(none)'}` "
                f"| {_fmt(r.get('faults'))} "
                f"| {','.join(viol) if viol else 'ok'} "
                f"| {_fmt(r.get('replay_ticks'))} "
                f"| {_fmt(r.get('episode_crc'))} |"
            )
        lines.append("")
        if "episodes" in ch:
            lines += [
                "| chaos | episodes | violating | episodes crc "
                "| min plan | shrink probes |",
                "|---|" + "---|" * 5,
                f"| | {_fmt(ch.get('episodes'))} "
                f"| {_fmt(ch.get('violations'))} "
                f"| {_fmt(ch.get('episodes_crc'))} "
                f"| {'`' + ch['min_plan'] + '`' if ch.get('min_plan') else ''} "
                f"| {_fmt(ch.get('shrink_probes'))} |",
                "",
            ]
    if "alerts" in summary:
        al = summary["alerts"]
        lines += [
            "| alerts | by severity | by rule |",
            "|---|---|---|",
            f"| {al['count']} | {_fmt(al['by_severity'])} "
            f"| {_fmt(al['by_rule'])} |",
            "",
        ]
    if "robustness" in summary:
        rb = summary["robustness"]
        lines += [
            "| robustness | events | restarts | preempted "
            "| topology changes | non-finite steps "
            "| ckpt fallbacks | by kind |",
            "|---|---|---|---|---|---|---|---|",
            f"| | {rb['events']} | {rb['restarts']} "
            f"| {rb.get('preemptions', 0)} "
            f"| {rb.get('topology_changes', 0)} "
            f"| {rb['nonfinite_steps']} | {rb['checkpoint_fallbacks']} "
            f"| {_fmt(rb['by_kind'])} |",
            "",
        ]
        if rb.get("ckpt_events"):
            lines += [
                "| checkpoints | " + " | ".join(rb["ckpt_events"]) + " |",
                "|---|" + "---|" * len(rb["ckpt_events"]),
                "| | " + " | ".join(str(v) for v in
                                    rb["ckpt_events"].values()) + " |",
                "",
            ]
    if "fleet" in summary:
        fl = summary["fleet"]
        bk = fl["by_kind"]
        lines += [
            "| fleet | joins | crashes | restarts | circuit opens "
            "| leaves | last replicas | last pending |",
            "|---|---|---|---|---|---|---|---|",
            f"| | {bk.get('join', 0)} | {bk.get('crash', 0)} "
            f"| {bk.get('restart', 0)} | {bk.get('circuit_open', 0)} "
            f"| {bk.get('leave', 0)} | {_fmt(fl['replicas_last'])} "
            f"| {_fmt(fl['pending_last'])} |",
            "",
        ]
        if fl["by_replica"]:
            lines += ["| replica | lifecycle |", "|---|---|"]
            for name, per in fl["by_replica"].items():
                lines.append(f"| {name} | {_fmt(per)} |")
            lines.append("")
        if fl.get("route_last"):
            # Per-replica routing split (ISSUE 18): cumulative routed
            # hits / dispatches from the newest fleet record — where
            # the cache-aware wins actually landed.
            lines += ["| replica routing | routed hits | dispatches "
                      "| hit rate |", "|---|---|---|---|"]
            for name, pair in sorted(fl["route_last"].items()):
                hits, disp = (pair + [0, 0])[:2]
                rate = f"{100.0 * hits / disp:.1f}%" if disp else "—"
                lines.append(
                    f"| {name} | {_fmt(hits)} | {_fmt(disp)} | {rate} |")
            lines.append("")
    if "transport" in summary:
        # Lossy transport (ISSUE 20): bus message totals + lease
        # refusals — the exactly-once machinery's visible work.
        tr = summary["transport"]
        lines += [
            "| transport | sent | delivered | dropped | duped | delayed "
            "| deduped | retransmits | lease refused | partitions |",
            "|---|---|---|---|---|---|---|---|---|---|",
            f"| {'lease %st' % _fmt(tr.get('lease_ticks')) if tr.get('lease_ticks') else 'lease off'} "
            f"| {_fmt(tr.get('msgs_sent'))} "
            f"| {_fmt(tr.get('msgs_delivered'))} "
            f"| {_fmt(tr.get('msgs_dropped'))} "
            f"| {_fmt(tr.get('msgs_duped'))} "
            f"| {_fmt(tr.get('msgs_delayed'))} "
            f"| {_fmt(tr.get('msgs_deduped'))} "
            f"| {_fmt(tr.get('retransmits'))} "
            f"| {_fmt(tr.get('lease_refusals'))} "
            f"| {_fmt(tr.get('partitions'))} |",
        ]
        if tr.get("events"):
            lines.append("partition lifecycle: " + "  ".join(
                f"{k}:{v}" for k, v in tr["events"].items()))
        lines.append("")
    if "handoffs" in summary:
        # Disaggregated KV handoffs (ISSUE 13).
        ho = summary["handoffs"]
        st = ho["by_state"]
        lines += [
            "| handoffs | started | done | aborted | pages moved "
            "| aborts by reason |",
            "|---|---|---|---|---|---|",
            f"| | {st.get('started', 0)} | {st.get('done', 0)} "
            f"| {st.get('aborted', 0)} | {ho['pages']} "
            f"| {_fmt(ho['aborts_by_reason'])} |",
            "",
        ]
    if "serve" in summary:
        lines += [
            "| serve run | requests | tokens/s | decode ticks "
            "| prefill chunks | preempt | TTFT p99 ms | tok p99 ms "
            "| spec accept |",
            "|---|---|---|---|---|---|---|---|---|",
        ]
        for s in summary["serve"]:
            # Speculative acceptance rate (ISSUE 14): accepted draft
            # tokens / proposed, em-dash on spec-off runs.
            prop = s.get("spec_proposed") or 0
            acc = (f"{100.0 * (s.get('spec_accepted') or 0) / prop:.1f}%"
                   if prop else "—")
            lines.append(
                f"| {s['mode']} | {_fmt(s['requests'])} "
                f"| {_fmt(s['tokens_per_s'])} | {_fmt(s['decode_ticks'])} "
                f"| {_fmt(s['prefill_chunks'])} | {_fmt(s['preemptions'])} "
                f"| {_fmt(s['ttft_p99_ms'])} | {_fmt(s['tpot_p99_ms'])} "
                f"| {acc} |"
            )
        lines.append("")
        # Prefix-cache table (ISSUE 9): only for runs that did any
        # matching — an all-zero row on a sharing-off run is noise.
        pruns = [s for s in summary["serve"]
                 if (s.get("prefix_hits") or 0) + (s.get("prefix_misses")
                                                   or 0) > 0]
        if pruns:
            lines += [
                "| prefix cache | hits | misses | hit tokens | cow "
                "| evictions |",
                "|---|---|---|---|---|---|",
            ]
            for s in pruns:
                lines.append(
                    f"| {s['mode']} | {_fmt(s['prefix_hits'])} "
                    f"| {_fmt(s['prefix_misses'])} "
                    f"| {_fmt(s['prefix_hit_tokens'])} "
                    f"| {_fmt(s['prefix_cow'])} "
                    f"| {_fmt(s['prefix_evictions'])} |"
                )
            lines.append("")
        # Host-tier table (ISSUE 17): only for runs that ran WITH a
        # host tier (host_pages stamped nonzero) — spill-off runs stamp
        # all-zero tier counters and must not grow a table of dashes.
        truns = [s for s in summary["serve"] if s.get("host_pages")]
        if truns:
            lines += [
                "| host tier | host pages | spills | readmits "
                "| refusals | host evictions |",
                "|---|---|---|---|---|---|",
            ]
            for s in truns:
                lines.append(
                    f"| {s['mode']} | {_fmt(s['host_pages'])} "
                    f"| {_fmt(s['tier_spills'])} "
                    f"| {_fmt(s['tier_readmits'])} "
                    f"| {_fmt(s['tier_refusals'])} "
                    f"| {_fmt(s['tier_host_evictions'])} |"
                )
            lines.append("")
        # Cache-aware routing table (ISSUE 18): only for runs the
        # router actually scored (cache_aware dispatches counted) — a
        # hash-affinity run must not grow a table of zeros.
        rruns = [s for s in summary["serve"]
                 if (s.get("route_hits") or 0) + (s.get("route_misses")
                                                  or 0) > 0]
        if rruns:
            lines += [
                "| routing | policy | routed hits | misses "
                "| hit tokens | hit rate |",
                "|---|---|---|---|---|---|",
            ]
            for s in rruns:
                hits = s.get("route_hits") or 0
                total = hits + (s.get("route_misses") or 0)
                lines.append(
                    f"| {s['mode']} | {s.get('policy', '—')} "
                    f"| {_fmt(hits)} | {_fmt(s.get('route_misses'))} "
                    f"| {_fmt(s.get('route_hit_tokens'))} "
                    f"| {100.0 * hits / total:.1f}% |"
                )
            lines.append("")
        # Autoscale table (ISSUE 18): runs that scaled (or ran the
        # policy — an autoscaled run that never moved is information).
        aruns = [s for s in summary["serve"] if s.get("autoscale")]
        if aruns:
            lines += [
                "| autoscale | scale ups | scale downs | replica ticks "
                "| final replicas |",
                "|---|---|---|---|---|",
            ]
            for s in aruns:
                lines.append(
                    f"| {s['mode']} | {_fmt(s.get('scale_ups'))} "
                    f"| {_fmt(s.get('scale_downs'))} "
                    f"| {_fmt(s.get('replica_ticks'))} "
                    f"| {_fmt((summary.get('fleet') or {}).get('replicas_last'))} |"
                )
            lines.append("")
    if "metrics" in summary:
        # Runtime-registry snapshots (ISSUE 6): the p50/p95/p99 tables
        # the serving sections of PERF.md are made from, produced by
        # obs.metrics histograms instead of hand-assembled.
        lines += [
            "| runtime histogram | count | p50 | p95 | p99 | min | max |",
            "|---|---|---|---|---|---|---|",
        ]
        for label, m in summary["metrics"].items():
            for name, h in m["histograms"].items():
                lines.append(
                    f"| {label}: {name} | {h['count']} | {_fmt(h['p50'])} "
                    f"| {_fmt(h['p95'])} | {_fmt(h['p99'])} "
                    f"| {_fmt(h['min'])} | {_fmt(h['max'])} |"
                )
        lines.append("")
        for label, m in summary["metrics"].items():
            kv = {**m["counters"],
                  **{k: v for k, v in m["gauges"].items()
                     if v is not None}}
            if kv:
                lines.append(
                    f"Runtime totals [{label}]: "
                    + ", ".join(f"{k}={_fmt(v)}" for k, v in kv.items())
                )
        lines.append("")
    if "memory" in summary:
        m = summary["memory"]
        peak = m["hbm_peak_bytes"]
        peak_s = f"{peak / 2**20:.1f} MiB" if peak else "—"
        lines += [f"Device memory: peak {peak_s} "
                  f"({m['records']} snapshots)", ""]
    if "spans" in summary:
        lines += ["| span | count | total ms | mean ms |",
                  "|---|---|---|---|"]
        for name, s in summary["spans"].items():
            lines.append(
                f"| {name} | {s['count']} | {s['total_ms']:.4g} "
                f"| {s['mean_ms']:.4g} |"
            )
        lines.append("")
    return "\n".join(lines)


def report_main(argv: list[str] | None = None) -> int:
    """The `mctpu report` subcommand (also scripts/obs_report.py)."""
    ap = argparse.ArgumentParser(
        prog="mctpu report",
        description="Summarize a metrics JSONL run as markdown tables "
                    "(or JSON with --format json).",
    )
    ap.add_argument("paths", nargs="+", help="metrics JSONL file(s)")
    ap.add_argument("--format", choices=("md", "json"), default="md")
    ap.add_argument("--merge", action="store_true",
                    help="merge every run segment of every file into ONE "
                         "report — a supervised run's pre/post-restart "
                         "segments (or a multi-file capture) render as "
                         "one table instead of one report per segment")
    ap.add_argument("--peak-tflops", type=float, default=None,
                    help="chip bf16 peak for the MFU column (defaults to "
                         "v5e when records say backend=tpu)")
    args = ap.parse_args(argv)
    rc = 0
    per_path: list[tuple[str, list[list[dict]]]] = []
    for path in args.paths:
        try:
            # Per-run segments ('# run' markers from MetricsLogger's
            # append mode): aggregating across unrelated runs would pair
            # one run's FLOPs with another's step times — unless --merge
            # says the segments ARE one logical run (supervisor
            # restarts resume the same training).
            per_path.append((path, [r for r in iter_runs(path) if r]))
        except (OSError, ValueError) as e:
            print(f"error: {path}: {e}", file=sys.stderr)
            rc = 1
    if args.merge:
        # Tag each record with its run-segment ordinal: registry
        # snapshots are cumulative only WITHIN a process, so summarize
        # needs the segment boundary to fold counters across restarts
        # instead of letting the last segment's totals shadow the rest.
        segments = [records for _, runs in per_path for records in runs]
        merged = [dict(rec, _seg=seg)
                  for seg, records in enumerate(segments)
                  for rec in records]
        nseg = len(segments)
        summary = summarize(merged, peak_tflops=args.peak_tflops)
        label = (f"merged ({nseg} segment(s) from "
                 f"{len(per_path)} file(s))")
        if args.format == "json":
            print(json.dumps({"paths": [p for p, _ in per_path],
                              "segments": nseg, **summary}))
        else:
            print(render_markdown(summary, title=f"Run report — {label}"))
        return rc
    for path, runs in per_path:
        for i, records in enumerate(runs, 1):
            summary = summarize(records, peak_tflops=args.peak_tflops)
            label = path if len(runs) == 1 else f"{path} (run {i}/{len(runs)})"
            if args.format == "json":
                print(json.dumps(
                    {"path": path, "run": i, "runs": len(runs), **summary}
                ))
            else:
                print(render_markdown(summary, title=f"Run report — {label}"))
    return rc
