"""Streaming alert rules over the live JSONL event stream.

Nobody watches a run's JSONL while it happens: a latency regression, a
stalled replica, or an SLO burn is discovered after the run, by `mctpu
compare`/`report`. This module is the watcher — a rule engine that
folds the SAME records the file gets (`tick`/`fleet`/`metrics`/`fault`/
`train`/`replica` families) and emits a versioned `alert` event the
moment a rule trips.

THE REPLAY CONTRACT: the engine is a pure fold over the record
sequence — no clock reads, no randomness, state only from ingested
records. Attached live (MetricsLogger's observer hook, or directly on
a bench's tick sinks), it sees exactly the records the file receives;
replaying the finished file therefore reproduces the bitwise-identical
alert sequence, and `alerts_crc` pins it as one number `mctpu compare`
gates at 0% (the determinism CI uses for the fleet storm). The engine
ignores `alert` records on ingest, so a file that already carries live
alerts replays cleanly.

Rule kinds (spec objects in the "rules" list of an SLO file, or passed
directly):

- threshold:      {"name", "kind": "threshold", "event", "field",
                   "op": ">|>=|<|<=|==|!=", "value", "for_count": 1,
                   "per": null|"mode", "each": false, "severity"}
                  Edge-triggered by default: fires when the predicate
                  has held for `for_count` consecutive matching records
                  (per group), re-arms when it goes false. "each": true
                  fires on every matching record (discrete events like
                  a replica crash).
- rate_of_change: {"kind": "rate_of_change", "event", "field",
                   "max_rise_pct", "max_fall_pct"} — compares each
                  record's field to the previous one (per group).
- absence:        {"kind": "absence", "event", "max_gap_s",
                   "per": "mode"} — staleness: fires when the watched
                  family goes quiet for longer than max_gap_s on the
                  run timeline. Only records carrying "now" (tick /
                  fleet families) advance the staleness clock: end-of-
                  run records are stamped on the producer's OWN "t"
                  timeline, and mixing the two would fabricate gaps.
- burn_rate:      built from an SLOSpec (never spelled by hand): per
                  (tenant, objective) multi-window burn — fires when
                  EVERY window of a [long, short] pair burns faster
                  than the spec's max_rate (Google SRE multi-window
                  multi-burn-rate), re-arms when the pair stops
                  qualifying. Folds the per-tick `terminal` entries.

Alert record fields: seq (emission index), rule, kind, severity, at
(the triggering record's timeline stamp), plus context (tenant/metric/
value/threshold/burn/windows_s, group for per-grouped rules, tick when
the trigger carried one).
"""

from __future__ import annotations

import json
import zlib

from .slo import Accountant, SLOSpec, run_mode

ALERT_KINDS = ("threshold", "rate_of_change", "absence", "burn_rate")
_OPS = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
}


def event_time(rec: dict) -> float:
    """A record's position on its producer's timeline: engine/fleet
    records carry "now" (run-relative, FakeClock-deterministic), the
    rest only their logger "t" stamp."""
    now = rec.get("now")
    return float(now if now is not None else rec.get("t", 0.0) or 0.0)


class _ThresholdRule:
    def __init__(self, spec: dict):
        self.name = spec["name"]
        self.event = spec["event"]
        self.field = spec["field"]
        op = spec.get("op", ">")
        if op not in _OPS:
            raise ValueError(f"rule {self.name!r}: unknown op {op!r}")
        self.op, self._cmp = op, _OPS[op]
        self.value = spec["value"]
        self.for_count = int(spec.get("for_count", 1))
        self.per = spec.get("per")
        self.each = bool(spec.get("each", False))
        self.severity = spec.get("severity", "warn")
        self._streak: dict = {}
        self._firing: dict = {}

    def ingest(self, event: str, rec: dict) -> list[dict]:
        if event != self.event or self.field not in rec:
            return []
        group = rec.get(self.per) if self.per else None
        v = rec[self.field]
        try:
            hit = self._cmp(v, self.value)
        except TypeError:
            return []
        if not hit:
            self._streak[group] = 0
            self._firing[group] = False
            return []
        if self.each:
            return [self._alert(rec, group, v)]
        self._streak[group] = self._streak.get(group, 0) + 1
        if self._streak[group] >= self.for_count \
                and not self._firing.get(group):
            self._firing[group] = True
            return [self._alert(rec, group, v)]
        return []

    def _alert(self, rec: dict, group, v) -> dict:
        a = {"rule": self.name, "kind": "threshold",
             "severity": self.severity, "at": round(event_time(rec), 4),
             "field": f"{self.event}.{self.field}", "value": v,
             "threshold": self.value, "op": self.op}
        if group is not None:
            a["group"] = group
        if rec.get("tick") is not None:
            a["tick"] = rec["tick"]
        return a


class _RateRule:
    def __init__(self, spec: dict):
        self.name = spec["name"]
        self.event = spec["event"]
        self.field = spec["field"]
        self.max_rise = spec.get("max_rise_pct")
        self.max_fall = spec.get("max_fall_pct")
        if self.max_rise is None and self.max_fall is None:
            raise ValueError(
                f"rule {self.name!r}: rate_of_change needs max_rise_pct "
                "and/or max_fall_pct"
            )
        self.per = spec.get("per")
        self.severity = spec.get("severity", "warn")
        self._prev: dict = {}

    def ingest(self, event: str, rec: dict) -> list[dict]:
        if event != self.event:
            return []
        v = rec.get(self.field)
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            return []
        group = rec.get(self.per) if self.per else None
        prev, self._prev[group] = self._prev.get(group), v
        if prev is None or prev == 0:
            return []
        delta_pct = (v - prev) / abs(prev) * 100.0
        breach = ((self.max_rise is not None and delta_pct > self.max_rise)
                  or (self.max_fall is not None
                      and delta_pct < -self.max_fall))
        if not breach:
            return []
        a = {"rule": self.name, "kind": "rate_of_change",
             "severity": self.severity, "at": round(event_time(rec), 4),
             "field": f"{self.event}.{self.field}", "value": v,
             "prev": prev, "delta_pct": round(delta_pct, 3)}
        if group is not None:
            a["group"] = group
        if rec.get("tick") is not None:
            a["tick"] = rec["tick"]
        return [a]


class _AbsenceRule:
    """Staleness: the watched family stopped arriving. Driven only by
    records that carry "now" (one shared run timeline); gap checks run
    on EVERY such record, watched family or not — another family's
    heartbeat is what reveals the watched one went quiet."""

    def __init__(self, spec: dict):
        self.name = spec["name"]
        self.event = spec["event"]
        self.max_gap_s = float(spec["max_gap_s"])
        self.per = spec.get("per")
        self.severity = spec.get("severity", "warn")
        self._last_seen: dict = {}
        self._fired: dict = {}
        # Stalest (group, last-seen) cache: the common no-gap record
        # costs one subtraction instead of a scan over every group —
        # the rule folds EVERY timeline record of a 10^5-request storm.
        self._stale_t: float | None = None
        self._stale_g = None

    def ingest(self, event: str, rec: dict) -> list[dict]:
        now = rec.get("now")
        if now is None:
            return []
        out = []
        # Gap check BEFORE this record updates its own group: a watched
        # record arriving late is itself the proof of the gap it ends
        # (the FakeClock serve runs surface a `slow` fault exactly this
        # way — the next tick lands max_gap_s late). The cached stalest
        # time makes the scan conditional: if even the stalest group is
        # fresh, nobody can fire.
        if self._stale_t is not None and now - self._stale_t > self.max_gap_s:
            for group, seen in self._last_seen.items():
                gap = now - seen
                if gap > self.max_gap_s and not self._fired.get(group):
                    self._fired[group] = True
                    a = {"rule": self.name, "kind": "absence",
                         "severity": self.severity, "at": round(now, 4),
                         "family": self.event, "gap_s": round(gap, 4),
                         "max_gap_s": self.max_gap_s}
                    if group is not None:
                        a["group"] = group
                    if rec.get("tick") is not None:
                        a["tick"] = rec["tick"]
                    out.append(a)
        if event == self.event:
            group = rec.get(self.per) if self.per else None
            prev = self._last_seen.get(group)
            self._last_seen[group] = now
            self._fired[group] = False
            if self._stale_t is None or prev is None \
                    or group == self._stale_g:
                # The stalest group moved (or membership changed):
                # re-derive the cache. Amortized O(1): each group takes
                # its turn as stalest once per heartbeat round.
                self._stale_g, self._stale_t = min(
                    self._last_seen.items(), key=lambda kv: kv[1])
        return out


class _BurnRule:
    """Multi-window multi-burn-rate over the SLO accountant: one
    logical rule spanning every (mode, tenant, objective, window-pair)
    combination, each with its own firing latch. One Accountant per
    run MODE — a serve-bench file's static and continuous halves live
    on independent timelines (obs.slo.verdicts_from_terminals makes
    the same split), while a fleet's per-replica modes share one clock
    and fold together."""

    def __init__(self, slo: SLOSpec):
        self.slo = slo
        self._accs: dict[str, Accountant] = {}
        self._firing: dict[tuple, bool] = {}

    def ingest(self, event: str, rec: dict) -> list[dict]:
        if event != "tick" or not rec.get("terminal"):
            return []
        now = event_time(rec)
        mode = run_mode(rec)
        acc = self._accs.get(mode)
        if acc is None:
            acc = self._accs[mode] = Accountant(self.slo)
        out = []
        for tenant, obj, we, good in acc.observe_all(rec, now):
            if good:
                continue  # burn can only rise on a bad event
            for lo, sh in self.slo.windows:
                key = (mode, tenant, obj.metric, lo, sh)
                b_lo = we.burn_rate(lo, obj.target)
                b_sh = we.burn_rate(sh, obj.target)
                if b_lo > self.slo.max_burn and b_sh > self.slo.max_burn:
                    if not self._firing.get(key):
                        self._firing[key] = True
                        a = {"rule": f"burn:{tenant}:{obj.metric}",
                             "kind": "burn_rate", "severity": "page",
                             "at": round(now, 4), "group": mode,
                             "tenant": tenant, "metric": obj.metric,
                             "windows_s": [lo, sh],
                             "burn": round(max(b_lo, b_sh), 3),
                             "max_rate": self.slo.max_burn,
                             "target": obj.target}
                        if rec.get("tick") is not None:
                            a["tick"] = rec["tick"]
                        out.append(a)
                elif b_lo <= self.slo.max_burn:
                    # The long window recovered: re-arm.
                    self._firing[key] = False
        return out


_RULE_CLASSES = {
    "threshold": _ThresholdRule,
    "rate_of_change": _RateRule,
    "absence": _AbsenceRule,
}


def parse_rules(specs: list[dict]) -> list:
    rules = []
    names = set()
    for spec in specs:
        kind = spec.get("kind")
        if kind == "burn_rate":
            raise ValueError(
                "burn_rate rules are derived from the SLO spec's "
                '"tenants"/"burn" sections, not spelled in "rules"'
            )
        cls = _RULE_CLASSES.get(kind)
        if cls is None:
            raise ValueError(
                f"alert rule {spec.get('name')!r}: unknown kind {kind!r} "
                f"(want one of {ALERT_KINDS})"
            )
        if not spec.get("name"):
            raise ValueError(f"alert rule missing a name: {spec}")
        if spec["name"] in names:
            raise ValueError(f"duplicate alert rule name {spec['name']!r}")
        names.add(spec["name"])
        try:
            rules.append(cls(spec))
        except KeyError as e:
            raise ValueError(
                f"alert rule {spec['name']!r}: missing key {e}"
            ) from e
    return rules


class AlertEngine:
    """The streaming fold: rules + (optionally) SLO burn accounting.

    `ingest(record)` returns the alert field dicts the record tripped,
    each stamped with a monotonically increasing "seq"; `alerts`
    accumulates them all and `crc` pins the sequence. Attach live with
    `attach(metrics_logger)` (observes every record the logger writes
    and logs the resulting alerts back through it), or fold a finished
    file with `replay(records)`.
    """

    def __init__(self, rules: list[dict] | None = None,
                 slo: SLOSpec | None = None):
        specs = list(rules or ())
        if slo is not None:
            specs = specs + list(slo.rules)
        self.rules = parse_rules(specs)
        if slo is not None:
            self.rules.append(_BurnRule(slo))
        self.slo = slo
        self.alerts: list[dict] = []
        # Event-indexed dispatch: threshold/rate rules see only their
        # own family; absence (any timeline record is its clock) and
        # burn rules see everything. The fold runs per record of a
        # 10^5-request storm — the index is what keeps it cheap.
        self._timeline_rules = [r for r in self.rules
                                if isinstance(r, (_AbsenceRule, _BurnRule))]
        self._by_event: dict[str, list] = {}
        for r in self.rules:
            if not isinstance(r, (_AbsenceRule, _BurnRule)):
                self._by_event.setdefault(r.event, []).append(r)

    def ingest(self, rec: dict, event: str | None = None) -> list[dict]:
        """Fold one record; `event` overrides rec["event"] (the benches'
        sink tee passes bare tick/fleet field dicts without copying)."""
        if not isinstance(rec, dict):
            return []
        ev = event if event is not None else rec.get("event")
        if ev == "alert":
            return []
        fired = []
        for rule in self._by_event.get(ev, ()):
            fired.extend(rule.ingest(ev, rec))
        for rule in self._timeline_rules:
            fired.extend(rule.ingest(ev, rec))
        for a in fired:
            a["seq"] = len(self.alerts)
            self.alerts.append(a)
        return fired

    def replay(self, records) -> list[dict]:
        for rec in records:
            self.ingest(rec)
        return self.alerts

    def attach(self, metrics) -> None:
        """Wire into a MetricsLogger: every record it logs is ingested,
        and fired alerts are logged straight back (the reentrant log of
        an `alert` record is ignored by ingest, so this terminates)."""

        def observer(rec: dict) -> None:
            for a in self.ingest(rec):
                metrics.log("alert", **a)

        metrics.observer = observer

    @property
    def crc(self) -> int:
        return alerts_crc(self.alerts)


def alert_site(a: dict) -> str:
    """The alert's location label, by specificity: tenant (burn),
    per-group (grouped rules), field (threshold/rate), watched family
    (absence)."""
    return (a.get("tenant") or a.get("group") or a.get("field")
            or a.get("family") or "")


def format_alert(a: dict) -> str:
    """The one-line alert rendering `mctpu health` and the `mctpu top`
    ALERTS panel share — one spelling, so the two surfaces cannot
    drift as alert kinds grow context fields."""
    tick = f" tick {a['tick']}" if a.get("tick") is not None else ""
    return (f"[{a.get('seq')}] {a.get('rule')} "
            f"({a.get('kind')}, {a.get('severity')}) "
            f"{alert_site(a)} at t={a.get('at'):g}{tick}")


def alerts_crc(alerts: list[dict]) -> int:
    """crc32 over the canonical identity of every alert in sequence —
    the one number the determinism gate holds at exact equality. The
    identity covers (seq, rule, kind, group, tenant, tick, at): enough
    to pin ordering, cause, and timing without depending on rounding of
    derived context fields — absent keys hash as null, so the CRC of a
    sequence rebuilt from logged records matches the live engine's."""
    key = [[a.get("seq"), a.get("rule"), a.get("kind"), a.get("group"),
            a.get("tenant"), a.get("tick"), a.get("at")]
           for a in alerts]
    return zlib.crc32(json.dumps(key).encode())
