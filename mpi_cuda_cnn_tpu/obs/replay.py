"""Deterministic flight-recorder replay: `mctpu replay` (ISSUE 15).

The serving stack is CI-gated on run-vs-run bitwise determinism, but a
failed gate used to say only "trace_crc differs" over a 10^5-request
storm. This module makes the tick trail a REPLAYABLE flight recorder
(the deterministic-replay discipline of Friday, Geels et al., NSDI '07,
applied to the repo's own JSONL trail): producers stamp every tick with
`state_crc` — the crc32 of a canonical projection of their full
host-side serving state (queue order anchors, slot table, page counts,
prefix-tree stats, fence epochs, in-flight handoffs, pool membership;
`serve.scheduler.state_digest` / `serve.router.fleet_state_digest`, the
ONE spelling both sides call) — and this module folds the trail back
into a reconstructed state machine, recomputing that digest at EVERY
tick and exiting 1 on the first drift (the trace/explain cross-check
discipline). obs/diverge.py builds on the same fold to diff two trails
at their first disagreement.

The reconstruction is event-sourced: per-replica scheduler mirrors
apply exactly the events the producers already emit (admitted /
prefill / decoded / spec / preempted / finished / aborted, plus the
ISSUE-15 routing-target and handoff-placement markers), deriving slot
extents, block-table page counts, queue membership, local token
counts, and pool free counts from first principles — page arithmetic
follows the scheduler's own laws (admission allocates
pages_for(context), decode growth lands at pages_for(max(cached,
target)), spec commit rolls back to pages_for(cached)). Along the way
it audits conservation invariants: the reconstructed free-page count
must equal the recorded one at every tick (pages), every fence grant
must move an epoch forward (fences), and every request must reach a
terminal status at most once (rid accounting).

Deliberately jax-free (`mctpu lint` MCT001): reads records, folds
integers, prints tables, sets an exit code. Exit contract (the
regress/health convention): 0 clean replay, 1 digest drift or
invariant violation, 2 config/legacy-trail errors (a pre-ISSUE-15
trail without `state_crc` cannot be replayed — regenerate the run).
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import deque

from ..serve.pool import pages_for
from ..serve.router import fence_chain, fleet_state_digest
from ..serve.scheduler import _rid_sig, state_digest
from ..serve.transport import COUNTER_KEYS, transport_digest_tuple
from .schema import fmt_cell as _fmt
from .schema import iter_runs

_PREFIX_STATS = ("hits", "misses", "hit_tokens", "cow_copies",
                 "inserts", "evictions")
# Host-tier counters (ISSUE 17), adopted cumulatively from the tick
# record's prefix block like cow/inserts/evictions: the readmit delta
# drives the free-page law (a readmission allocates a device page with
# no other trail event), the rest ride the digest's tier tuple.
_TIER_STATS = ("spills", "readmits", "refusals", "host_evictions")


class ReplayError(Exception):
    """Config/legacy-trail problem (CLI exit 2): the trail cannot be
    replayed at all — as opposed to a replay that RAN and drifted."""


class DriftError(Exception):
    """The reconstruction disagreed with the producer (CLI exit 1)."""

    def __init__(self, msg: str, *, tick=None, stream=None, rids=()):
        super().__init__(msg)
        self.tick = tick
        self.stream = stream
        self.rids = tuple(rids)


class _Slot:
    """One reconstructed engine slot."""

    __slots__ = ("rid", "cached", "target", "npages", "nrefs", "terminal")

    def __init__(self, rid, cached, target, npages, nrefs):
        self.rid = rid
        self.cached = cached
        self.target = target
        self.npages = npages
        self.nrefs = nrefs
        self.terminal = False  # static reserve-until-drain flag


class SchedMirror:
    """One scheduler's state, reconstructed purely from its tick
    records. `apply` replays one tick's events in the producer's
    order, then `check` recomputes the canonical digest against the
    stamped one."""

    def __init__(self, *, label: str, slots: int, num_pages: int,
                 page_size: int, reqinfo: dict, static: bool = False,
                 prefix: bool = False, spec_extra=(0, 0), tier: bool = False,
                 draft_usable: int = 0):
        self.label = label
        self.slots: list[_Slot | None] = [None] * slots
        self.queue: deque[int] = deque()
        self.queue_sig = 0
        self.free = num_pages - 1
        self.page_size = page_size
        self.reqinfo = reqinfo          # rid -> (prompt_tokens, max_new)
        self.static = static
        self.prefix = prefix
        self.spec_extra = tuple(spec_extra)
        self.outlen: dict[int, int] = {}   # rid -> replica-LOCAL tokens
        # Prefix-tree stats: hits/misses/hit_tokens derived from the
        # events; cow/inserts/evictions adopted from the per-tick
        # cumulative stats block (their deltas drive the free-page and
        # refs accounting, and the digest pins the adopted values).
        self.pstats = dict.fromkeys(_PREFIX_STATS, 0)
        # Host spill tier (ISSUE 17): cumulative tier counters + host
        # occupancy, adopted from the tick record's prefix block; the
        # readmit delta joins the free-page law and the whole tuple
        # joins the digest (the producer's PrefixCache.digest_tuple).
        self.tier = tier
        self.tstats = dict.fromkeys(_TIER_STATS, 0)
        self.host_used = 0
        # Paged draft cache (ISSUE 17): per-engine-slot draft page
        # counts re-derived from the spec rounds via the page law
        # "after a slot's round the draft holds pages_for(committed
        # rows)" — entries persist lazily across slot release, exactly
        # like the producer's PagedDraftProposer state.
        self.draft_usable = draft_usable       # 0 = no paged draft
        self.draft_pages: dict[int, int] = {}  # engine slot idx -> pages

    # -- queue ops (mirroring the scheduler's _q_* helpers) ------------

    def q_append(self, rid: int) -> None:
        self.queue.append(rid)
        self.queue_sig ^= _rid_sig(rid)

    def _q_appendleft(self, rid: int) -> None:
        self.queue.appendleft(rid)
        self.queue_sig ^= _rid_sig(rid)

    def _q_remove(self, rid: int) -> bool:
        if not self.queue:
            return False
        if self.queue[0] == rid:
            self.queue.popleft()
        else:
            try:
                self.queue.remove(rid)
            except ValueError:
                return False
        self.queue_sig ^= _rid_sig(rid)
        return True

    # -- helpers -------------------------------------------------------

    def _slot_of(self, rid: int) -> tuple[int, _Slot] | None:
        for i, s in enumerate(self.slots):
            if s is not None and s.rid == rid:
                return i, s
        return None

    def _release(self, i: int) -> None:
        s = self.slots[i]
        self.free += s.npages - s.nrefs
        self.slots[i] = None

    def _req(self, rid: int, tick, what: str):
        info = self.reqinfo.get(rid)
        if info is None:
            raise DriftError(
                f"{self.label}: tick {tick}: {what} for rid {rid} with no "
                "request record in the trail", tick=tick,
                stream=self.label, rids=[rid])
        return info[0], info[1]

    def seed_queue(self) -> None:
        """An engine run submits the WHOLE workload up front (sorted by
        (arrival, rid) — make_workload arrivals are monotone in rid, so
        the rounded arrival_s preserves the order); fleet replica queues
        start empty and fill via the dispatch markers."""
        for rid, _info in sorted(self.reqinfo.items(),
                                 key=lambda kv: (kv[1][2], kv[0])):
            self.q_append(rid)

    # -- the fold ------------------------------------------------------

    def apply(self, rec: dict) -> tuple[int, _Slot] | None:
        tick = rec.get("tick")
        ps = self.page_size
        hits = {rid: m for rid, m in rec.get("prefix_hits") or []}
        prec = rec.get("prefix")
        evict_delta = 0
        insert_delta = 0
        readmit_delta = 0
        if prec is not None:
            insert_delta = prec["inserts"] - self.pstats["inserts"]
            evict_delta = prec["evictions"] - self.pstats["evictions"]
            self.pstats["cow_copies"] = prec["cow_copies"]
            self.pstats["inserts"] = prec["inserts"]
            self.pstats["evictions"] = prec["evictions"]
            if self.tier:
                readmit_delta = prec["readmits"] - self.tstats["readmits"]
                for k in _TIER_STATS:
                    self.tstats[k] = prec[k]
                self.host_used = prec["host_used"]
        # LRU reclaim returns tree leaves to the pool (admission or
        # growth pressure — the tick's eviction delta is the only
        # trace); a host-tier readmission pulls one page back OUT per
        # readmit (the tier's re-insert allocates a fresh device page,
        # which the requesting slot then shares like any resident hit).
        self.free += evict_delta - readmit_delta

        # 1. Aborts (sweep expiries/cancels, queue-bound rejections,
        # livelock failures): wherever the rid sits. Static in-flight
        # aborts HOLD their reservation until the batch drains.
        for rid, _status in rec.get("aborted") or []:
            at = self._slot_of(rid)
            if at is not None:
                if self.static:
                    at[1].terminal = True
                else:
                    self._release(at[0])
            else:
                self._q_remove(rid)

        # 2. Admissions: bind at the recorded slot. The page law:
        # admission allocates pages_for(context) (static: the worst-case
        # reservation), a prefix hit leads with matched//ps shared pages.
        for idx, rid in rec.get("admitted") or []:
            prompt, max_new = self._req(rid, tick, "admission")
            out = self.outlen.setdefault(rid, 0)
            target = prompt + out
            m = hits.get(rid, 0)
            nrefs = m // ps
            if self.static:
                npages = pages_for(target + max_new - 1, ps)
            else:
                npages = pages_for(target, ps)
            if self.slots[idx] is not None:
                raise DriftError(
                    f"{self.label}: tick {tick}: admission of rid {rid} "
                    f"into occupied slot {idx}", tick=tick,
                    stream=self.label, rids=[rid])
            self.slots[idx] = _Slot(rid, m, target, npages, nrefs)
            self.free -= npages - nrefs
            self._q_remove(rid)
            if self.prefix:
                if m > 0:
                    self.pstats["hits"] += 1
                    self.pstats["hit_tokens"] += m
                else:
                    self.pstats["misses"] += 1

        # 3. The prefill chunk (at most one per tick). A completing
        # chunk emits the first token; with sharing on, the completed
        # prompt's new pages adopt into the tree (the tick's insert
        # delta) and the slot becomes their first reader.
        pf = rec.get("prefill")
        detached = None
        if pf:
            at = self._slot_of(pf[1])
            if at is None:
                raise DriftError(
                    f"{self.label}: tick {tick}: prefill for rid {pf[1]} "
                    "with no bound slot", tick=tick, stream=self.label,
                    rids=[pf[1]])
            s = at[1]
            s.cached += pf[2]
            if s.cached >= s.target:
                if insert_delta:
                    s.nrefs += insert_delta
                if pf[-1] == "emit":
                    self.outlen[s.rid] = self.outlen.get(s.rid, 0) + 1
            detached = at  # candidate for a fleet KV handoff (caller)

        # 4. Preemptions: release + requeue at the head, in log order.
        for rid in rec.get("preempted") or []:
            at = self._slot_of(rid)
            if at is None:
                raise DriftError(
                    f"{self.label}: tick {tick}: preemption of rid {rid} "
                    "with no bound slot", tick=tick, stream=self.label,
                    rids=[rid])
            self._release(at[0])
            self._q_appendleft(rid)

        # 5. The decode tick / speculative round. Page law: growth
        # lands at pages_for(max(cached, target)); a spec commit's
        # rejected-draft rollback lands there too (cached >= target in
        # decode, so the two spellings agree).
        spec = rec.get("spec")
        if spec is not None:
            slot_of_rid = {rid: idx for idx, rid in rec.get("decoded") or []}
            for rid, _proposed, accepted in spec:
                at = self._slot_of(rid)
                if at is None:
                    raise DriftError(
                        f"{self.label}: tick {tick}: spec round for rid "
                        f"{rid} with no bound slot", tick=tick,
                        stream=self.label, rids=[rid])
                s = at[1]
                if self.draft_usable:
                    # The paged-draft page law (ISSUE 17): after this
                    # slot's round the draft holds pages_for(committed
                    # rows), committed rows = context-1 at propose time
                    # (the last committed token is the round's input,
                    # not yet a draft cache row).
                    prompt, _mx = self._req(rid, tick, "draft round")
                    rows = prompt + self.outlen.get(rid, 0) - 1
                    self.draft_pages[slot_of_rid[rid]] = pages_for(rows, ps)
                j = 1 + accepted
                s.cached += j
                self.outlen[rid] = self.outlen.get(rid, 0) + j
                new = pages_for(max(s.cached, s.target), ps)
                self.free -= new - s.npages
                s.npages = new
        else:
            for _idx, rid in rec.get("decoded") or []:
                at = self._slot_of(rid)
                if at is None:
                    raise DriftError(
                        f"{self.label}: tick {tick}: decode for rid {rid} "
                        "with no bound slot", tick=tick,
                        stream=self.label, rids=[rid])
                s = at[1]
                s.cached += 1
                self.outlen[rid] = self.outlen.get(rid, 0) + 1
                if not self.static:
                    new = pages_for(max(s.cached, s.target), ps)
                    self.free -= new - s.npages
                    s.npages = new

        # 6. Finishes release immediately under continuous batching;
        # static finishes arrive all at once at the drain.
        for rid in rec.get("finished") or []:
            at = self._slot_of(rid)
            if at is not None:
                self._release(at[0])
        if self.static:
            occupied = [i for i, s in enumerate(self.slots) if s is not None]
            if occupied and all(self.slots[i].terminal for i in occupied):
                # The drain's aborted leg: terminal rows held their
                # reservation until the whole batch ended (no event
                # marks it — the batch_done law is mirrored instead).
                for i in occupied:
                    self._release(i)
        return detached

    def digest(self, squeezed: int = 0) -> int:
        slots: list[int] = []
        for i, s in enumerate(self.slots):
            if s is not None:
                slots.extend((i, s.rid, s.cached, s.target, s.npages,
                              s.nrefs))
        prefix = None
        if self.prefix:
            st = self.pstats
            # Node count: inserts - evictions, plus readmits with a
            # tier on (a readmitted node re-enters the tree without an
            # insert — the producer counts only fresh adoptions).
            nodes = st["inserts"] - st["evictions"]
            if self.tier:
                nodes += self.tstats["readmits"]
            prefix = (nodes, st["hits"],
                      st["misses"], st["hit_tokens"], st["cow_copies"],
                      st["inserts"], st["evictions"])
            if self.tier:
                prefix += (self.tstats["spills"], self.tstats["readmits"],
                           self.tstats["refusals"],
                           self.tstats["host_evictions"], self.host_used)
        extra = self.spec_extra
        if self.draft_usable:
            # The paged-draft digest extension (engine.run's spelling):
            # (spec on, k, draft paged, free draft pages, tracked slots).
            extra = extra + (1,
                             self.draft_usable
                             - sum(self.draft_pages.values()),
                             len(self.draft_pages))
        q = self.queue
        return state_digest(len(q), q[0] if q else -1, q[-1] if q else -1,
                            self.queue_sig, slots, self.free - squeezed,
                            prefix, extra)

    def check(self, rec: dict) -> None:
        """The per-tick cross-check: recomputed digest == stamped, and
        the free-page conservation audit (a split error message — the
        pages invariant is the one that names the leak directly)."""
        tick = rec.get("tick")
        squeezed = rec.get("squeezed", 0)
        if self.free - squeezed != rec["free_pages"]:
            raise DriftError(
                f"{self.label}: tick {tick}: page conservation violated — "
                f"reconstructed free {self.free - squeezed} != recorded "
                f"{rec['free_pages']}", tick=tick, stream=self.label)
        got = self.digest(squeezed)
        if got != rec["state_crc"]:
            raise DriftError(
                f"{self.label}: tick {tick}: state digest drift — "
                f"recomputed {got} != stamped {rec['state_crc']}",
                tick=tick, stream=self.label)

    def snapshot(self) -> dict:
        q = self.queue
        out = {
            "label": self.label,
            "slots": [[i, s.rid, s.cached, s.target, s.npages, s.nrefs]
                      for i, s in enumerate(self.slots) if s is not None],
            "queue_len": len(q),
            "queue_head": q[0] if q else None,
            "queue_tail": q[-1] if q else None,
            "free_pages": self.free,
        }
        if self.prefix:
            out["prefix"] = dict(self.pstats)
        if self.tier:
            out["tier"] = {**self.tstats, "host_used": self.host_used}
        if self.draft_usable:
            out["draft"] = {
                "free": self.draft_usable - sum(self.draft_pages.values()),
                "tracked": len(self.draft_pages),
            }
        return out


class _Member:
    __slots__ = ("name", "phase", "draining", "alive", "gen", "sched")

    def __init__(self, name, phase, gen, sched):
        self.name = name
        self.phase = phase
        self.draining = False
        self.alive = True
        self.gen = gen
        self.sched = sched


class _HandoffM:
    __slots__ = ("rid", "src", "src_gen", "pages", "private", "cached",
                 "outlen", "state", "dst", "dst_gen")

    def __init__(self, rid, src, src_gen, pages, private, cached, outlen):
        self.rid = rid
        self.src = src
        self.src_gen = src_gen
        self.pages = pages
        self.private = private
        self.cached = cached
        self.outlen = outlen
        self.state = "pending"
        self.dst = None
        self.dst_gen = -1


class FleetMirror:
    """The fleet-level reconstruction: membership, fences, handoffs,
    and one SchedMirror per replica incarnation. Replica lifecycle
    comes from the `replica` records (indexed by tick), routing targets
    from the fleet records' ISSUE-15 fields."""

    def __init__(self, *, config: dict, reqinfo: dict):
        self.cfg = config
        self.reqinfo = reqinfo
        self.members: dict[str, _Member] = {}
        self._gen: dict[str, int] = {}
        self._phase_of: dict[str, str | None] = {}
        self.handoffs: dict[int, _HandoffM] = {}
        self.fence_crc = 0
        self.epochs: dict[int, int] = {}
        self.pending = len(reqinfo)
        self.redispatch: deque[int] = deque()
        self.terminal: set[int] = set()
        # Lossy transport (ISSUE 20): the latest adopted per-tick bus
        # block (None = bus off), and the dispatches granted but not
        # yet wire-delivered — rid -> (replica name, resume outlen).
        # The bus's internals (retransmit timers, dedup stores) are not
        # event-sourced; the mirror adopts the producer's block after
        # AUDITING its invariants (conservation + counter monotonicity)
        # and folds it through the SAME transport_digest_tuple spelling.
        self.transport: dict | None = None
        self._inflight: dict[int, tuple[str, int]] = {}
        pools = config.get("pools")
        n = int(config.get("replicas_initial") or config.get("replicas", 0))
        phases: list[str | None] = [None] * n
        if pools:
            phases = (["prefill"] * int(pools["prefill"])
                      + ["decode"] * int(pools["decode"]))
        for i, phase in enumerate(phases):
            self._add_member(f"r{i}", phase)

    def _spec_extra(self):
        on = self.cfg.get("spec", "off") != "off"
        return (1 if on else 0, int(self.cfg.get("spec_k", 0)) if on else 0)

    def _add_member(self, name: str, phase) -> _Member:
        gen = self._gen.get(name, -1) + 1
        self._gen[name] = gen
        # Names keep their pool across restarts (the fleet's
        # _phase_of law): remember it for the restart path.
        self._phase_of[name] = phase
        sched = SchedMirror(
            label=f"fleet/{name}", slots=int(self.cfg["slots"]),
            num_pages=int(self.cfg["pages"]),
            page_size=int(self.cfg["page_size"]), reqinfo=self.reqinfo,
            prefix=bool(self.cfg.get("prefix_cache")),
            spec_extra=self._spec_extra(),
            tier=bool(self.cfg.get("host_pages")),
        )
        m = _Member(name, phase, gen, sched)
        self.members[name] = m
        return m

    # -- fence chain (the ONE router.fence_chain spelling) -------------

    def _grant(self, rid: int, name: str) -> None:
        epoch = self.epochs.get(rid, -1) + 1
        self.epochs[rid] = epoch
        self.fence_crc = fence_chain(self.fence_crc, "g", rid, name, epoch)

    def _revoke(self, rid: int) -> None:
        self.fence_crc = fence_chain(self.fence_crc, "r", rid)

    # -- liveness (incarnation-exact, like the producer's checks) ------

    def _live(self, name: str, gen: int) -> bool:
        m = self.members.get(name)
        return m is not None and m.gen == gen and m.alive

    # -- replica lifecycle events --------------------------------------

    def apply_replica_event(self, ev: dict) -> None:
        kind, name = ev.get("kind"), ev.get("name")
        if kind == "join":
            pools = self.cfg.get("pools")
            phase = ev.get("pool")
            if phase is None and pools:
                phase = "decode"  # the unlabeled-join law (fleet.py)
            self._add_member(name, phase)
        elif kind == "crash":
            m = self.members.get(name)
            if m is not None:
                m.alive = False
        elif kind == "dead":
            for rid in ev.get("stranded") or []:
                self._revoke(rid)
                self.redispatch.append(rid)
                # A dispatch still on the wire to the dead incarnation
                # can never produce a t_delivered marker (deliveries
                # are stamped for CURRENT incarnations only) — the
                # harvest strands it and re-dispatch will re-stash it.
                self._inflight.pop(rid, None)
            self.members.pop(name, None)
        elif kind == "restart":
            if self.members.get(name) is None:
                # Names keep their pool across restarts: whatever phase
                # this name joined with (initial plan or a pooled join).
                self._add_member(name, self._phase_of.get(name))
        elif kind == "leave":
            m = self.members.get(name)
            if m is not None:
                m.draining = True
        elif kind == "drain_complete":
            self.members.pop(name, None)
        # restart_scheduled / circuit_open / degraded / restored carry
        # no digested state.

    # -- fleet (router) records ----------------------------------------

    def _handoff(self, rid: int, tick, what: str) -> _HandoffM:
        ho = self.handoffs.get(rid)
        if ho is None:
            raise DriftError(
                f"fleet: tick {tick}: {what} for rid {rid} with no "
                "in-flight handoff (tampered or truncated trail)",
                tick=tick, stream="fleet", rids=[rid])
        return ho

    def _member(self, name: str, tick, what: str) -> _Member:
        m = self.members.get(name)
        if m is None:
            raise DriftError(
                f"fleet: tick {tick}: {what} names {name}, which is not "
                "a member (tampered or truncated trail)", tick=tick,
                stream="fleet")
        return m

    def _adopt_transport(self, rec: dict) -> None:
        """Audit + adopt the record's bus block (ISSUE 20). The audits
        are what make adoption more than trust: conservation must hold
        bitwise (sent == delivered + deduped + dropped + inflight) and
        every counter must be monotone vs the previous tick's block —
        a truncated/tampered/nondeterministic trail trips one of them
        before the digest would even be compared."""
        t = rec.get("transport")
        if t is None:
            return
        tick = rec.get("tick")
        c = {k: int(t[k]) for k in COUNTER_KEYS}
        wire = c["sent"] - c["delivered"] - c["deduped"] - c["dropped"]
        if wire != int(t["inflight"]):
            raise DriftError(
                f"fleet: tick {tick}: transport conservation violated — "
                f"sent {c['sent']} != delivered {c['delivered']} + "
                f"deduped {c['deduped']} + dropped {c['dropped']} + "
                f"inflight {t['inflight']}", tick=tick, stream="fleet")
        if self.transport is not None:
            for k in COUNTER_KEYS:
                if c[k] < int(self.transport[k]):
                    raise DriftError(
                        f"fleet: tick {tick}: transport counter {k} "
                        f"went backwards ({self.transport[k]} -> "
                        f"{c[k]})", tick=tick, stream="fleet")
        self.transport = t

    def apply_fleet(self, rec: dict) -> None:
        tick = rec.get("tick")
        self._adopt_transport(rec)
        for t in rec.get("t_terminal") or []:
            self.terminal.add(t["id"])
        for rid, reason in rec.get("handoff_aborted") or []:
            ho = self._handoff(rid, tick, "handoff abort")
            del self.handoffs[rid]
            if self._live(ho.src, ho.src_gen):
                self.members[ho.src].sched.free += ho.private
            if (ho.dst is not None and reason != "receiver_dead"
                    and self._live(ho.dst, ho.dst_gen)):
                self.members[ho.dst].sched.free += ho.pages
            self.redispatch.append(rid)
        for rid, dst in rec.get("handoff_unplaced") or []:
            ho = self._handoff(rid, tick, "handoff un-place")
            self._member(dst, tick, "handoff un-place").sched.free += \
                ho.pages
            ho.state, ho.dst = "pending", None
        for rid, dst in rec.get("handoff_placed") or []:
            ho = self._handoff(rid, tick, "handoff placement")
            m = self._member(dst, tick, "handoff placement")
            m.sched.free -= ho.pages
            ho.state, ho.dst, ho.dst_gen = "copying", dst, m.gen
        for rid, dst in rec.get("handoff_done") or []:
            ho = self._handoff(rid, tick, "handoff completion")
            del self.handoffs[rid]
            sched = self._member(dst, tick, "handoff completion").sched
            idx = next((i for i, s in enumerate(sched.slots) if s is None),
                       None)
            if idx is None:
                raise DriftError(
                    f"fleet: tick {tick}: handoff bind for rid {rid} with "
                    f"no free slot on {dst}", tick=tick, stream="fleet",
                    rids=[rid])
            sched.slots[idx] = _Slot(rid, ho.cached, ho.cached, ho.pages, 0)
            sched.outlen[rid] = ho.outlen
            self._grant(rid, dst)
            if self._live(ho.src, ho.src_gen):
                self.members[ho.src].sched.free += ho.private
        bus = "transport" in rec
        for rid, name, outl in rec.get("redispatched_to") or []:
            if not self.redispatch or self.redispatch[0] != rid:
                raise DriftError(
                    f"fleet: tick {tick}: re-dispatch of rid {rid} out of "
                    "queue order", tick=tick, stream="fleet", rids=[rid])
            self.redispatch.popleft()
            self._grant(rid, name)
            if bus:
                # The grant is the SEND; queue membership waits for the
                # wire (the t_delivered marker, same tick when inline).
                self._inflight[rid] = (name, outl)
            else:
                sched = self._member(name, tick, "re-dispatch").sched
                sched.outlen[rid] = outl
                sched.q_append(rid)
        for rid, name in rec.get("dispatched_to") or []:
            self.pending -= 1
            self._grant(rid, name)
            if bus:
                self._inflight[rid] = (name, 0)
            else:
                sched = self._member(name, tick, "dispatch").sched
                sched.outlen[rid] = 0
                sched.q_append(rid)
        # Wire deliveries LAST: an inline zero-fault delivery rides the
        # same record as its send, and must pop the stash it just made.
        for rid, name in rec.get("t_delivered") or []:
            ent = self._inflight.pop(rid, None)
            if ent is None or ent[0] != name:
                raise DriftError(
                    f"fleet: tick {tick}: wire delivery of rid {rid} to "
                    f"{name} without a matching in-flight dispatch "
                    f"(stashed: {ent})", tick=tick, stream="fleet",
                    rids=[rid])
            sched = self._member(name, tick, "wire delivery").sched
            sched.outlen[rid] = ent[1]
            sched.q_append(rid)

    def fleet_digest(self) -> int:
        return fleet_state_digest(
            ((m.name, m.phase or "", m.draining, m.alive)
             for m in sorted(self.members.values(), key=lambda m: m.name)),
            ((rid, ho.state, ho.src, ho.dst or "")
             for rid, ho in sorted(self.handoffs.items())),
            self.pending, tuple(self.redispatch), self.fence_crc,
            transport=(transport_digest_tuple(self.transport)
                       if self.transport is not None else None))

    def check_fleet(self, rec: dict) -> None:
        tick = rec.get("tick")
        got = self.fleet_digest()
        if got != rec["state_crc"]:
            raise DriftError(
                f"fleet: tick {tick}: router state digest drift — "
                f"recomputed {got} != stamped {rec['state_crc']}",
                tick=tick, stream="fleet")

    # -- replica tick records ------------------------------------------

    def apply_replica_tick(self, rec: dict) -> None:
        tick = rec.get("tick")
        name = rec["mode"].split("/", 1)[1]
        if name == "router":
            # The mass-failure record: every undispatched request
            # failed terminally and both dispatch queues emptied.
            for rid, _status in rec.get("aborted") or []:
                self.terminal.add(rid)
            self.pending = 0
            self.redispatch.clear()
            self._inflight.clear()
            self.check_fleet(rec)
            return
        m = self.members.get(name)
        if m is None:
            raise DriftError(
                f"fleet: tick {tick}: tick record from {name}, which is "
                "not a member", tick=tick, stream=f"fleet/{name}")
        detached = m.sched.apply(rec)
        if detached is not None:
            self._maybe_handoff(m, detached, rec)
        for t in rec.get("terminal") or []:
            self.terminal.add(t["id"])
        if rec["queue"] != len(m.sched.queue):
            raise DriftError(
                f"fleet/{name}: tick {tick}: queue length drift — "
                f"reconstructed {len(m.sched.queue)} != recorded "
                f"{rec['queue']}", tick=tick, stream=f"fleet/{name}")
        m.sched.check(rec)

    def _maybe_handoff(self, m: _Member, detached, rec) -> None:
        """Mirror the _begin_handoff decision: a prefill-pool slot that
        just COMPLETED its prefill with decode work remaining detaches
        into a KV handoff — iff the sender incarnation is a live member
        of a pooled fleet and the decode pool has dispatchable members
        (else it degrades to unified decoding in place)."""
        idx, s = detached
        pf = rec.get("prefill")
        if not (pf and pf[-1] == "emit" and s.cached >= s.target):
            return
        if not self.cfg.get("pools") or m.phase != "prefill":
            return
        if not m.alive:
            return  # a zombie's completed prefill never opens a handoff
        rid = s.rid
        _prompt, max_new = m.sched._req(rid, rec.get("tick"), "handoff")
        if m.sched.outlen.get(rid, 0) >= max_new:
            return  # done at its first token: finished, not handed off
        if rid in self.handoffs or rid in self.terminal:
            return
        if not any(mm.phase == "decode" and not mm.draining
                   for mm in self.members.values()):
            return  # decode pool empty: degraded to unified, slot kept
        self._revoke(rid)
        self.handoffs[rid] = _HandoffM(
            rid, m.name, m.gen, s.npages, s.npages - s.nrefs, s.cached,
            m.sched.outlen.get(rid, 0))
        m.sched.slots[idx] = None  # detached: sealed, nothing freed

    def snapshot(self) -> dict:
        return {
            "members": [[m.name, m.phase or "", m.draining, m.alive]
                        for m in sorted(self.members.values(),
                                        key=lambda m: m.name)],
            "handoffs": [[rid, ho.state, ho.src, ho.dst or ""]
                         for rid, ho in sorted(self.handoffs.items())],
            "pending": self.pending,
            "redispatch": list(self.redispatch),
            "fence_crc": self.fence_crc,
            "replicas": {m.name: m.sched.snapshot()
                         for m in self.members.values()},
            **({"transport": dict(self.transport),
                "wire_inflight": sorted(self._inflight)}
               if self.transport is not None else {}),
        }


# -- run assembly ------------------------------------------------------


def split_run(records: list[dict]) -> dict:
    """Partition one run's records into replayable streams:
    {"engine": {mode: [tick recs]}, "fleet": [fleet+tick recs in file
    order] or None, "configs": {mode: serve rec}, "reqinfo": {mode:
    {rid: (prompt, max_new)}}, "replica_events": {tick: [replica recs]}}.
    Raises ReplayError when the trail has no ticks or predates the
    flight recorder (no state_crc)."""
    engine: dict[str, list[dict]] = {}
    fleet: list[dict] = []
    configs: dict[str, dict] = {}
    reqinfo: dict[str, dict] = {}
    replica_events: dict[int, list[dict]] = {}
    saw_tick = saw_digest = False
    for rec in records:
        ev = rec.get("event")
        if ev == "tick":
            saw_tick = True
            saw_digest = saw_digest or "state_crc" in rec
            mode = rec.get("mode", "?")
            if mode.startswith("fleet/"):
                fleet.append(rec)
            else:
                engine.setdefault(mode, []).append(rec)
        elif ev == "fleet":
            saw_tick = True
            saw_digest = saw_digest or "state_crc" in rec
            fleet.append(rec)
        elif ev == "serve":
            configs[rec.get("mode", "?")] = rec
        elif ev == "request":
            per = reqinfo.setdefault(rec.get("mode", "?"), {})
            if "max_new_tokens" not in rec:
                raise ReplayError(
                    "request records carry no max_new_tokens — "
                    "pre-ISSUE-15 trail; regenerate the run")
            per[rec["id"]] = (rec["prompt_tokens"], rec["max_new_tokens"],
                              rec.get("arrival_s", 0.0))
        elif ev == "replica":
            replica_events.setdefault(rec.get("tick", 0), []).append(rec)
    if not saw_tick:
        raise ReplayError(
            "no tick trail to replay (run with --metrics-jsonl and "
            "--log full)")
    if not saw_digest:
        raise ReplayError(
            "tick records carry no state_crc — pre-ISSUE-15 trail; "
            "regenerate the run with a flight-recorder producer")
    for mode in list(engine) + (["fleet"] if fleet else []):
        if mode not in configs:
            raise ReplayError(
                f"mode {mode!r} has tick records but no serve summary "
                "record — the replay needs the run's geometry")
        if mode not in reqinfo:
            raise ReplayError(
                f"mode {mode!r} has tick records but no request records "
                "— the replay needs per-request prompt/budget info")
    return {"engine": engine, "fleet": fleet or None, "configs": configs,
            "reqinfo": reqinfo, "replica_events": replica_events}


def _engine_mirror(mode: str, cfg: dict, reqinfo: dict) -> SchedMirror:
    spec_on = (mode == "continuous" and cfg.get("spec", "off") != "off")
    draft_usable = 0
    if (spec_on and cfg.get("spec") == "draft"
            and cfg.get("draft_cache") == "paged"):
        # The draft pool's usable size (ISSUE 17): slots x
        # pages_for(max_len) — PagedDraftProposer's full-coverage
        # sizing, so the mirror can re-derive free draft pages from
        # the per-slot page law alone.
        draft_usable = int(cfg["slots"]) * pages_for(
            int(cfg["max_len"]), int(cfg["page_size"]))
    return SchedMirror(
        label=mode, slots=int(cfg["slots"]), num_pages=int(cfg["pages"]),
        page_size=int(cfg["page_size"]), reqinfo=reqinfo,
        static=(mode == "static"),
        prefix=bool(cfg.get("prefix_cache")) and mode == "continuous",
        spec_extra=(1, int(cfg.get("spec_k", 0))) if spec_on else (0, 0),
        tier=(mode == "continuous" and bool(cfg.get("host_pages"))),
        draft_usable=draft_usable,
    )


class RunReplay:
    """One run's full replay: engine-mode mirrors + the fleet mirror,
    folded record by record. `fold` raises DriftError at the first
    disagreement; `fold(collect=...)` records per-digest outcomes and
    keeps going best-effort (the diverge path)."""

    def __init__(self, records: list[dict]):
        self.parts = split_run(records)
        self.mirrors: dict[str, SchedMirror] = {}
        for mode, _ticks in self.parts["engine"].items():
            self.mirrors[mode] = _engine_mirror(
                mode, self.parts["configs"][mode],
                self.parts["reqinfo"][mode])
            self.mirrors[mode].seed_queue()
        self.fleet: FleetMirror | None = None
        if self.parts["fleet"] is not None:
            self.fleet = FleetMirror(config=self.parts["configs"]["fleet"],
                                     reqinfo=self.parts["reqinfo"]["fleet"])
        self.ticks_checked = 0

    def _ordered(self):
        """(kind, stream_key, rec) in replay order. Engine modes fold
        independently; the fleet stream interleaves replica lifecycle
        events (applied at their tick, before that tick's records —
        the producer's own chronology) with router and replica ticks."""
        for mode, ticks in self.parts["engine"].items():
            for rec in ticks:
                yield "engine", (mode, rec.get("tick")), rec
        if self.parts["fleet"] is not None:
            seen_ticks: set[int] = set()
            for rec in self.parts["fleet"]:
                tick = rec.get("tick")
                if tick not in seen_ticks:
                    seen_ticks.add(tick)
                    for ev in self.parts["replica_events"].get(tick, ()):
                        yield "event", ("replica-event", tick), ev
                if rec.get("event") == "fleet":
                    yield "fleet", ("fleet", tick), rec
                else:
                    yield "replica", (rec.get("mode"), tick), rec

    def fold(self, *, stop_tick=None, collect: list | None = None):
        """Replay every record. With `collect`, digest mismatches and
        apply errors are appended as (stream_key, stamped, recomputed,
        error) and the fold continues best-effort (the diverge path);
        without it the first problem raises DriftError. `stop_tick`
        ends the fold after the given tick (the `--at-tick` rendering)."""
        for kind, key, rec in self._ordered():
            tick = key[1]
            if stop_tick is not None and tick is not None \
                    and tick > stop_tick:
                continue
            if kind == "event":
                self.fleet.apply_replica_event(rec)
                continue
            if "state_crc" not in rec:
                raise ReplayError(
                    f"tick record at tick {tick} carries no state_crc — "
                    "pre-ISSUE-15 trail; regenerate the run")
            try:
                if kind == "fleet":
                    self.fleet.apply_fleet(rec)
                    self.fleet.check_fleet(rec)
                elif kind == "replica":
                    self.fleet.apply_replica_tick(rec)
                else:
                    mirror = self.mirrors[key[0]]
                    mirror.apply(rec)
                    mirror.check(rec)
                self.ticks_checked += 1
                if collect is not None:
                    collect.append((key, rec["state_crc"],
                                    rec["state_crc"], None))
            except DriftError as e:
                if collect is None:
                    raise
                collect.append((key, rec.get("state_crc"), None, str(e)))
        return self

    def snapshot(self) -> dict:
        out = {mode: m.snapshot() for mode, m in self.mirrors.items()}
        if self.fleet is not None:
            out["fleet"] = self.fleet.snapshot()
        return out


# -- rendering ---------------------------------------------------------


def _render_sched(snap: dict) -> list[str]:
    lines = [
        f"free pages: {snap['free_pages']}   queue: "
        f"len={snap['queue_len']} head={_fmt(snap['queue_head'])} "
        f"tail={_fmt(snap['queue_tail'])}",
    ]
    if snap["slots"]:
        lines += ["| slot | rid | cached | target | pages | refs |",
                  "|---|---|---|---|---|---|"]
        for i, rid, cached, target, npages, nrefs in snap["slots"]:
            lines.append(f"| {i} | {rid} | {cached} | {target} "
                         f"| {npages} | {nrefs} |")
    else:
        lines.append("(no occupied slots)")
    if "prefix" in snap:
        p = snap["prefix"]
        lines.append(
            "prefix: " + ", ".join(f"{k}={p[k]}" for k in _PREFIX_STATS))
    if "tier" in snap:
        t = snap["tier"]
        lines.append("host tier: " + ", ".join(
            f"{k}={t[k]}" for k in (*_TIER_STATS, "host_used")))
    if "draft" in snap:
        d = snap["draft"]
        lines.append(f"draft pool: free={d['free']} "
                     f"tracked={d['tracked']}")
    return lines


def render_state(snapshot: dict, *, replica: str | None = None) -> str:
    lines: list[str] = []
    for mode in sorted(k for k in snapshot if k != "fleet"):
        lines.append(f"### [{mode}]")
        lines += _render_sched(snapshot[mode])
        lines.append("")
    fleet = snapshot.get("fleet")
    if fleet is not None:
        lines.append("### [fleet]")
        lines.append(
            "members: " + (", ".join(
                f"{n}{'(' + p + ')' if p else ''}"
                f"{'!' if not alive else ''}{'~' if draining else ''}"
                for n, p, draining, alive in fleet["members"]) or "none"))
        lines.append(f"pending: {fleet['pending']}   redispatch queue: "
                     f"{fleet['redispatch']}   fence chain: "
                     f"{fleet['fence_crc']}")
        if fleet["handoffs"]:
            lines.append("handoffs: " + ", ".join(
                f"rid {rid} {state} {src}->{dst or '?'}"
                for rid, state, src, dst in fleet["handoffs"]))
        for name in sorted(fleet["replicas"]):
            if replica is not None and name != replica:
                continue
            lines.append(f"#### replica {name}")
            lines += _render_sched(fleet["replicas"][name])
        lines.append("")
    return "\n".join(lines)


# -- the CLI -----------------------------------------------------------


def replay_main(argv: list[str] | None = None) -> int:
    """`mctpu replay RUN [--at-tick T] [--replica R]` — fold a tick
    trail back into the reconstructed serving state, cross-checking the
    stamped per-tick state digests the whole way. Exit 0 clean, 1 on
    drift/invariant violation, 2 on config/legacy errors."""
    ap = argparse.ArgumentParser(
        prog="mctpu replay",
        description="Deterministic flight-recorder replay: reconstruct "
                    "the full serving state from a run's tick trail, "
                    "cross-checking the stamped state_crc at every tick "
                    "and auditing page/fence/rid conservation.",
    )
    ap.add_argument("path", help="metrics JSONL with a full tick trail")
    ap.add_argument("--at-tick", type=int, default=None,
                    help="render the reconstructed state as of this tick "
                         "(default: end of run)")
    ap.add_argument("--replica", default=None,
                    help="restrict the fleet rendering to one replica")
    ap.add_argument("--format", choices=("md", "json"), default="md")
    args = ap.parse_args(argv)

    try:
        runs = [r for r in iter_runs(args.path) if r]
    except (OSError, ValueError) as e:
        print(f"error: {args.path}: {e}", file=sys.stderr)
        return 2
    if not runs:
        print(f"error: {args.path}: no records", file=sys.stderr)
        return 2
    rc = 0
    for i, records in enumerate(runs, 1):
        label = args.path if len(runs) == 1 \
            else f"{args.path} (run {i}/{len(runs)})"
        try:
            replay = RunReplay(records)
            replay.fold(stop_tick=args.at_tick)
        except ReplayError as e:
            print(f"error: {args.path}: {e}", file=sys.stderr)
            return 2
        except DriftError as e:
            print(f"error: {label}: REPLAY DRIFT — {e}", file=sys.stderr)
            print("the trail does not reproduce its own stamped state: "
                  "producer nondeterminism or a tampered/truncated file",
                  file=sys.stderr)
            rc = max(rc, 1)
            continue
        snap = replay.snapshot()
        if args.format == "json":
            print(json.dumps({
                "path": args.path, "run": i,
                "ticks_checked": replay.ticks_checked,
                "at_tick": args.at_tick, "state": snap,
            }))
        else:
            at = f" at tick {args.at_tick}" if args.at_tick is not None \
                else ""
            print(f"## Replay — {label}{at}\n")
            print(f"{replay.ticks_checked} tick digest(s) cross-checked, "
                  "zero drift\n")
            print(render_state(snap, replica=args.replica))
    return rc


if __name__ == "__main__":
    sys.exit(replay_main())
