"""First-divergence localization: `mctpu diverge A B` (ISSUE 15).

The determinism gates compare two identical-seed runs at 0%/equal; on
failure they used to say only WHICH summary counter drifted. This
module streams two flight-recorder trails (obs/replay.py's fold) in
lockstep and finds the FIRST tick, in the first stream (engine mode /
fleet router / replica), where the two runs' state digests disagree —
then diffs the two reconstructed states at that tick into a
human-readable delta: rid sets, per-slot extent/page changes, queue
and free-page drift, dispatch/handoff decisions, and the surrounding
lifecycle context. "trace_crc differs" over a 10^5-request storm
becomes "tick 4071, replica r2: rid 5513 decoded on A but was
preempted on B (for rid 5498)".

Divergence is judged on BOTH signals per record: the RECORDED
state_crc pair (two genuinely diverged producers stamp different
digests) and each side's own recomputed-vs-stamped drift (a tampered
or truncated trail diverges from itself). Either fires the report, so
the tool serves the CI failure path and the forensic one.

Exit contract: 0 = trails digest-identical end to end, 1 = divergence
found (the report is the output), 2 = config/legacy errors (either
input missing a tick trail or predating the flight recorder).
jax-free (`mctpu lint` MCT001).
"""

from __future__ import annotations

import argparse
import json
import sys

from .replay import ReplayError, RunReplay
from .schema import fmt_cell as _fmt
from .schema import iter_runs

# Tick-record event fields worth echoing as the divergence context.
_CONTEXT_FIELDS = ("admitted", "prefill", "decoded", "spec", "preempted",
                   "preempted_for", "finished", "aborted", "blocked",
                   "dispatched", "redispatched", "failed_over",
                   "handoff_started", "handoff_placed", "handoff_done",
                   "handoff_aborted")


def _last_run(path: str) -> list[dict]:
    runs = [r for r in iter_runs(path) if r]
    if not runs:
        raise ReplayError("no records")
    return runs[-1]


def _fold_collect(records: list[dict]):
    """(RunReplay folded best-effort, collected digest stream).
    Entries: (stream_key, stamped, recomputed|None, error|None)."""
    replay = RunReplay(records)
    collected: list = []
    replay.fold(collect=collected)
    return replay, collected


def _state_at(records: list[dict], stop_key) -> dict:
    """Re-fold up to and including the record at `stop_key`'s position
    (first occurrence), best-effort (the divergent record itself may
    not apply cleanly), and snapshot the state."""
    replay = RunReplay(records)
    for kind, key, rec in replay._ordered():
        if kind == "event":
            replay.fleet.apply_replica_event(rec)
            continue
        try:
            if kind == "fleet":
                replay.fleet.apply_fleet(rec)
            elif kind == "replica":
                replay.fleet.apply_replica_tick(rec)
            else:
                replay.mirrors[key[0]].apply(rec)
        except Exception:
            pass  # best-effort: the divergent record may not apply
        if key == stop_key:
            break
    return replay.snapshot()


def _mirror_of(snapshot: dict, stream) -> dict | None:
    if not isinstance(stream, str):
        return None
    if stream.startswith("fleet/"):
        fleet = snapshot.get("fleet") or {}
        return (fleet.get("replicas") or {}).get(stream.split("/", 1)[1])
    if stream == "fleet":
        return None
    return snapshot.get(stream)


def _diff_sched(a: dict, b: dict) -> list[str]:
    lines: list[str] = []
    sa = {row[0]: row for row in a.get("slots", [])}
    sb = {row[0]: row for row in b.get("slots", [])}
    for idx in sorted(set(sa) | set(sb)):
        ra, rb = sa.get(idx), sb.get(idx)
        if ra == rb:
            continue
        def show(r):
            if r is None:
                return "free"
            return (f"rid {r[1]} cached {r[2]} target {r[3]} "
                    f"pages {r[4]} refs {r[5]}")
        lines.append(f"  slot {idx}: A[{show(ra)}]  B[{show(rb)}]")
    for key, label in (("queue_len", "queue length"),
                       ("queue_head", "queue head"),
                       ("queue_tail", "queue tail"),
                       ("free_pages", "free pages")):
        if a.get(key) != b.get(key):
            lines.append(f"  {label}: A={_fmt(a.get(key))} "
                         f"B={_fmt(b.get(key))}")
    pa, pb = a.get("prefix"), b.get("prefix")
    if pa != pb and (pa or pb):
        for k in sorted(set(pa or {}) | set(pb or {})):
            va, vb = (pa or {}).get(k), (pb or {}).get(k)
            if va != vb:
                lines.append(f"  prefix.{k}: A={_fmt(va)} B={_fmt(vb)}")
    return lines


def _diff_fleet(a: dict, b: dict) -> list[str]:
    lines: list[str] = []
    for key, label in (("members", "members"), ("handoffs", "handoffs"),
                       ("pending", "pending"),
                       ("redispatch", "redispatch queue"),
                       ("fence_crc", "fence chain")):
        if a.get(key) != b.get(key):
            lines.append(f"  {label}: A={_fmt(a.get(key))} "
                         f"B={_fmt(b.get(key))}")
    ra, rb = a.get("replicas") or {}, b.get("replicas") or {}
    for name in sorted(set(ra) | set(rb)):
        sub = _diff_sched(ra.get(name) or {}, rb.get(name) or {})
        if sub:
            lines.append(f"  replica {name}:")
            lines += ["  " + ln for ln in sub]
    return lines


def _rids_in(rec: dict) -> set[int]:
    rids: set[int] = set()
    for field in _CONTEXT_FIELDS:
        v = rec.get(field)
        if not v:
            continue
        if field == "prefill":
            rids.add(v[1])
        else:
            for entry in v:
                rids.add(entry[0] if isinstance(entry, list) else entry)
    return rids


def _find_record(records: list[dict], key) -> dict | None:
    stream, tick = key
    for rec in records:
        if rec.get("tick") != tick:
            continue
        if rec.get("event") == "fleet" and stream == "fleet":
            return rec
        if rec.get("event") == "tick" and rec.get("mode") == stream:
            return rec
    return None


def _context_lines(rec: dict | None, label: str) -> list[str]:
    if rec is None:
        return [f"  {label}: (no matching record)"]
    shown = {f: rec[f] for f in _CONTEXT_FIELDS if rec.get(f)}
    body = ", ".join(f"{k}={json.dumps(v)}" for k, v in shown.items()) \
        or "(no events)"
    return [f"  {label}: {body}"]


def diverge_main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="mctpu diverge",
        description="Localize the first divergent tick between two "
                    "flight-recorder trails (identical-seed runs of a "
                    "determinism-gated storm) and diff the "
                    "reconstructed states into a human-readable delta.",
    )
    ap.add_argument("path_a", help="first run's metrics JSONL (full log)")
    ap.add_argument("path_b", help="second run's metrics JSONL (full log)")
    ap.add_argument("--format", choices=("md", "json"), default="md")
    args = ap.parse_args(argv)

    try:
        recs_a = _last_run(args.path_a)
        recs_b = _last_run(args.path_b)
        _, seq_a = _fold_collect(recs_a)
        _, seq_b = _fold_collect(recs_b)
    except ReplayError as e:
        # The one-line config-error contract (legacy/summary trails).
        print(f"error: {e}", file=sys.stderr)
        return 2
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    div_idx = None
    why = None
    for i in range(min(len(seq_a), len(seq_b))):
        (key_a, stamped_a, _rc_a, err_a) = seq_a[i]
        (key_b, stamped_b, _rc_b, err_b) = seq_b[i]
        if key_a != key_b:
            div_idx, why = i, (f"stream structure differs: A has "
                               f"{key_a}, B has {key_b}")
            break
        if err_a or err_b:
            div_idx = i
            why = "; ".join(filter(None, [
                err_a and f"A drifts from its own stamps: {err_a}",
                err_b and f"B drifts from its own stamps: {err_b}"]))
            break
        if stamped_a != stamped_b:
            div_idx, why = i, (f"stamped state_crc differs: "
                               f"A={stamped_a} B={stamped_b}")
            break
    truncated = False
    if div_idx is None and len(seq_a) != len(seq_b):
        truncated = True
        why = (f"trail lengths differ: A has {len(seq_a)} digest(s), "
               f"B has {len(seq_b)} — one trail ends early")
        div_idx = max(min(len(seq_a), len(seq_b)) - 1, 0)
    if div_idx is None:
        if args.format == "json":
            print(json.dumps({"divergence": None,
                              "digests_compared": len(seq_a)}))
        else:
            print(f"no divergence: {len(seq_a)} per-tick digests "
                  "identical across both trails")
        return 0

    key = seq_a[div_idx][0] if div_idx < len(seq_a) else seq_b[div_idx][0]
    stream, tick = key
    snap_a = _state_at(recs_a, key)
    snap_b = _state_at(recs_b, key)
    rec_a = _find_record(recs_a, key)
    rec_b = _find_record(recs_b, key)
    rids = sorted(_rids_in(rec_a or {}) | _rids_in(rec_b or {}))
    delta: list[str] = []
    if snap_a.get("fleet") is not None or snap_b.get("fleet") is not None:
        # The fleet diff covers every replica mirror (the divergent
        # stream's included), plus membership/handoffs/fences.
        delta += _diff_fleet(snap_a.get("fleet") or {},
                             snap_b.get("fleet") or {})
    else:
        mirror_a = _mirror_of(snap_a, stream)
        mirror_b = _mirror_of(snap_b, stream)
        if mirror_a is not None or mirror_b is not None:
            delta += _diff_sched(mirror_a or {}, mirror_b or {})
    if args.format == "json":
        print(json.dumps({
            "divergence": {"stream": stream, "tick": tick,
                           "index": div_idx, "why": why, "rids": rids},
            "delta": delta,
            "state_a": snap_a, "state_b": snap_b,
        }))
        return 1
    print(f"## Diverge — {args.path_a} vs {args.path_b}\n")
    print(f"first divergence: tick {tick}, stream {stream} "
          f"(digest #{div_idx} of the lockstep fold)")
    print(f"cause: {why}")
    if rids:
        print(f"rids touched at the divergent tick: {rids}")
    print("\nevents at the divergent tick:")
    for line in _context_lines(rec_a, "A") + _context_lines(rec_b, "B"):
        print(line)
    print("\nstate delta after the divergent tick (A vs B):")
    if not delta:
        delta = (["  (states identical at the last common digest — one "
                  "trail simply ends here)"] if truncated else
                 ["  (reconstructed states identical — the divergence "
                  "is in the stamps alone)"])
    for line in delta:
        print(line)
    return 1


if __name__ == "__main__":
    sys.exit(diverge_main())
