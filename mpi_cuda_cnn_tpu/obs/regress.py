"""Perf-regression gate: `mctpu compare A B [--gate thresholds.json]`.

The banked BENCH_r*.json files were compared by eye — a tokens/s
regression would merge silently. This module makes the comparison a
program with an exit code:

- `extract_metrics(path)` flattens a run into {name: value}. It reads
  BOTH shapes in the repo: a metrics JSONL run file (obs.schema — the
  `serve`/`train`/`epoch`/`bench`/`metrics` events become
  "serve.continuous.tokens_per_s"-style names, last run of the file),
  and a driver capture JSON (BENCH_r*.json: one object whose "parsed"
  field holds {metric, value}).
- `compare(base, cand)` evaluates each gated metric directionally
  (tokens/s up is good, ticks/ms down is good) against a per-metric
  tolerance; anything worse than tolerance is a REGRESSION and the CLI
  exits 1 — wired into CI against a committed baseline, so the gate
  runs on every PR instead of at PERF.md-assembly time.
- With more than two files (`mctpu compare BENCH_r*.json`) the LAST
  file is the candidate and the directional BEST of the earlier files
  is the baseline — "did the newest capture regress the trajectory".

Thresholds JSON:

    {"default_tol_pct": 10,
     "metrics": {"serve.continuous.decode_ticks": {"tol_pct": 0},
                 "serve.continuous.tokens_per_s":
                     {"tol_pct": 10, "direction": "higher"},
                 "serve.fleet.trace_crc":
                     {"tol_pct": 0, "direction": "equal"}}}

Directions: "higher" (a drop regresses), "lower" (a rise regresses),
or "equal" (ANY drift regresses — the determinism gate's two-sided
form; never inferred from a name, only explicit).

With --gate only the listed metrics are gated (a listed metric missing
from either side fails loudly — a silently-vanishing metric is how
gates rot). Without --gate, every common metric whose direction is
inferable from its name is gated at 10%.

Deliberately jax-free: reads files, prints a table, sets an exit code.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from .schema import fmt_cell as _fmt
from .schema import iter_runs

DEFAULT_TOL_PCT = 10.0

# Direction inference by name fragment, first match wins. "higher"
# means bigger is better (a drop is a regression); "lower" the
# opposite. Metrics matching neither are informational-only unless a
# thresholds file names them with an explicit direction.
_HIGHER = ("tokens_per_s", "samples_per_s", "accuracy", "acc", "mfu",
           "speedup", "vs_baseline", "requests_finished")
_LOWER = ("_ms", "ticks", "chunks", "preemptions", "restarts", "loss",
          "ppl", "bytes", "nonfinite", "wallclock", "seconds",
          "watchdog", "requests_failed", "requests_expired",
          "requests_rejected", "alerts_fired")


def infer_direction(name: str) -> str | None:
    low = name.lower()
    for frag in _HIGHER:
        if frag in low:
            return "higher"
    for frag in _LOWER:
        if frag in low:
            return "lower"
    # A trailing "_s" is a duration (duration_s, epoch.last_s) — but
    # only as a suffix: "last_step" is not a time.
    if low.endswith("_s"):
        return "lower"
    return None


def _num(v) -> float | None:
    return float(v) if isinstance(v, (int, float)) \
        and not isinstance(v, bool) else None


# serve-event keys worth gating (the engine summary's numeric columns,
# plus the fleet summary's structural counts — absent keys are skipped,
# so single-engine records don't grow phantom fleet metrics). The
# statuses dict is additionally flattened to serve.<mode>.status.<k>:
# the fleet determinism gate pins per-status totals at exact equality.
_SERVE_KEYS = ("tokens_per_s", "decode_ticks", "prefill_chunks",
               "preemptions", "output_tokens", "requests",
               "watchdog_slow_ticks", "ttft_p50_ms", "ttft_p99_ms",
               "tpot_p50_ms", "tpot_p99_ms", "duration_s",
               "fleet_ticks", "dispatches", "redispatches",
               "fenced_discards", "crashes", "joins", "leaves",
               "restarts", "circuit_opens", "replicas", "trace_crc",
               "alerts_fired", "alerts_crc",
               # Flight recorder (ISSUE 15): the per-tick state-digest
               # chain — the determinism gates pin it at 0%/equal, and
               # a failure's next step is `mctpu diverge A B`.
               "state_crc",
               # Prefix-sharing structural counters (ISSUE 9).
               "prefix_hits", "prefix_misses", "prefix_hit_tokens",
               "prefix_cow", "prefix_inserts", "prefix_evictions",
               # Causal-blame attribution (ISSUE 11): the canonical
               # per-request blame CRC plus per-category tick totals —
               # the fleet determinism gate pins them at exact equality.
               "blame_crc", "blame_self_compute", "blame_queued_behind",
               "blame_preempted_by", "blame_redispatch_replay",
               "blame_router_wait", "blame_quota_ticks",
               # Disaggregated serving (ISSUE 13): handoff / integrity
               # / degradation counters plus the handoff-wait blame
               # category — the disagg determinism gate pins them at
               # exact equality (zeros on a unified fleet).
               "blame_handoff_wait", "handoffs", "handoff_pages",
               "handoffs_aborted", "kv_refusals", "degraded_unified",
               # Batched speculative decoding (ISSUE 14): rounds run,
               # draft tokens proposed/accepted — the fleet/spec
               # determinism gates pin them at exact equality (zeros
               # on a spec-off run).
               "spec_rounds", "spec_proposed", "spec_accepted",
               # Host-tier KV spill (ISSUE 17): spill / readmission /
               # CRC-refusal / host-LRU-eviction counters — the
               # fleet/spec/disagg determinism gates pin them at exact
               # equality (zeros on a spill-off run).
               "tier_spills", "tier_readmits", "tier_refusals",
               "tier_host_evictions",
               # Cache-aware routing + autoscaling (ISSUE 18): routed-
               # dispatch counters, scale-event totals, the cumulative
               # live-replica integral, and the scale-event CRC chain —
               # the fleet/autoscale determinism gates pin them at
               # exact equality (zeros/empty-CRC on a hash-routed or
               # fixed-size fleet).
               "route_hits", "route_misses", "route_hit_tokens",
               "scale_ups", "scale_downs", "replica_ticks", "scale_crc",
               # Lossy transport (ISSUE 20): bus wire accounting,
               # lease refusals, partition count, and the transport-
               # wait blame category — the fleet/transport determinism
               # gates pin them at exact equality (zeros with the bus
               # off).
               "msgs_sent", "msgs_delivered", "msgs_dropped",
               "msgs_duped", "msgs_delayed", "msgs_deduped",
               "retransmits", "lease_refusals", "partitions",
               "blame_transport_wait")

# Per-tenant summary keys (ISSUE 8): the "tenants" block of a serve
# summary flattens to serve.<mode>.tenant.<name>.<key> (statuses to
# ...tenant.<name>.status.<k>), so an SLO-class gate can pin one
# tenant's p99 or finished count without gating the rest.
_TENANT_KEYS = ("requests", "output_tokens", "ttft_p50_ms", "ttft_p99_ms",
                "tpot_p50_ms", "tpot_p99_ms")


def metrics_from_records(records: list[dict]) -> dict[str, float]:
    """Flatten one run's records into {metric_name: value}; later
    records of the same name win (the run's final state)."""
    out: dict[str, float] = {}
    for rec in records:
        ev = rec.get("event")
        if ev == "serve":
            mode = rec.get("mode", "?")
            for k in _SERVE_KEYS:
                v = _num(rec.get(k))
                if v is not None:
                    out[f"serve.{mode}.{k}"] = v
            for k, v in (rec.get("statuses") or {}).items():
                v = _num(v)
                if v is not None:
                    out[f"serve.{mode}.status.{k}"] = v
            for tname, block in (rec.get("tenants") or {}).items():
                for k in _TENANT_KEYS:
                    v = _num(block.get(k))
                    if v is not None:
                        out[f"serve.{mode}.tenant.{tname}.{k}"] = v
                for k, v in (block.get("statuses") or {}).items():
                    v = _num(v)
                    if v is not None:
                        out[f"serve.{mode}.tenant.{tname}.status.{k}"] = v
        elif ev == "goodput":
            # Autosize sweep output (ISSUE 16): candidates flatten
            # under their candidate spelling, the frontier summary
            # under bare autosize.* — where the CI autosize determinism
            # gate pins frontier_crc / recommendation_crc / evaluated
            # at exact equality.
            kind = rec.get("kind")
            if kind == "candidate":
                cand = rec.get("cand", "?")
                for k, v in rec.items():
                    v = _num(v)
                    if v is not None and k not in ("schema", "t"):
                        out[f"autosize.{cand}.{k}"] = v
            elif kind == "frontier":
                for k, v in rec.items():
                    v = _num(v)
                    if v is not None and k not in ("schema", "t"):
                        out[f"autosize.{k}"] = v
            else:  # kind == "run": a single measured run's goodput
                for k, v in rec.items():
                    v = _num(v)
                    if v is not None and k not in ("schema", "t"):
                        out[f"goodput.{k}"] = v
        elif ev == "chaos":
            # Chaos-search output (ISSUE 19): per-episode records
            # flatten under their episode ordinal, the run summary
            # under bare chaos.* — where the CI chaos gate pins
            # episodes / violations / episodes_crc at exact equality.
            kind = rec.get("kind")
            if kind == "episode":
                ep = rec.get("episode", "?")
                out[f"chaos.ep{ep}.violations"] = float(
                    len(rec.get("violations") or []))
                for k, v in rec.items():
                    v = _num(v)
                    if v is not None and k not in ("schema", "t",
                                                   "episode"):
                        out[f"chaos.ep{ep}.{k}"] = v
            elif kind == "summary":
                out["chaos.failed"] = float(len(rec.get("failed") or []))
                for k, v in rec.items():
                    v = _num(v)
                    if v is not None and k not in ("schema", "t"):
                        out[f"chaos.{k}"] = v
        elif ev == "train":
            v = _num(rec.get("loss"))
            if v is not None:
                out["train.last_loss"] = v
            v = _num(rec.get("step"))
            if v is not None:
                out["train.last_step"] = v
        elif ev == "epoch":
            v = _num(rec.get("seconds"))
            if v is not None:
                out["epoch.last_s"] = v
        elif ev == "eval":
            for k, v in rec.items():
                v = _num(v)
                if v is not None and k not in ("schema", "t"):
                    out[f"eval.{k}"] = v
        elif ev == "bench":
            name, v = rec.get("metric"), _num(rec.get("value"))
            if name and v is not None:
                out[str(name)] = v
                # Secondary numeric fields ride along, namespaced under
                # the headline metric (same convention as the driver-
                # capture branch below: e.g. decode_tokens_per_s
                # .plain_tokens_per_s).
                for k, sv in rec.items():
                    sv = _num(sv)
                    if sv is not None and k not in ("metric", "value",
                                                    "schema", "t"):
                        out[f"{name}.{k}"] = sv
        elif ev == "metrics":
            label = rec.get("mode", "train")
            for k, v in (rec.get("counters") or {}).items():
                v = _num(v)
                if v is not None:
                    out[f"metrics.{label}.{k}"] = v
            for k, g in (rec.get("gauges") or {}).items():
                v = _num((g or {}).get("value"))
                if v is not None:
                    out[f"metrics.{label}.{k}"] = v
    return out


def extract_metrics(path: str | Path) -> dict[str, float]:
    """Metrics from a file of either shape (driver JSON / run JSONL).

    A driver capture (BENCH_r*.json) is ONE json object spanning
    multiple lines — detected by parsing the whole file first. A run
    JSONL yields its LAST non-empty run (append-mode files accumulate;
    the newest run is the one being compared).
    """
    text = Path(path).read_text()
    try:
        obj = json.loads(text)
    except json.JSONDecodeError:
        obj = None
    if isinstance(obj, dict):
        # Driver capture: {"parsed": {"metric", "value", ...}} — or a
        # bare {metric, value} object (bench.py's stdout line).
        parsed = obj.get("parsed") if isinstance(obj.get("parsed"), dict) \
            else obj
        out = {}
        name, v = parsed.get("metric"), _num(parsed.get("value"))
        if name and v is not None:
            out[str(name)] = v
            # Secondary numeric fields ride along, namespaced under the
            # headline metric (e.g. mnist_epoch_wallclock.vs_baseline).
            for k, sv in parsed.items():
                sv = _num(sv)
                if sv is not None and k not in ("metric", "value", "schema",
                                                "t", "n", "rc"):
                    out[f"{name}.{k}"] = sv
        return out
    runs = [r for r in iter_runs(path) if r]
    return metrics_from_records(runs[-1]) if runs else {}


def load_thresholds(path: str | Path) -> dict:
    spec = json.loads(Path(path).read_text())
    if not isinstance(spec.get("metrics"), dict) or not spec["metrics"]:
        raise ValueError(
            f"{path}: thresholds file needs a non-empty 'metrics' object"
        )
    return spec


def compare(base: dict[str, float], cand: dict[str, float],
            thresholds: dict | None = None) -> tuple[list[dict], list[str]]:
    """Evaluate candidate vs baseline; returns (rows, regressed names).

    With thresholds: exactly the listed metrics are gated (missing on
    either side = regression). Without: common metrics with inferable
    direction gate at DEFAULT_TOL_PCT; the rest are informational.
    """
    rows: list[dict] = []
    regressed: list[str] = []
    if thresholds is not None:
        default_tol = float(thresholds.get("default_tol_pct",
                                           DEFAULT_TOL_PCT))
        gated = thresholds["metrics"]
        names = sorted(set(gated) | (set(base) & set(cand)))
    else:
        default_tol = DEFAULT_TOL_PCT
        gated = None
        names = sorted(set(base) & set(cand))
    for name in names:
        spec = (gated or {}).get(name)
        a, b = base.get(name), cand.get(name)
        direction = (spec or {}).get("direction") or infer_direction(name)
        tol = float((spec or {}).get("tol_pct", default_tol))
        is_gated = spec is not None if gated is not None \
            else direction is not None
        row = {"metric": name, "base": a, "cand": b,
               "direction": direction, "tol_pct": tol if is_gated else None}
        if a is None or b is None:
            # A vanished metric needs no direction to fail the gate.
            if is_gated:
                row["verdict"] = "MISSING"
                regressed.append(name)
            else:
                row["verdict"] = "info"
            rows.append(row)
            continue
        if spec is not None and direction is None:
            # An explicitly gated, present metric that can't be
            # evaluated is a broken gate, not an info row — demoting it
            # silently is exactly the gate rot this module exists to
            # prevent.
            raise ValueError(
                f"gate metric {name!r}: direction neither specified nor "
                'inferable from the name — add "direction": "higher", '
                '"lower", or "equal" to its thresholds entry'
            )
        delta_pct = (b - a) / abs(a) * 100.0 if a else \
            (0.0 if b == a else float("inf") * (1 if b > a else -1))
        row["delta_pct"] = round(delta_pct, 3) if delta_pct == delta_pct \
            and abs(delta_pct) != float("inf") else delta_pct
        if not is_gated or direction is None:
            row["verdict"] = "info"
        else:
            # "equal" is the determinism direction (ISSUE 7): ANY drift
            # past tolerance regresses, both ways — two identical-seed
            # fleet runs must match their structural counts exactly, and
            # a one-sided gate would wave through half of all drifts
            # (a trace-crc change moves in a random direction).
            if direction == "equal":
                worse = abs(delta_pct) > tol
            else:
                worse = delta_pct < -tol if direction == "higher" \
                    else delta_pct > tol
            row["verdict"] = "REGRESS" if worse else "ok"
            if worse:
                regressed.append(name)
        rows.append(row)
    return rows, regressed


def render_table(rows: list[dict], base_label: str, cand_label: str) -> str:
    lines = [
        f"| metric | {base_label} | {cand_label} | Δ% | dir | tol% "
        "| verdict |",
        "|---|---|---|---|---|---|---|",
    ]
    for r in rows:
        lines.append(
            f"| {r['metric']} | {_fmt(r['base'])} | {_fmt(r['cand'])} "
            f"| {_fmt(r.get('delta_pct'))} | {_fmt(r['direction'])} "
            f"| {_fmt(r['tol_pct'])} | {r['verdict']} |"
        )
    return "\n".join(lines)


def best_of(metric_sets: list[dict[str, float]]) -> dict[str, float]:
    """Directional best per metric across files — the trajectory
    baseline (unknown-direction metrics take the LAST occurrence)."""
    out: dict[str, float] = {}
    for ms in metric_sets:
        for name, v in ms.items():
            if name not in out:
                out[name] = v
                continue
            d = infer_direction(name)
            if d == "higher":
                out[name] = max(out[name], v)
            elif d == "lower":
                out[name] = min(out[name], v)
            else:
                out[name] = v
    return out


def _has_tick_trail(path: str | Path) -> bool:
    """Whether a run file carries per-tick records (cheap textual scan
    with early exit — the hint below must not re-parse a storm file)."""
    try:
        with Path(path).open() as fh:
            for line in fh:
                if '"event": "tick"' in line or '"event": "fleet"' in line:
                    return True
    except OSError:
        return False
    return False


def _print_diverge_hint(paths: list[str], rows: list[dict],
                        regressed: list[str]) -> None:
    """Determinism-failure next step (ISSUE 15): when a gated *_crc /
    equal-direction metric regressed between exactly two runs that both
    carry tick trails, name the exact `mctpu diverge` invocation that
    localizes the first divergent tick."""
    if len(paths) != 2:
        return
    bad = {r["metric"] for r in rows if r.get("verdict") == "REGRESS"
           and (r.get("direction") == "equal"
                or r["metric"].endswith("_crc"))}
    if not (bad & set(regressed)):
        return
    if _has_tick_trail(paths[0]) and _has_tick_trail(paths[1]):
        print(f"hint: determinism metric(s) drifted "
              f"({', '.join(sorted(bad & set(regressed)))}) and both "
              "runs carry tick trails — localize the first divergent "
              f"tick with:\n  mctpu diverge {paths[0]} {paths[1]}",
              file=sys.stderr)
    else:
        print("hint: determinism metric(s) drifted "
              f"({', '.join(sorted(bad & set(regressed)))}) — re-run "
              "both storms with --log full and localize the first "
              "divergent tick with `mctpu diverge A B`", file=sys.stderr)


def compare_main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="mctpu compare",
        description="Compare run files (metrics JSONL or BENCH_r*.json "
                    "driver captures) on named metrics; exit 1 on "
                    "regression past per-metric tolerance.",
    )
    ap.add_argument("paths", nargs="+",
                    help="2 files: baseline candidate; 3+: trajectory "
                         "(last = candidate, best-of-earlier = baseline)")
    ap.add_argument("--gate", default=None,
                    help="thresholds JSON: gate exactly these metrics "
                         "with per-metric tol_pct/direction")
    ap.add_argument("--format", choices=("md", "json"), default="md")
    args = ap.parse_args(argv)
    if len(args.paths) < 2:
        print("error: need at least two files to compare", file=sys.stderr)
        return 2
    try:
        sets = [extract_metrics(p) for p in args.paths]
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    thresholds = None
    if args.gate:
        try:
            thresholds = load_thresholds(args.gate)
        except (OSError, ValueError) as e:
            print(f"error: {args.gate}: {e}", file=sys.stderr)
            return 2
    if len(sets) == 2:
        base, base_label = sets[0], args.paths[0]
    else:
        base = best_of(sets[:-1])
        base_label = f"best of {len(sets) - 1} earlier"
    cand, cand_label = sets[-1], args.paths[-1]
    try:
        rows, regressed = compare(base, cand, thresholds)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.format == "json":
        print(json.dumps({"base": base_label, "cand": cand_label,
                          "regressed": regressed, "rows": rows}))
    else:
        print(render_table(rows, base_label, cand_label))
        print()
    if regressed:
        print(f"REGRESSION: {len(regressed)} metric(s) worse than "
              f"tolerance: {', '.join(regressed)}", file=sys.stderr)
        _print_diverge_hint(args.paths, rows, regressed)
        return 1
    n_ok = sum(1 for r in rows if r["verdict"] == "ok")
    if n_ok == 0:
        # Nothing was actually gated (e.g. two files sharing no metric
        # with an inferable direction): exiting 0 would let a gate run
        # vacuously green forever.
        print("error: no metric was gated — nothing was compared",
              file=sys.stderr)
        return 2
    print(f"ok: {n_ok} gated metric(s) within tolerance", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(compare_main())
