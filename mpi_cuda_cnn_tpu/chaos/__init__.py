"""mctpu chaos: seeded fault-schedule search over the fleet storm.

Three jax-free pieces (ISSUE 19): a registry-driven plan sampler
(`sampler`), a deterministic episode harness with a global invariant
oracle (`episode`), and a ddmin plan minimizer (`shrink`). The
`mctpu chaos` CLI (`cli.chaos_main`) drives all three.
"""

from .episode import EpisodeConfig, EpisodeResult, config_for, run_episode
from .sampler import (
    RAISING_KINDS,
    SURFACE,
    EpisodeAxes,
    sample_axes,
    sample_plan,
)
from .shrink import shrink

__all__ = [
    "RAISING_KINDS",
    "SURFACE",
    "EpisodeAxes",
    "EpisodeConfig",
    "EpisodeResult",
    "config_for",
    "run_episode",
    "sample_axes",
    "sample_plan",
    "shrink",
]
